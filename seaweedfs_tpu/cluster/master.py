"""Master server: control plane over gRPC + HTTP.

Mirrors weed/server/master_server.go + master_grpc_server.go (SURVEY.md §2
"weed master", §3.4): volume servers stream heartbeats in and get
leader/size-limit back; clients assign file ids (``/dir/assign``, gRPC
``Assign``) and look volumes up (``/dir/lookup``, ``LookupVolume``,
``LookupEcVolume``). When an assign finds no writable volume the master
grows one — picks replica targets off the topology and calls
``AllocateVolume`` on each (volume_growth.go's
``GrowByCountAndType``). A single process is always leader: the
reference's Raft election exists to pick one master among many; the build
runs one master per cluster and reports itself leader (raft_server.go's
observable behavior, minus the consensus protocol).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
from concurrent import futures
from pathlib import Path
from http.server import BaseHTTPRequestHandler
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .. import pb
from ..pb import master_pb2, volume_server_pb2
from ..storage.superblock import ReplicaPlacement, Ttl
from ..storage.types import FileId
from ..util import config as config_mod
from ..util import faults as faults_mod
from ..util import glog
from ..util import httpserver
from ..util import profiler
from ..util import retry
from ..util import security
from ..util import tls as tls_mod
from ..util import tracing
from ..util import varz
from ..util.stats import EXPOSITION_CONTENT_TYPE, Metrics
from ..cache import invalidation as invalidation_mod
from . import ha as ha_mod
from .ha import NotLeaderError
from . import jobs as jobs_mod
from . import usage as usage_mod
from .sequence import MemorySequencer
from .telemetry import SloEngine
from .topology import Topology, TopologyError, VolumeInfo


def _grpc_port(http_port: int) -> int:
    """The reference convention: gRPC port = HTTP port + 10000."""
    return http_port + 10000


class MasterServer:
    def __init__(self, ip: str = "127.0.0.1", port: int = 9333,
                 volume_size_limit_mb: int = 30 * 1024,
                 default_replication: str = "000",
                 pulse_seconds: float = 5.0,
                 sequencer: Optional[MemorySequencer] = None,
                 secret: str = "", seed: Optional[int] = None,
                 garbage_threshold: float = 0.3,
                 garbage_scan_seconds: float = 60.0,
                 peers: Optional[list[str]] = None,
                 meta_dir: Optional[str] = None,
                 election_timeout: tuple[float, float] = (0.45, 0.9),
                 metrics_address: str = "",
                 metrics_interval_seconds: float = 15.0,
                 trace_ring_size: int = 256,
                 clock=time.time):
        self.ip = ip
        self.port = port
        self.url = f"{ip}:{port}"
        #: Injectable time source threaded through every registry so
        #: the sim harness can drive the whole control plane on a
        #: virtual clock (seaweedfs_tpu/sim); production uses time.time.
        self.clock = clock
        self.topology = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            pulse_seconds=pulse_seconds, seed=seed, clock=clock)
        if sequencer is None and meta_dir:
            Path(meta_dir).mkdir(parents=True, exist_ok=True)
            sequencer = MemorySequencer(
                persist_path=Path(meta_dir) / "sequence")
        self.sequencer = sequencer or MemorySequencer()
        # Raft-lite leader election among ``peers`` (HTTP urls incl. or
        # excl. self — self is filtered). No peers = standing leader.
        self.ha = ha_mod.RaftNode(
            self.url, list(peers or []),
            state_path=(Path(meta_dir) / "master.raft.json")
            if meta_dir else None,
            snapshot_state=self._ha_snapshot,
            apply_state=self._ha_apply,
            election_timeout=election_timeout)
        self.default_replication = default_replication
        #: Vacuum trigger: deleted/content ratio above which the reap
        #: loop drives Compact+Commit on the owning server
        #: (topology_vacuum.go; 0 disables the scan).
        self.garbage_threshold = garbage_threshold
        self.garbage_scan_seconds = garbage_scan_seconds
        self.guard = security.Guard(secret)
        self.metrics = Metrics(namespace="master")
        #: Exclusive admin lease for the shell (reference: the master's
        #: LeaseAdminToken behind shell `lock`/`unlock`): one named
        #: client at a time may run destructive choreography; the lease
        #: expires unless renewed so a crashed shell never wedges the
        #: cluster.
        self.admin_lease_seconds = 30.0
        self._admin_mu = threading.Lock()
        self._admin_holder = ""
        self._admin_expires = 0.0
        #: Prometheus push-gateway address, distributed to volume
        #: servers via heartbeat responses (the reference's
        #: -metrics.address flow).
        self.metrics_address = metrics_address
        self.metrics_interval_seconds = metrics_interval_seconds
        #: Cluster-wide stores for the observability plane: stitched
        #: tail-sampled traces (servers POST /cluster/traces) and the
        #: SLO burn-rate engine over the telemetry registry. Both live
        #: on every master but only the leader's fill up — volume
        #: servers heartbeat (and push traces to) the leader, so the
        #: /cluster/* read paths leader-proxy like /cluster/telemetry.
        self.trace_collector = tracing.TraceCollector(
            ring_size=trace_ring_size)
        self.slo = SloEngine(self.topology.telemetry, clock=clock)
        #: Traffic accounting registry: volume servers ride the
        #: heartbeat (Heartbeat.usage); gateways/filer POST the same
        #: payload to /cluster/usage. Leader-only for the same reason
        #: as traces/telemetry.
        self.usage = usage_mod.ClusterUsage(clock=clock)
        #: Maintenance plane (docs/jobs.md): durable per-volume task
        #: queues pulled by volume servers under leases renewed on the
        #: heartbeat, plus the policy engine that turns telemetry/usage
        #: signals into submitted jobs. Leader-only like the other
        #: /cluster/* planes; the checkpoint keeps sweeps resumable
        #: across master restarts.
        self.jobs = jobs_mod.JobManager(
            topology=self.topology,
            checkpoint_path=(Path(meta_dir) / "jobs.json")
            if meta_dir else None,
            clock=clock,
            on_commit=self._job_task_committed)
        self.policy = jobs_mod.PolicyEngine(master=self, jobs=self.jobs,
                                            clock=clock)
        #: Cluster cache-invalidation fan-out: gateways subscribe via
        #: POST /cluster/cache_subscribe; job commits that mutate a
        #: volume's bytes publish to subscribers + all volume servers.
        self.cache_hub = invalidation_mod.ClusterInvalidationHub()
        self._pusher = None
        self._channels: dict[str, object] = {}
        # dial cache is hit from the reap/vacuum/ttl loops, job
        # workers AND ingress handlers; unlocked check-then-set would
        # leak a duplicate (never-closed) channel per lost race
        self._chan_lock = threading.Lock()
        self._grpc_server = None
        self._http_server: Optional[httpserver.IngressHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._reaper: Optional[threading.Thread] = None
        self._vacuum_thread: Optional[threading.Thread] = None
        self._ttl_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._grow_lock = threading.Lock()

    # ------------- HA plumbing -------------

    def _ha_snapshot(self) -> dict:
        return {"max_volume_id": self.topology.max_volume_id,
                "sequence_next": self.sequencer.peek()}

    def _ha_apply(self, state: dict) -> None:
        self.topology.observe_max_volume_id(
            int(state.get("max_volume_id", 0)))
        seq = int(state.get("sequence_next", 0))
        if seq > 1:
            self.sequencer.set_max(seq - 1)

    @property
    def is_leader(self) -> bool:
        return self.ha.is_leader

    @property
    def leader_url(self) -> str:
        return self.ha.leader or (self.url if self.is_leader else "")

    def _require_leader(self) -> None:
        if not self.is_leader:
            raise NotLeaderError(self.leader_url)

    # ------------- lifecycle -------------

    def start(self) -> "MasterServer":
        import grpc

        self._grpc_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16))
        self._grpc_server.add_generic_rpc_handlers((pb.generic_handler(
            pb.MASTER_SERVICE, pb.MASTER_METHODS, _MasterServicer(self)),))
        bound = tls_mod.serve_port(
            self._grpc_server, f"{self.ip}:{_grpc_port(self.port)}")
        if bound == 0:
            raise RuntimeError(
                f"cannot bind master grpc port {_grpc_port(self.port)}")
        self._grpc_server.start()

        handler = _make_http_handler(self)
        self._http_server = httpserver.IngressHTTPServer(
            (self.ip, self.port), handler, component="master")
        self._http_thread = threading.Thread(
            target=self._http_server.serve_forever, daemon=True,
            name=f"master-http-{self.port}")
        self._http_thread.start()

        self._reaper = threading.Thread(target=self._reap_loop, daemon=True,
                                        name=f"master-reaper-{self.port}")
        self._reaper.start()
        self.ha.start()
        self.slo.start()
        # The master's own slow/errored roots go straight into the
        # in-process collector — no HTTP round trip to self.
        tracing.configure_push(self.trace_collector.ingest,
                               node=self.url, component="master")
        if self.metrics_address:
            from ..util.stats import MetricsPusher
            self._pusher = MetricsPusher(
                self.metrics, self.metrics_address, "master", self.url,
                self.metrics_interval_seconds).start()
        glog.info("master started at %s (grpc %d)", self.url,
                  _grpc_port(self.port))
        return self

    def stop(self) -> None:
        self._stop.set()
        self.ha.stop()
        self.slo.stop()
        if self._pusher is not None:
            self._pusher.stop()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.5)
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        for ch in self._channels.values():
            ch.close()
        self._channels.clear()

    def __enter__(self) -> "MasterServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _reap_loop(self) -> None:
        vacuum_every = max(1, int(self.garbage_scan_seconds /
                                  max(self.topology.pulse_seconds, 0.01)))
        # TTL expiry has minute granularity — a full-topology scan per
        # pulse would be pure churn; once a minute matches the vacuum
        # scan's throttling approach.
        ttl_every = max(1, int(60.0 /
                               max(self.topology.pulse_seconds, 0.01)))
        tick = 0
        while not self._stop.wait(self.topology.pulse_seconds):
            dead = self.topology.reap_dead_nodes()
            for url in dead:
                glog.warning("master: data node %s missed heartbeats, "
                             "removed from topology", url)
                self.usage.forget(url)
                # Reaped workers hand their leased tasks back now
                # rather than sitting out the rest of the lease.
                self.jobs.forget_worker(url)
                self.cache_hub.forget(url)
            self.jobs.expire()
            if self.is_leader:
                self.policy.maybe_tick()
            if self.is_leader and tick % ttl_every == 0 \
                    and (self._ttl_thread is None or
                         not self._ttl_thread.is_alive()):
                # Off the reap thread: a hung VolumeDelete must not
                # stall dead-node detection (same rationale as the
                # vacuum scan below).
                # check-then-spawn runs only on the single reap loop
                # seaweedlint: disable=SW802 — single reap-loop caller
                self._ttl_thread = threading.Thread(
                    target=self._reap_ttl_safe, daemon=True,
                    name="master-ttl-reap")
                self._ttl_thread.start()
            tick += 1
            if self.garbage_threshold > 0 and self.is_leader \
                    and tick % vacuum_every == 0 \
                    and (self._vacuum_thread is None
                         or not self._vacuum_thread.is_alive()):
                # Off the reap thread: a long compaction must not stall
                # dead-node detection.
                # check-then-spawn runs only on the single reap loop
                # seaweedlint: disable=SW802 — single reap-loop caller
                self._vacuum_thread = threading.Thread(
                    target=self._scan_and_vacuum_safe, daemon=True,
                    name="master-vacuum-scan")
                self._vacuum_thread.start()

    # ------------- maintenance jobs -------------

    def _job_task_committed(self, task) -> None:
        """JobManager on_commit hook: a task that changed what a
        volume's bytes mean (EC seal/rebuild, vacuum, replica drop)
        fans a cache-invalidation event out to every subscribed
        gateway plus every other volume server, so remote chunk caches
        never serve the pre-maintenance bytes."""
        if task.kind not in jobs_mod.MUTATING_KINDS:
            return
        extra = [n.url for n in self.topology.snapshot_nodes()
                 if n.url != task.worker]
        self.cache_hub.publish(task.volume_id, reason=task.kind,
                               origin=task.worker, extra=extra)

    def job_candidate_volumes(self, kind: str,
                              collection: str = "") -> list[int]:
        """Enumerate the work-list for a whole-collection submission
        (``job.submit ec.encode -collection X`` names no volumes):
        ec_encode targets plain volumes not yet EC'd, ec_rebuild
        targets EC volumes, scrub both forms (integrity is universal),
        the rest every plain volume."""
        plain: set[int] = set()
        for node in self.topology.snapshot_nodes():
            for (col, vid) in node.volumes:
                if col == collection:
                    plain.add(vid)
        ec = {vid for vid, col in self.topology.ec_collections.items()
              if col == collection}
        if kind == "ec_rebuild":
            return sorted(ec)
        if kind == "scrub":
            return sorted(plain | ec)
        if kind == "ec_encode":
            plain -= set(self.topology.ec_locations)
        return sorted(plain)

    def _reap_ttl_safe(self) -> None:
        try:
            self.reap_expired_ttl_volumes()
        except Exception as e:  # noqa: BLE001 — keep the scan cadence
            glog.warning("master: ttl reap failed: %s", e)

    def reap_expired_ttl_volumes(self) -> int:
        """Topology TTL maintenance (weed/topology/ TTL reaping role):
        a TTL volume whose last write is older than its TTL is deleted
        from every replica server — the needles inside are all expired
        by definition, so the whole volume goes at once (that is the
        point of per-TTL volumes). Returns volumes reaped.

        The deadline carries a grace margin beyond the TTL: the mtime
        seen here is from the last heartbeat (stale by up to a pulse),
        so reaping exactly at TTL could destroy a just-acknowledged
        write the next heartbeat would have reported."""
        now = time.time()
        grace = max(10 * self.topology.pulse_seconds, 30.0)
        reaped = 0
        for node in self.topology.snapshot_nodes():
            for v in list(node.volumes.values()):
                if not v.ttl:
                    continue
                ttl_s = Ttl.parse(v.ttl).seconds
                if not ttl_s or not v.modified_at_second:
                    continue
                if now - v.modified_at_second <= ttl_s + grace:
                    continue
                glog.info("master: volume %d on %s expired "
                          "(ttl %s, idle %.0fs); deleting", v.id,
                          node.url, v.ttl, now - v.modified_at_second)
                try:
                    self._volume_stub(node.url).VolumeDelete(
                        volume_server_pb2.VolumeDeleteRequest(
                            volume_id=v.id, collection=v.collection),
                        timeout=30)
                    self.topology.unregister_volume(node.url, v.id,
                                                    v.collection)
                    reaped += 1
                except Exception as e:  # noqa: BLE001 — next scan retries
                    glog.warning("master: ttl delete of volume %d on "
                                 "%s failed: %s", v.id, node.url, e)
        return reaped

    def _scan_and_vacuum_safe(self) -> None:
        try:
            self.scan_and_vacuum()
        except Exception as e:  # noqa: BLE001 — keep the scan cadence up
            glog.warning("master: vacuum scan failed: %s", e)

    def scan_and_vacuum(self, threshold: Optional[float] = None) -> int:
        """topology_vacuum.go analog: walk every volume, and when a
        node-reported garbage ratio exceeds the threshold, drive the
        Check → Compact → Commit rpc sequence on its server. Returns the
        number of volumes vacuumed."""
        threshold = self.garbage_threshold if threshold is None \
            else threshold
        done = 0
        for node in self.topology.snapshot_nodes():
            for v in list(node.volumes.values()):
                if v.size <= 8 or v.read_only:
                    continue
                if v.deleted_byte_count / max(1, v.size - 8) <= threshold:
                    continue
                # Per-volume isolation: one failing volume/server must
                # not starve the rest of the scan.
                try:
                    done += self._vacuum_one(node.url, v, threshold)
                except Exception as e:  # noqa: BLE001
                    glog.warning(
                        "master: vacuum of volume %d on %s failed: %s",
                        v.id, node.url, e)
        return done

    def _vacuum_one(self, node_url: str, v, threshold: float) -> int:
        stub = self._volume_stub(node_url)
        check = stub.VacuumVolumeCheck(
            volume_server_pb2.VacuumVolumeCheckRequest(
                volume_id=v.id, collection=v.collection))
        if check.garbage_ratio <= threshold:
            return 0
        glog.info("master: vacuuming volume %d on %s (garbage %.0f%%)",
                  v.id, node_url, check.garbage_ratio * 100)
        try:
            stub.VacuumVolumeCompact(
                volume_server_pb2.VacuumVolumeCompactRequest(
                    volume_id=v.id, collection=v.collection))
            stub.VacuumVolumeCommit(
                volume_server_pb2.VacuumVolumeCommitRequest(
                    volume_id=v.id, collection=v.collection))
            return 1
        except Exception:
            try:
                stub.VacuumVolumeCleanup(
                    volume_server_pb2.VacuumVolumeCleanupRequest(
                        volume_id=v.id, collection=v.collection))
            except Exception as ce:  # noqa: BLE001 — keep original error
                glog.warning("master: vacuum cleanup of volume %d on %s "
                             "also failed: %s", v.id, node_url, ce)
            raise

    # ------------- volume-server dialing -------------

    def _volume_stub(self, node_url: str) -> pb.Stub:
        import grpc

        with self._chan_lock:
            ch = self._channels.get(node_url)
            if ch is None:
                ip, http_port = node_url.rsplit(":", 1)
                ch = security.grpc_auth_channel(
                    tls_mod.dial(
                        f"{ip}:{_grpc_port(int(http_port))}"), self.guard)
                self._channels[node_url] = ch
        return pb.volume_stub(ch)

    # ------------- core ops -------------

    # ---- admin lock (shell lock/unlock) ----

    def admin_acquire(self, client: str) -> dict:
        """Acquire (or renew) the exclusive shell lease. Raises
        PermissionError naming the holder when another live lease
        exists.

        Like the reference's master lease, this lives in the LEADER's
        memory: an HA failover forgets it, so a lock can briefly be
        granted twice across a leader change (the displaced holder's
        renewer detects the conflict within a third of the lease and
        its shell then refuses further destructive commands)."""
        if not client:
            raise ValueError("admin lock needs a client name")
        with self._admin_mu:
            now = time.time()
            if (self._admin_holder
                    and self._admin_holder != client
                    and self._admin_expires > now):
                raise PermissionError(
                    f"cluster is locked by {self._admin_holder}")
            self._admin_holder = client
            self._admin_expires = now + self.admin_lease_seconds
            return {"holder": client,
                    "leaseSeconds": self.admin_lease_seconds}

    def admin_release(self, client: str) -> dict:
        with self._admin_mu:
            if self._admin_holder and self._admin_holder != client \
                    and self._admin_expires > time.time():
                raise PermissionError(
                    f"cluster is locked by {self._admin_holder}, "
                    f"not {client}")
            self._admin_holder = ""
            self._admin_expires = 0.0
            return {"released": True}

    def grow_volume(self, collection: str = "",
                    replication: Optional[str] = None,
                    ttl: str = "") -> int:
        """Allocate one new volume on replica-placement-chosen nodes."""
        self._require_leader()
        replication = replication or self.default_replication
        # Growth is deliberately serialized END TO END under this lock:
        # the raft id-replication and the AllocateVolume rpcs must
        # complete before a second grow may observe topology, or two
        # volumes could land on one id.
        # seaweedlint: disable=SW103 — intentional rpc under grow lock
        with self._grow_lock:
            targets = self.topology.pick_grow_targets(replication)
            vid = self.topology.next_volume_id()
            # Persist + replicate the consumed id BEFORE the volume goes
            # live: a leader crash right after allocation must not let
            # its successor reissue the same id (raft MaxVolumeId role).
            self.ha.replicate_now()
            for node in targets:
                self._volume_stub(node.url).AllocateVolume(
                    volume_server_pb2.AllocateVolumeRequest(
                        volume_id=vid, collection=collection,
                        replication=replication, ttl=ttl))
                # Optimistic registration so the volume is writable now;
                # the next heartbeat snapshot confirms it.
                self.topology.register_volume(node.url, VolumeInfo(
                    id=vid, collection=collection,
                    replica_placement=replication, ttl=ttl))
            glog.info("master: grew volume %d on %s", vid,
                      [n.url for n in targets])
            return vid

    def assign(self, count: int = 1, collection: str = "",
               replication: Optional[str] = None, ttl: str = "") -> dict:
        self._require_leader()
        replication = replication or self.default_replication
        self.metrics.counter("assign_requests").inc()
        for _attempt in (0, 1):
            try:
                vid, nodes = self.topology.pick_for_write(
                    collection, replication, ttl)
                break
            except TopologyError:
                if _attempt:
                    raise
                self.grow_volume(collection, replication, ttl)
        key = self.sequencer.next_batch(max(1, count))
        fid = str(FileId(volume_id=vid, key=key,
                         cookie=security.new_cookie()))
        node = nodes[0]
        return {"fid": fid, "url": node.url,
                "publicUrl": node.public_url or node.url,
                "count": max(1, count),
                "auth": self.guard.sign(fid)}

    def lookup(self, volume_id: int, collection: str = "") -> list[dict]:
        nodes = self.topology.lookup_volume(volume_id, collection)
        if not nodes:
            # EC volumes answer lookups too (any node with a shard);
            # keep the shard list per node so clients and traffic.top
            # can attribute EC reads.
            by_shard = self.topology.lookup_ec_volume(volume_id)
            seen: dict[str, dict] = {}
            shards: dict[str, list[int]] = {}
            for sid, node_list in sorted(by_shard.items()):
                for n in node_list:
                    seen[n.url] = n
                    shards.setdefault(n.url, []).append(sid)
            # EC holders are ranked but never excluded: every node
            # may hold shards that exist nowhere else, and a decode
            # needs k distinct shards more than it needs fast ones
            out = [{"url": n.url,
                    "publicUrl": n.public_url or n.url,
                    "shards": shards[n.url]}
                   for n in self._rank_replicas(
                       list(seen.values()), volume_id,
                       exclude_unhealthy=False)]
            return out
        return [{"url": n.url, "publicUrl": n.public_url or n.url}
                for n in self._rank_replicas(nodes, volume_id)]

    def _rank_replicas(self, nodes: list, volume_id: int,
                       exclude_unhealthy: bool = True) -> list:
        """Telemetry-ranked read routing: healthy nodes first (then
        degraded, unhealthy last), and within a tier by health score
        plus a chunk-cache-warmth bonus for this volume — so clients
        that try locations in order hit the warm healthy replica and
        only fall through to a faulted node at the tail. With no
        telemetry ingested every node scores 100/healthy and the
        topology's deterministic order is preserved (the sort is
        stable).

        Unhealthy-verdict nodes are *excluded* (not just demoted)
        whenever at least one healthy/degraded replica exists —
        handing a client a location the telemetry plane already
        condemned only buys it a timeout before it falls through to
        the next one anyway. The floor: a fully-degraded volume still
        returns every location, because a slow answer beats none."""
        if len(nodes) < 2:
            return nodes
        tele = self.topology.telemetry
        pulse = self.topology.pulse_seconds
        tiers = {"healthy": 0, "degraded": 1, "unhealthy": 2}
        ranked = []
        for i, n in enumerate(nodes):
            h = tele.health(n.url, n.last_seen, pulse)
            warmth = tele.volume_row(n.url, volume_id).get(
                "cache_hit_ratio", 0.0)
            key = (tiers.get(h["verdict"], 2),
                   -(h["score"] + 25.0 * warmth), i)
            ranked.append((key, n))
        ranked.sort(key=lambda kn: kn[0])
        alive = sum(1 for key, _n in ranked if key[0] < 2)
        if exclude_unhealthy and 0 < alive < len(ranked):
            self.metrics.counter(
                "lookup_unhealthy_excluded_total").inc(
                    len(ranked) - alive)
            ranked = ranked[:alive]  # sort left unhealthy at the tail
        return [n for _key, n in ranked]

    # ------------- heartbeat ingestion -------------

    def ingest_heartbeat(self, hb) -> master_pb2.HeartbeatResponse:
        """One heartbeat through the full ingestion path — shared by
        the gRPC stream servicer and the sim harness (which drives a
        real master in-process, no sockets).

        The steady-state fast path: a pulse whose snapshot changes
        nothing in the topology allocates no span and formats no log
        line — at thousands of nodes the per-pulse cost must stay flat
        (the sim's span-count test pins this down), and unchanged
        pulses are the overwhelmingly common case.
        """
        url = f"{hb.ip}:{hb.port}"
        volumes = [VolumeInfo(
            id=v.id, collection=v.collection, size=v.size,
            file_count=v.file_count, delete_count=v.delete_count,
            deleted_byte_count=v.deleted_byte_count,
            read_only=v.read_only,
            replica_placement=str(
                ReplicaPlacement.from_byte(v.replica_placement)),
            version=v.version or 3,
            ttl="" if not v.ttl else str(Ttl.from_bytes(
                v.ttl.to_bytes(2, "big"))),
            modified_at_second=v.modified_at_second,
        ) for v in hb.volumes]
        ec = [(s.collection, s.id, s.ec_index_bits)
              for s in hb.ec_shards]
        node = self.topology.register_heartbeat(
            url, public_url=hb.public_url,
            data_center=hb.data_center, rack=hb.rack,
            max_volume_count=hb.max_volume_count or 8,
            volumes=volumes, ec_shards=ec)
        if node.last_heartbeat_changed:
            with tracing.span("master.heartbeat.topology", node=url,
                              volumes=str(len(volumes))):
                glog.v(1, "master: heartbeat from %s changed topology "
                       "(%d volumes, %d ec entries)", url,
                       len(volumes), len(ec))
        if hb.HasField("telemetry"):
            self.topology.telemetry.ingest(url, hb.telemetry,
                                           metrics=self.metrics)
        if hb.HasField("usage"):
            self.usage.ingest_proto(url, hb.usage)
        if hb.HasField("job_progress"):
            # The heartbeat IS the lease renewal for every task
            # the worker still reports in flight.
            self.jobs.renew(url, hb.job_progress)
        if hb.max_file_key:
            self.sequencer.set_max(hb.max_file_key)
        return master_pb2.HeartbeatResponse(
            volume_size_limit=self.topology.volume_size_limit,
            leader=self.leader_url or self.url,
            metrics_address=self.metrics_address)


class _MasterServicer:
    """gRPC service impl bound via pb.generic_handler."""

    def __init__(self, ms: MasterServer):
        self.ms = ms

    def SendHeartbeat(self, request_iterator, context):
        for hb in request_iterator:
            yield self.ms.ingest_heartbeat(hb)

    def Assign(self, request, context):
        try:
            r = self.ms.assign(count=request.count or 1,
                               collection=request.collection,
                               replication=request.replication or None,
                               ttl=request.ttl)
        except (TopologyError, ValueError, NotLeaderError) as e:
            return master_pb2.AssignResponse(error=str(e))
        return master_pb2.AssignResponse(
            fid=r["fid"], url=r["url"], public_url=r["publicUrl"],
            count=r["count"], auth=r["auth"])

    def LookupVolume(self, request, context):
        resp = master_pb2.LookupVolumeResponse()
        # Volume servers heartbeat only the leader; a follower's cold
        # topology must not masquerade as "volume not found".
        not_leader = None if self.ms.is_leader else \
            NotLeaderError(self.ms.leader_url)
        for vid_str in request.volume_ids:
            entry = resp.volume_id_locations.add()
            entry.volume_id = vid_str
            if not_leader is not None:
                entry.error = str(not_leader)
                continue
            try:
                vid = int(vid_str.split(",")[0])
            except ValueError:
                entry.error = f"bad volume id {vid_str!r}"
                continue
            locs = self.ms.lookup(vid, request.collection)
            if not locs:
                entry.error = f"volume {vid} not found"
            for loc in locs:
                entry.locations.add(url=loc["url"],
                                    public_url=loc["publicUrl"],
                                    shards=loc.get("shards", ()))
        return resp

    def LookupEcVolume(self, request, context):
        # No per-entry error field here: raising surfaces as an RpcError
        # the client's failover loop rotates on.
        self.ms._require_leader()
        resp = master_pb2.LookupEcVolumeResponse(
            volume_id=request.volume_id)
        for sid, nodes in sorted(
                self.ms.topology.lookup_ec_volume(
                    request.volume_id).items()):
            entry = resp.shard_id_locations.add(shard_id=sid)
            for n in nodes:
                entry.locations.add(url=n.url,
                                    public_url=n.public_url or n.url)
        return resp

    def VolumeList(self, request, context):
        resp = master_pb2.VolumeListResponse(
            volume_size_limit_mb=self.ms.topology.volume_size_limit
            // (1024 * 1024))
        topo = resp.topology_info
        topo.id = "topo"
        by_dc: dict[str, dict[str, list]] = {}
        for n in self.ms.topology.snapshot_nodes():
            by_dc.setdefault(n.data_center, {}).setdefault(
                n.rack, []).append(n)
        for dc, racks in sorted(by_dc.items()):
            dci = topo.data_center_infos.add(id=dc)
            for rack, nodes in sorted(racks.items()):
                ri = dci.rack_infos.add(id=rack)
                for n in nodes:
                    dni = ri.data_node_infos.add(
                        id=n.url, volume_count=n.volume_count,
                        max_volume_count=n.max_volume_count,
                        free_volume_count=n.free_slots,
                        active_volume_count=n.volume_count)
                    for v in n.volumes.values():
                        dni.volume_infos.add(
                            id=v.id, size=v.size, collection=v.collection,
                            file_count=v.file_count,
                            delete_count=v.delete_count,
                            deleted_byte_count=v.deleted_byte_count,
                            read_only=v.read_only,
                            replica_placement=ReplicaPlacement.parse(
                                v.replica_placement).to_byte(),
                            version=v.version,
                            ttl=int.from_bytes(
                                Ttl.parse(v.ttl or "").to_bytes(), "big"),
                            modified_at_second=v.modified_at_second)
                    for (col, vid), bits in n.ec_shards.items():
                        dni.ec_shard_infos.add(
                            id=vid, collection=col, ec_index_bits=bits.bits)
        return resp

    def GetMasterConfiguration(self, request, context):
        return master_pb2.GetMasterConfigurationResponse(
            volume_size_limit=self.ms.topology.volume_size_limit,
            jwt_enabled=self.ms.guard.enabled,
            metrics_address=self.ms.metrics_address,
            metrics_interval_seconds=max(1, round(
                self.ms.metrics_interval_seconds))
            if self.ms.metrics_address else 0)


def _make_http_handler(ms: MasterServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through glog
            glog.v(2, "master http: " + fmt, *args)

        def _json(self, obj, code: int = 200) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _text(self, body: bytes, code: int = 200) -> None:
            self.send_response(code)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _proxy_to_leader(self) -> bool:
            """Forward this request to the current leader (follower
            masters stay useful to dumb HTTP clients), preserving the
            method and body. Returns True if proxied; False when we ARE
            the leader or none is known."""
            leader = ms.leader_url
            if ms.is_leader or not leader or leader == ms.url:
                return False
            try:
                data = None
                if self.command == "POST":
                    n = int(self.headers.get("Content-Length", 0) or 0)
                    data = self.rfile.read(n) if n else b""
                # No breaker: the "endpoint" is whoever holds the lease
                # right now, and a 503 here is already the retry signal.
                r = retry.http_request(
                    f"http://{leader}{self.path}", data=data,
                    method=self.command, point="master.proxy",
                    timeout=10, use_breaker=False)
                self.send_response(r.status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(r.data)))
                self.end_headers()
                self.wfile.write(r.data)
            except urllib.error.HTTPError as e:
                body = e.read()
                self.send_response(e.code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except Exception as e:  # noqa: BLE001
                self._json({"error": f"leader {leader} unreachable: {e}"},
                           503)
            return True

        def do_GET(self):
            u = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            try:
                if u.path == "/dir/assign":
                    if self._proxy_to_leader():
                        return
                    self._json(ms.assign(
                        count=int(q.get("count", 1)),
                        collection=q.get("collection", ""),
                        replication=q.get("replication") or None,
                        ttl=q.get("ttl", "")))
                elif u.path == "/dir/lookup":
                    # Volume servers heartbeat only the leader, so a
                    # follower's topology is cold — answer from the
                    # leader's; mid-election (no leader known) a 503
                    # retry signal, never a false 404.
                    if self._proxy_to_leader():
                        return
                    ms._require_leader()
                    vid = int(str(q.get("volumeId", "0")).split(",")[0])
                    locs = ms.lookup(vid, q.get("collection", ""))
                    if not locs:
                        self._json({"volumeId": str(vid),
                                    "error": "volume not found"}, 404)
                    else:
                        self._json({"volumeId": str(vid),
                                    "locations": locs})
                elif u.path in ("/cluster/status", "/dir/status"):
                    with ms._admin_mu:
                        lock_holder = (ms._admin_holder
                                       if ms._admin_expires > time.time()
                                       else "")
                    self._json({"IsLeader": ms.is_leader,
                                "Leader": ms.leader_url or ms.url,
                                "Peers": ms.ha.peers,
                                "Term": ms.ha.term,
                                "AdminLockHolder": lock_holder,
                                "Topology": ms.topology.to_map()})
                elif u.path == "/metrics":
                    body = (ms.metrics.render()
                            + ms.slo.metrics.render()
                            + ms.usage.metrics.render()
                            + ms.jobs.metrics.render()
                            + tracing.METRICS.render()
                            + retry.METRICS.render()
                            + httpserver.METRICS.render()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     EXPOSITION_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif u.path == "/cluster/telemetry":
                    # Volume servers heartbeat only the leader, so a
                    # follower's registry is cold — answer from the
                    # leader's.
                    if self._proxy_to_leader():
                        return
                    last_seen = {n.url: n.last_seen
                                 for n in ms.topology.snapshot_nodes()}
                    # Default cap keeps the per-volume section top-N by
                    # read rate; ?limit=0 restores the unbounded body.
                    self._json(ms.topology.telemetry.to_map(
                        nodes_last_seen=last_seen,
                        pulse_seconds=ms.topology.pulse_seconds,
                        limit=int(q.get("limit", 512)) or None))
                elif u.path == "/cluster/traces":
                    # Tail-sampled traces land on the leader (that is
                    # where servers push), so read from there.
                    if self._proxy_to_leader():
                        return
                    self._json(ms.trace_collector.payload(
                        int(q["limit"]) if q.get("limit") else None))
                elif u.path == "/cluster/usage":
                    # Usage lands on the leader (heartbeats + gateway
                    # pushes go there), so read from there.
                    if self._proxy_to_leader():
                        return
                    self._json(ms.usage.to_map(
                        limit=int(q.get("limit", 256)) or None))
                elif u.path == "/cluster/topk":
                    if self._proxy_to_leader():
                        return
                    self._json(ms.usage.topk_map(
                        int(q.get("n", 32))))
                elif u.path == "/cluster/jobs":
                    # Jobs live on the leader (claims/completions and
                    # heartbeat renewals land there), so read there.
                    if self._proxy_to_leader():
                        return
                    doc = ms.jobs.to_map(
                        with_tasks=q.get("tasks", "1") != "0",
                        limit=int(q.get("limit", 1000)) or None)
                    doc["policy"] = ms.policy.payload()
                    self._json(doc)
                elif u.path == "/cluster/scrub":
                    # Scrub-plane view: the scrub jobs (a filtered
                    # /cluster/jobs) plus the candidate volume count,
                    # so operators see coverage at a glance.
                    if self._proxy_to_leader():
                        return
                    doc = ms.jobs.to_map(
                        with_tasks=q.get("tasks", "1") != "0",
                        limit=int(q.get("limit", 1000)) or None)
                    scrub_jobs = [j for j in doc["jobs"]
                                  if j["kind"] == "scrub"]
                    self._json({
                        "enabled": doc["enabled"],
                        "jobs": scrub_jobs,
                        "candidates": len(ms.job_candidate_volumes(
                            "scrub", q.get("collection", "")))})
                elif u.path == "/cluster/slo":
                    if self._proxy_to_leader():
                        return
                    # Evaluate on demand: the tick is idempotent and
                    # this keeps curl output fresh even with a long
                    # background interval.
                    self._json(ms.slo.evaluate())
                elif u.path == "/cluster/profile":
                    # Master-side proxy to any node's /debug/profile so
                    # operators profile the fleet from one place.
                    node = q.get("node", "")
                    if not node:
                        self._json(
                            {"error": "node query parameter required"},
                            400)
                        return
                    seconds = min(float(q.get("seconds", 2.0)),
                                  profiler.MAX_SECONDS)
                    try:
                        r = retry.http_request(
                            f"http://{node}/debug/profile"
                            f"?seconds={seconds}",
                            point="master.profile_proxy",
                            timeout=seconds + 30.0, use_breaker=False)
                    except Exception as e:  # noqa: BLE001
                        self._json({"error":
                                    f"node {node} unreachable: {e}"},
                                   502)
                        return
                    self._text(r.data)
                elif u.path == "/debug/profile":
                    self._text(profiler.profile(
                        float(q.get("seconds", 2.0)),
                        hz=float(q.get("hz",
                                       profiler.DEFAULT_BURST_HZ))
                    ).encode())
                elif u.path == "/debug/traces":
                    self._json(tracing.debug_payload(
                        int(q.get("limit", -1))
                        if q.get("limit") else None))
                elif u.path == "/debug/vars":
                    self._json(varz.payload(
                        "master", ms.metrics,
                        extra={"is_leader": ms.is_leader,
                               "nodes": len(ms.topology.nodes),
                               "slo_state": ms.slo.worst_state(),
                               "slo_alerts": list(ms.slo.alerts),
                               "jobs": ms.jobs.summary(),
                               "cache_hub": ms.cache_hub.to_map(),
                               "trace_collector":
                                   ms.trace_collector.payload(0)}))
                else:
                    self._json({"error": "not found"}, 404)
            except NotLeaderError as e:
                self._json({"error": str(e), "leader": e.leader}, 503)
            except (TopologyError, ValueError) as e:
                self._json({"error": str(e)}, 500)

        def do_POST(self):
            u = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            if u.path in ("/raft/vote", "/raft/heartbeat"):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if u.path == "/raft/vote":
                        self._json(ms.ha.handle_vote(req))
                    else:
                        self._json(ms.ha.handle_heartbeat(req))
                except (ValueError, OSError) as e:
                    self._json({"error": str(e)}, 400)
            elif u.path in ("/admin/lock", "/admin/unlock"):
                if self._proxy_to_leader():
                    return
                try:
                    client = q.get("client", "")
                    if u.path == "/admin/lock":
                        self._json(ms.admin_acquire(client))
                    else:
                        self._json(ms.admin_release(client))
                except PermissionError as e:
                    self._json({"error": str(e)}, 409)
                except ValueError as e:
                    self._json({"error": str(e)}, 400)
            elif u.path == "/cluster/traces":
                # Tail-sample sink: servers push slow/errored root
                # bundles here (tracing._push_loop).
                if self._proxy_to_leader():
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0) or 0)
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    ms.trace_collector.ingest(payload)
                    self._json({"ok": True})
                except (ValueError, OSError) as e:
                    self._json({"error": str(e)}, 400)
            elif u.path == "/cluster/usage":
                # Accounting sink for ingresses that do not heartbeat
                # (S3/WebDAV/filer push their cumulative snapshots
                # here; usage.UsagePusher).
                if self._proxy_to_leader():
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0) or 0)
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    source = str(payload.get("source", "") or
                                 self.client_address[0])
                    ms.usage.ingest(source, payload)
                    self._json({"ok": True})
                except (ValueError, OSError) as e:
                    self._json({"error": str(e)}, 400)
            elif u.path.startswith("/cluster/jobs/"):
                # Maintenance-job control plane: all writes go to the
                # leader (whose JobManager owns the work-lists).
                if self._proxy_to_leader():
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0) or 0)
                    body = json.loads(self.rfile.read(n) or b"{}")
                    action = u.path[len("/cluster/jobs/"):]
                    if action == "submit":
                        kind = str(body.get("kind", ""))
                        vids = body.get("volumes") or []
                        if not vids:
                            vids = ms.job_candidate_volumes(
                                kind, str(body.get("collection", "")))
                        self._json({"job": ms.jobs.submit(
                            kind, vids,
                            collection=str(body.get("collection", "")),
                            params=body.get("params") or {},
                            parallel=int(body.get("parallel", 0)),
                            submitted_by=str(
                                body.get("submittedBy", "http")))})
                    elif action == "claim":
                        self._json({"task": ms.jobs.claim(
                            q.get("worker", ""))})
                    elif action == "complete":
                        self._json(ms.jobs.complete(
                            str(body.get("worker", "")),
                            str(body.get("taskId", "")),
                            bool(body.get("ok")),
                            str(body.get("error", ""))))
                    elif action in ("pause", "resume", "cancel"):
                        job_id = q.get("job", "") or str(
                            body.get("jobId", ""))
                        self._json({"job": getattr(ms.jobs, action)(
                            job_id)})
                    else:
                        self._json({"error": "not found"}, 404)
                except KeyError as e:
                    self._json({"error": str(e.args[0])}, 404)
                except (ValueError, OSError) as e:
                    self._json({"error": str(e)}, 400)
            elif u.path == "/cluster/scrub":
                # Convenience submit: a scrub job over the named
                # volumes (or every plain + EC volume of the
                # collection when none are named).
                if self._proxy_to_leader():
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0) or 0)
                    body = json.loads(self.rfile.read(n) or b"{}")
                    col = str(body.get("collection", ""))
                    vids = body.get("volumes") or \
                        ms.job_candidate_volumes("scrub", col)
                    params = dict(body.get("params") or {})
                    if body.get("rate_bytes_per_second") is not None:
                        params["rate_bytes_per_second"] = int(
                            body["rate_bytes_per_second"])
                    self._json({"job": ms.jobs.submit(
                        "scrub", vids, collection=col, params=params,
                        parallel=int(body.get("parallel", 0)),
                        submitted_by=str(
                            body.get("submittedBy", "http")))})
                except (ValueError, OSError) as e:
                    self._json({"error": str(e)}, 400)
            elif u.path == "/cluster/cache_subscribe":
                # Gateways (filer/S3/WebDAV chunk caches) register here
                # for job-commit invalidation fan-out; re-subscribing
                # refreshes the entry, so a periodic loop survives
                # leader changes.
                if self._proxy_to_leader():
                    return
                url = q.get("url", "")
                if not url:
                    self._json({"error": "url query parameter "
                                "required"}, 400)
                else:
                    ms.cache_hub.subscribe(url)
                    self._json({"ok": True,
                                "subscribers":
                                    len(ms.cache_hub.to_map())})
            elif u.path == "/vol/grow":
                if self._proxy_to_leader():
                    return
                try:
                    n = int(q.get("count", 1))
                    vids = [ms.grow_volume(
                        q.get("collection", ""),
                        q.get("replication") or None,
                        q.get("ttl", "")) for _ in range(n)]
                    self._json({"count": len(vids), "volumeIds": vids})
                except NotLeaderError as e:
                    self._json({"error": str(e), "leader": e.leader}, 503)
                except (TopologyError, ValueError) as e:
                    self._json({"error": str(e)}, 500)
            else:
                self.do_GET()

    return tracing.instrument_http_handler(
        httpserver.admission_gate(Handler), "master")


def main(argv: Optional[list[str]] = None) -> int:
    """``python -m seaweedfs_tpu master`` entry (weed/command/master.go)."""
    import argparse

    p = argparse.ArgumentParser(prog="master")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=9333)
    p.add_argument("-volumeSizeLimitMB", type=int, default=30 * 1024)
    p.add_argument("-defaultReplication", default="000")
    p.add_argument("-pulseSeconds", type=float, default=5.0)
    p.add_argument("-metricsAddress", default="",
                   help="Prometheus push-gateway host:port")
    p.add_argument("-metricsIntervalSeconds", type=float, default=15.0)
    p.add_argument("-peers", default="",
                   help="comma-separated master urls for HA election")
    p.add_argument("-mdir", default="",
                   help="meta dir persisting raft state + sequence")
    p.add_argument("-config", default="")
    args = p.parse_args(argv)
    conf = config_mod.load(args.config) if args.config else {}
    secret = config_mod.lookup(conf, "jwt.signing.key", "")
    tls_mod.install_from_config(conf)
    tracing.configure_from(conf)
    retry.configure_from(conf)
    faults_mod.configure_from(conf)
    profiler.configure_from(conf)
    usage_mod.configure_from(conf)
    httpserver.configure_from(conf)
    profiler.ensure_started()
    ms = MasterServer(ip=args.ip, port=args.port,
                      volume_size_limit_mb=args.volumeSizeLimitMB,
                      default_replication=args.defaultReplication,
                      pulse_seconds=args.pulseSeconds, secret=secret,
                      peers=[x for x in args.peers.split(",") if x],
                      meta_dir=args.mdir or None,
                      metrics_address=args.metricsAddress,
                      metrics_interval_seconds=args.metricsIntervalSeconds,
                      trace_ring_size=int(config_mod.lookup(
                          conf, "tracing.collector_ring_size", 256)))
    if config_mod.lookup(conf, "slo") is not None:
        ms.slo.configure(conf)
    jobs_mod.configure_from(conf)
    jsec = config_mod.lookup(conf, "jobs")
    if jsec is not None:
        ms.jobs.lease_seconds = float(
            jsec.get("lease_seconds", ms.jobs.lease_seconds))
        ms.jobs.max_attempts = int(
            jsec.get("max_attempts", ms.jobs.max_attempts))
        ms.policy.configure(jsec)
    ms.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        ms.stop()
    return 0
