"""Protobuf contracts + minimal gRPC stub plumbing.

The reference keeps all cross-process contracts in weed/pb/ (SURVEY.md §2
"Protos"); this package mirrors that with master.proto and
volume_server.proto subsets, their protoc-generated ``*_pb2`` modules, and
— because grpc_tools is not available in this environment — a small
declarative layer that builds grpc client stubs and server registrations
straight from the pb2 message classes (what ``*_pb2_grpc.py`` would have
contained, minus the codegen).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from . import filer_pb2, master_pb2, volume_server_pb2  # noqa: F401

UNARY = "unary"
SERVER_STREAM = "server_stream"
BIDI_STREAM = "bidi_stream"


@dataclass(frozen=True)
class Method:
    name: str
    request_cls: type
    response_cls: type
    kind: str = UNARY


#: /master_pb.Seaweed/... method table (matches master.proto service).
MASTER_SERVICE = "master_pb.Seaweed"
MASTER_METHODS = [
    Method("SendHeartbeat", master_pb2.Heartbeat,
           master_pb2.HeartbeatResponse, BIDI_STREAM),
    Method("Assign", master_pb2.AssignRequest, master_pb2.AssignResponse),
    Method("LookupVolume", master_pb2.LookupVolumeRequest,
           master_pb2.LookupVolumeResponse),
    Method("LookupEcVolume", master_pb2.LookupEcVolumeRequest,
           master_pb2.LookupEcVolumeResponse),
    Method("VolumeList", master_pb2.VolumeListRequest,
           master_pb2.VolumeListResponse),
    Method("GetMasterConfiguration",
           master_pb2.GetMasterConfigurationRequest,
           master_pb2.GetMasterConfigurationResponse),
]

#: /volume_server_pb.VolumeServer/... method table.
VOLUME_SERVICE = "volume_server_pb.VolumeServer"
VOLUME_METHODS = [
    Method("AllocateVolume", volume_server_pb2.AllocateVolumeRequest,
           volume_server_pb2.AllocateVolumeResponse),
    Method("VolumeDelete", volume_server_pb2.VolumeDeleteRequest,
           volume_server_pb2.VolumeDeleteResponse),
    Method("VolumeMarkReadonly", volume_server_pb2.VolumeMarkReadonlyRequest,
           volume_server_pb2.VolumeMarkReadonlyResponse),
    Method("VolumeMarkWritable", volume_server_pb2.VolumeMarkWritableRequest,
           volume_server_pb2.VolumeMarkWritableResponse),
    Method("VolumeStatus", volume_server_pb2.VolumeStatusRequest,
           volume_server_pb2.VolumeStatusResponse),
    Method("VolumeConfigure", volume_server_pb2.VolumeConfigureRequest,
           volume_server_pb2.VolumeConfigureResponse),
    Method("VolumeMount", volume_server_pb2.VolumeMountRequest,
           volume_server_pb2.VolumeMountResponse),
    Method("VolumeUnmount", volume_server_pb2.VolumeUnmountRequest,
           volume_server_pb2.VolumeUnmountResponse),
    Method("CopyFile", volume_server_pb2.CopyFileRequest,
           volume_server_pb2.CopyFileResponse, SERVER_STREAM),
    Method("ReadNeedleBlob", volume_server_pb2.ReadNeedleBlobRequest,
           volume_server_pb2.ReadNeedleBlobResponse),
    Method("WriteNeedleBlob", volume_server_pb2.WriteNeedleBlobRequest,
           volume_server_pb2.WriteNeedleBlobResponse),
    Method("VolumeCopy", volume_server_pb2.VolumeCopyRequest,
           volume_server_pb2.VolumeCopyResponse),
    Method("VolumeEcShardsGenerate",
           volume_server_pb2.VolumeEcShardsGenerateRequest,
           volume_server_pb2.VolumeEcShardsGenerateResponse),
    Method("VolumeEcShardsRebuild",
           volume_server_pb2.VolumeEcShardsRebuildRequest,
           volume_server_pb2.VolumeEcShardsRebuildResponse),
    Method("VolumeEcShardsCopy",
           volume_server_pb2.VolumeEcShardsCopyRequest,
           volume_server_pb2.VolumeEcShardsCopyResponse),
    Method("VolumeEcShardsDelete",
           volume_server_pb2.VolumeEcShardsDeleteRequest,
           volume_server_pb2.VolumeEcShardsDeleteResponse),
    Method("VolumeEcShardsMount",
           volume_server_pb2.VolumeEcShardsMountRequest,
           volume_server_pb2.VolumeEcShardsMountResponse),
    Method("VolumeEcShardsUnmount",
           volume_server_pb2.VolumeEcShardsUnmountRequest,
           volume_server_pb2.VolumeEcShardsUnmountResponse),
    Method("VolumeEcShardRead",
           volume_server_pb2.VolumeEcShardReadRequest,
           volume_server_pb2.VolumeEcShardReadResponse, SERVER_STREAM),
    Method("VolumeEcShardsToVolume",
           volume_server_pb2.VolumeEcShardsToVolumeRequest,
           volume_server_pb2.VolumeEcShardsToVolumeResponse),
    Method("VolumeEcBlobDelete",
           volume_server_pb2.VolumeEcBlobDeleteRequest,
           volume_server_pb2.VolumeEcBlobDeleteResponse),
    Method("VacuumVolumeCheck",
           volume_server_pb2.VacuumVolumeCheckRequest,
           volume_server_pb2.VacuumVolumeCheckResponse),
    Method("VacuumVolumeCompact",
           volume_server_pb2.VacuumVolumeCompactRequest,
           volume_server_pb2.VacuumVolumeCompactResponse),
    Method("VacuumVolumeCommit",
           volume_server_pb2.VacuumVolumeCommitRequest,
           volume_server_pb2.VacuumVolumeCommitResponse),
    Method("VacuumVolumeCleanup",
           volume_server_pb2.VacuumVolumeCleanupRequest,
           volume_server_pb2.VacuumVolumeCleanupResponse),
    Method("VolumeTierMoveDatToRemote",
           volume_server_pb2.VolumeTierMoveDatToRemoteRequest,
           volume_server_pb2.VolumeTierMoveDatToRemoteResponse),
    Method("VolumeTierMoveDatFromRemote",
           volume_server_pb2.VolumeTierMoveDatFromRemoteRequest,
           volume_server_pb2.VolumeTierMoveDatFromRemoteResponse),
]


#: /filer_pb.SeaweedFiler/... method table (matches filer.proto).
FILER_SERVICE = "filer_pb.SeaweedFiler"
FILER_METHODS = [
    Method("LookupDirectoryEntry",
           filer_pb2.LookupDirectoryEntryRequest,
           filer_pb2.LookupDirectoryEntryResponse),
    Method("ListEntries", filer_pb2.ListEntriesRequest,
           filer_pb2.ListEntriesResponse, SERVER_STREAM),
    Method("CreateEntry", filer_pb2.CreateEntryRequest,
           filer_pb2.CreateEntryResponse),
    Method("UpdateEntry", filer_pb2.UpdateEntryRequest,
           filer_pb2.UpdateEntryResponse),
    Method("DeleteEntry", filer_pb2.DeleteEntryRequest,
           filer_pb2.DeleteEntryResponse),
    Method("AtomicRenameEntry", filer_pb2.AtomicRenameEntryRequest,
           filer_pb2.AtomicRenameEntryResponse),
    Method("SubscribeMetadata", filer_pb2.SubscribeMetadataRequest,
           filer_pb2.SubscribeMetadataResponse, SERVER_STREAM),
    Method("GetFilerConfiguration",
           filer_pb2.GetFilerConfigurationRequest,
           filer_pb2.GetFilerConfigurationResponse),
]


def generic_handler(service_name: str, methods: list[Method],
                    servicer) -> "grpc.GenericRpcHandler":
    """Build the server-side dispatch table for one service.

    ``servicer`` provides one method per Method.name; unary handlers take
    (request, context), streaming handlers follow grpc's usual shapes.
    """
    import grpc

    from ..util import tracing

    handlers: dict[str, object] = {}
    for m in methods:
        fn: Callable = getattr(servicer, m.name)
        if m.kind == UNARY:
            handlers[m.name] = grpc.unary_unary_rpc_method_handler(
                tracing.wrap_grpc_unary(fn, m.name),
                request_deserializer=m.request_cls.FromString,
                response_serializer=m.response_cls.SerializeToString)
        elif m.kind == SERVER_STREAM:
            handlers[m.name] = grpc.unary_stream_rpc_method_handler(
                tracing.wrap_grpc_stream(fn, m.name),
                request_deserializer=m.request_cls.FromString,
                response_serializer=m.response_cls.SerializeToString)
        elif m.kind == BIDI_STREAM:
            handlers[m.name] = grpc.stream_stream_rpc_method_handler(
                fn, request_deserializer=m.request_cls.FromString,
                response_serializer=m.response_cls.SerializeToString)
        else:  # pragma: no cover - table is static
            raise ValueError(m.kind)
    return grpc.method_handlers_generic_handler(service_name, handlers)


class Stub:
    """Client stub: one callable attribute per service method."""

    def __init__(self, channel, service_name: str, methods: list[Method]):
        for m in methods:
            path = f"/{service_name}/{m.name}"
            if m.kind == UNARY:
                call = channel.unary_unary(
                    path, request_serializer=m.request_cls.SerializeToString,
                    response_deserializer=m.response_cls.FromString)
            elif m.kind == SERVER_STREAM:
                call = channel.unary_stream(
                    path, request_serializer=m.request_cls.SerializeToString,
                    response_deserializer=m.response_cls.FromString)
            elif m.kind == BIDI_STREAM:
                call = channel.stream_stream(
                    path, request_serializer=m.request_cls.SerializeToString,
                    response_deserializer=m.response_cls.FromString)
            else:  # pragma: no cover
                raise ValueError(m.kind)
            setattr(self, m.name, call)


def master_stub(channel) -> Stub:
    return Stub(channel, MASTER_SERVICE, MASTER_METHODS)


def volume_stub(channel) -> Stub:
    return Stub(channel, VOLUME_SERVICE, VOLUME_METHODS)


def filer_stub(channel) -> Stub:
    return Stub(channel, FILER_SERVICE, FILER_METHODS)
