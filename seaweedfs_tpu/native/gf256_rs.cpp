// Native GF(2^8) Reed-Solomon matrix apply — the CPU fast path.
//
// The reference's only native component is its vendored SIMD Galois
// arithmetic (galois_amd64.s in klauspost/reedsolomon, SURVEY.md §2 L0):
// per-coefficient multiply via PSHUFB high/low-nibble 16-entry table
// lookups. This is the same classical kernel rebuilt from the algorithm
// (Plank/Greenan/Miller "screaming fast Galois field arithmetic"):
// runtime-dispatched AVX2 / scalar paths behind one C ABI, driven from
// Python over ctypes. It serves two roles: the XLA:CPU-independent host
// fallback, and the AVX2-class baseline the TPU numbers are compared
// against in bench.py.
//
// Build: g++ -O3 -shared -fPIC gf256_rs.cpp -o _gf256_rs.so
// (seaweedfs_tpu/ops/rs_native.py does this on demand).

#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#define GF256_X86 1
#endif

namespace {

uint8_t MUL[256][256];
bool inited = false;

uint8_t gmul(uint8_t a, uint8_t b) {
    // Carry-less multiply mod the field polynomial 0x11D.
    uint8_t p = 0;
    while (b) {
        if (b & 1) p ^= a;
        const bool hi = a & 0x80;
        a = static_cast<uint8_t>(a << 1);
        if (hi) a ^= 0x1D;
        b >>= 1;
    }
    return p;
}

void xor_acc_scalar(const uint8_t* in, uint8_t* out, size_t n) {
    size_t i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t a, b;
        std::memcpy(&a, in + i, 8);
        std::memcpy(&b, out + i, 8);
        b ^= a;
        std::memcpy(out + i, &b, 8);
    }
    for (; i < n; ++i) out[i] ^= in[i];
}

void mul_acc_scalar(uint8_t c, const uint8_t* in, uint8_t* out, size_t n,
                    bool first) {
    const uint8_t* row = MUL[c];
    if (first) {
        for (size_t i = 0; i < n; ++i) out[i] = row[in[i]];
    } else {
        for (size_t i = 0; i < n; ++i) out[i] ^= row[in[i]];
    }
}

#ifdef GF256_X86
__attribute__((target("avx2")))
void mul_acc_avx2(uint8_t c, const uint8_t* in, uint8_t* out, size_t n,
                  bool first) {
    alignas(16) uint8_t lo_tab[16], hi_tab[16];
    for (int i = 0; i < 16; ++i) {
        lo_tab[i] = MUL[c][i];
        hi_tab[i] = MUL[c][i << 4];
    }
    const __m256i vlo = _mm256_broadcastsi128_si256(
        _mm_load_si128(reinterpret_cast<const __m128i*>(lo_tab)));
    const __m256i vhi = _mm256_broadcastsi128_si256(
        _mm_load_si128(reinterpret_cast<const __m128i*>(hi_tab)));
    const __m256i nib = _mm256_set1_epi8(0x0F);
    size_t i = 0;
    for (; i + 32 <= n; i += 32) {
        const __m256i x = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(in + i));
        const __m256i l = _mm256_shuffle_epi8(vlo, _mm256_and_si256(x, nib));
        const __m256i h = _mm256_shuffle_epi8(
            vhi, _mm256_and_si256(_mm256_srli_epi64(x, 4), nib));
        __m256i r = _mm256_xor_si256(l, h);
        if (!first)
            r = _mm256_xor_si256(r, _mm256_loadu_si256(
                reinterpret_cast<const __m256i*>(out + i)));
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(out + i), r);
    }
    if (i < n) mul_acc_scalar(c, in + i, out + i, n - i, first);
}

__attribute__((target("avx2")))
void xor_acc_avx2(const uint8_t* in, uint8_t* out, size_t n) {
    size_t i = 0;
    for (; i + 32 <= n; i += 32) {
        const __m256i x = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(in + i));
        const __m256i y = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(out + i));
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(out + i),
                            _mm256_xor_si256(x, y));
    }
    if (i < n) xor_acc_scalar(in + i, out + i, n - i);
}

bool have_avx2() { return __builtin_cpu_supports("avx2"); }

// ---- GFNI + AVX-512: one vgf2p8affineqb per 64 bytes ------------------
//
// Multiplication by a constant c in GF(2^8) is linear over GF(2), so it
// is an 8x8 bit matrix — exactly what VGF2P8AFFINEQB applies to every
// byte of a zmm in ONE instruction (the reference's klauspost codec
// ships the same GFNI path as its fastest amd64 kernel). The bit-layout
// convention of the matrix operand is LEARNED at init by probing the
// instruction with single-bit matrices against single-bit inputs, then
// the built tables are verified against MUL; any mismatch simply leaves
// the AVX2 path in charge — no SDM-convention trust required.

uint64_t MAT64[256];
bool gfni_ready = false;

bool have_gfni512() {
    return __builtin_cpu_supports("gfni")
        && __builtin_cpu_supports("avx512f")
        && __builtin_cpu_supports("avx512bw")
        && __builtin_cpu_supports("avx512vl");
}

__attribute__((target("avx512f,avx512bw,avx512vl,gfni")))
uint8_t gfni_apply_one(uint64_t mat, uint8_t x) {
    const __m128i vx = _mm_set1_epi8(static_cast<char>(x));
    const __m128i vA = _mm_set1_epi64x(static_cast<long long>(mat));
    const __m128i r = _mm_gf2p8affine_epi64_epi8(vx, vA, 0);
    return static_cast<uint8_t>(_mm_extract_epi8(r, 0));
}

void gfni_init() {
    if (!have_gfni512()) return;
    // learn which matrix bit k couples input bit j to output bit i
    int couple_i[64], couple_j[64];
    for (int k = 0; k < 64; ++k) {
        couple_i[k] = couple_j[k] = -1;
        const uint64_t A = 1ull << k;
        for (int j = 0; j < 8; ++j) {
            const uint8_t y = gfni_apply_one(
                A, static_cast<uint8_t>(1u << j));
            if (!y) continue;
            for (int i = 0; i < 8; ++i)
                if (y & (1u << i)) { couple_i[k] = i; couple_j[k] = j; }
        }
    }
    for (int c = 0; c < 256; ++c) {
        uint64_t A = 0;
        for (int k = 0; k < 64; ++k) {
            if (couple_i[k] < 0) continue;
            const uint8_t y = MUL[c][1u << couple_j[k]];
            if (y & (1u << couple_i[k])) A |= 1ull << k;
        }
        MAT64[c] = A;
    }
    static const uint8_t probe[] = {0, 1, 2, 3, 29, 76, 142, 253, 255};
    for (const uint8_t c : probe)
        for (int x = 0; x < 256; ++x)
            if (gfni_apply_one(MAT64[c], static_cast<uint8_t>(x))
                    != MUL[c][x])
                return;  // convention not learned: stay on AVX2
    gfni_ready = true;
}

__attribute__((target("avx512f,avx512bw,gfni")))
void mul_acc_gfni(uint8_t c, const uint8_t* in, uint8_t* out, size_t n,
                  bool first) {
    const __m512i A = _mm512_set1_epi64(
        static_cast<long long>(MAT64[c]));
    size_t i = 0;
    for (; i + 64 <= n; i += 64) {
        const __m512i x = _mm512_loadu_si512(
            reinterpret_cast<const void*>(in + i));
        __m512i r = _mm512_gf2p8affine_epi64_epi8(x, A, 0);
        if (!first)
            r = _mm512_xor_si512(r, _mm512_loadu_si512(
                reinterpret_cast<const void*>(out + i)));
        _mm512_storeu_si512(reinterpret_cast<void*>(out + i), r);
    }
    if (i < n) mul_acc_scalar(c, in + i, out + i, n - i, first);
}

__attribute__((target("avx512f,avx512bw")))
void xor_acc_avx512(const uint8_t* in, uint8_t* out, size_t n) {
    size_t i = 0;
    for (; i + 64 <= n; i += 64) {
        const __m512i x = _mm512_loadu_si512(
            reinterpret_cast<const void*>(in + i));
        const __m512i y = _mm512_loadu_si512(
            reinterpret_cast<const void*>(out + i));
        _mm512_storeu_si512(reinterpret_cast<void*>(out + i),
                            _mm512_xor_si512(x, y));
    }
    if (i < n) xor_acc_scalar(in + i, out + i, n - i);
}
#else
bool have_avx2() { return false; }
bool gfni_ready = false;
void gfni_init() {}
#endif

void mul_acc(uint8_t c, const uint8_t* in, uint8_t* out, size_t n,
             bool first) {
#ifdef GF256_X86
    if (gfni_ready) {
        mul_acc_gfni(c, in, out, n, first);
        return;
    }
    if (have_avx2()) {
        mul_acc_avx2(c, in, out, n, first);
        return;
    }
#endif
    mul_acc_scalar(c, in, out, n, first);
}

}  // namespace

extern "C" {

void gf256_init() {
    if (inited) return;
    for (int a = 0; a < 256; ++a)
        for (int b = 0; b < 256; ++b)
            MUL[a][b] = gmul(static_cast<uint8_t>(a),
                             static_cast<uint8_t>(b));
    gfni_init();
    inited = true;
}

// 0 = scalar, 2 = AVX2 nibble-LUT, 3 = GFNI+AVX512 affine.
int gf256_simd_level() {
    return gfni_ready ? 3 : (have_avx2() ? 2 : 0);
}

// out[o][s] = XOR_d coefs[o*n_in+d] * in[d][s], with explicit row
// strides so callers can hand out zero-copy column windows of larger
// arrays. The column loop is blocked so every (o, d) coefficient pass
// over a block runs against L1/L2-resident data instead of streaming
// whole shards through DRAM n_out times (klauspost's codeSomeShards
// blocks the same way for the same reason).
void rs_apply(const uint8_t* coefs, int n_out, int n_in,
              const uint8_t* in, size_t in_stride,
              uint8_t* out, size_t out_stride, size_t slen) {
    if (slen == 0) return;
    const size_t BLOCK = 32 * 1024;
    for (size_t col = 0; col < slen; col += BLOCK) {
        const size_t n = slen - col < BLOCK ? slen - col : BLOCK;
        for (int o = 0; o < n_out; ++o) {
            uint8_t* dst = out + static_cast<size_t>(o) * out_stride + col;
            bool first = true;
            for (int d = 0; d < n_in; ++d) {
                const uint8_t c = coefs[o * n_in + d];
                if (c == 0) continue;
                const uint8_t* src =
                    in + static_cast<size_t>(d) * in_stride + col;
                if (c == 1) {
                    if (first) {
                        std::memcpy(dst, src, n);
#ifdef GF256_X86
                    } else if (gfni_ready) {
                        xor_acc_avx512(src, dst, n);
                    } else if (have_avx2()) {
                        xor_acc_avx2(src, dst, n);
#endif
                    } else {
                        xor_acc_scalar(src, dst, n);
                    }
                } else {
                    mul_acc(c, src, dst, n, first);
                }
                first = false;
            }
            if (first) std::memset(dst, 0, n);
        }
    }
}

}  // extern "C"
