// Native needle map — RAM-frugal id -> (offset, size) index.
//
// The reference's CompactMap (weed/storage/needle_map/compact_map.go,
// SURVEY.md §2 "Needle map") exists because the needle index IS the
// Haystack trick: billions of entries must fit in RAM, so a Go
// map[uint64]... (~50+ B/entry of header+bucket overhead) is replaced
// with purpose-built segmented arrays. The Python-dict CompactMap pays
// ~200 B per entry; this native table stores 16-byte packed entries in
// one open-addressing array (~24 B/slot at the 0.7 load ceiling,
// including the occupancy byte) and replays .idx journals at memcpy
// speed — the same role, C++ instead of Go.
//
// Layout: linear probing, power-of-two capacity, grow at 70% load.
// Deletes keep the slot (needle tombstone IS data: deleted_bytes feeds
// vacuum scheduling) with size = 0xFFFFFFFF, mirroring the on-disk
// .idx tombstone sentinel.
//
// Build: g++ -O3 -shared -fPIC needle_map.cpp -o _needle_map.so
// (storage/needle_map_native.py does this on demand).

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

constexpr uint32_t TOMBSTONE = 0xFFFFFFFFu;

struct Entry {
    uint64_t key;
    uint32_t off;
    uint32_t size;
};

struct Map {
    Entry *slots;
    uint8_t *used;
    uint64_t cap;      // power of two
    uint64_t filled;   // used slots (live + tombstoned)
    // CompactMap bookkeeping (store status + heartbeats + vacuum)
    uint64_t file_count;
    uint64_t deleted_count;
    uint64_t deleted_bytes;
    uint64_t max_off;
    uint64_t max_key;
    uint64_t live;
};

inline uint64_t hash_key(uint64_t k) {
    // splitmix64 finalizer: full-avalanche, cheap
    k ^= k >> 30; k *= 0xbf58476d1ce4e5b9ULL;
    k ^= k >> 27; k *= 0x94d049bb133111ebULL;
    k ^= k >> 31;
    return k;
}

inline uint64_t probe_slot(const Map *m, uint64_t key, bool *found) {
    uint64_t mask = m->cap - 1;
    uint64_t i = hash_key(key) & mask;
    while (m->used[i]) {
        if (m->slots[i].key == key) { *found = true; return i; }
        i = (i + 1) & mask;
    }
    *found = false;
    return i;
}

bool grow(Map *m);

// Raw slot insert/update, no counter bookkeeping.
// Returns 1 = replaced existing (old size in *old_size), 0 = inserted
// new, -1 = allocation failure (nothing changed).
int raw_set(Map *m, uint64_t key, uint32_t off, uint32_t size,
            uint32_t *old_size) {
    if ((m->filled + 1) * 10 >= m->cap * 7) {
        if (!grow(m)) return -1;
    }
    bool found;
    uint64_t i = probe_slot(m, key, &found);
    if (found) {
        *old_size = m->slots[i].size;
        m->slots[i].off = off;
        m->slots[i].size = size;
        return 1;
    }
    m->used[i] = 1;
    m->filled++;
    m->slots[i] = Entry{key, off, size};
    return 0;
}

bool grow(Map *m) {
    uint64_t ncap = m->cap * 2;
    Entry *nslots = (Entry *)calloc(ncap, sizeof(Entry));
    uint8_t *nused = (uint8_t *)calloc(ncap, 1);
    if (!nslots || !nused) { free(nslots); free(nused); return false; }
    Entry *oslots = m->slots;
    uint8_t *oused = m->used;
    uint64_t ocap = m->cap;
    m->slots = nslots; m->used = nused; m->cap = ncap;
    uint64_t mask = ncap - 1;
    for (uint64_t i = 0; i < ocap; i++) {
        if (!oused[i]) continue;
        uint64_t j = hash_key(oslots[i].key) & mask;
        while (nused[j]) j = (j + 1) & mask;
        nused[j] = 1;
        nslots[j] = oslots[i];
    }
    free(oslots);
    free(oused);
    return true;
}

}  // namespace

extern "C" {

void *nm_new(uint64_t cap_hint) {
    uint64_t cap = 1024;
    while (cap * 7 < cap_hint * 10) cap <<= 1;  // fit hint under 70%
    Map *m = (Map *)calloc(1, sizeof(Map));
    if (!m) return nullptr;
    m->cap = cap;
    m->slots = (Entry *)calloc(cap, sizeof(Entry));
    m->used = (uint8_t *)calloc(cap, 1);
    if (!m->slots || !m->used) {
        free(m->slots); free(m->used); free(m);
        return nullptr;
    }
    return m;
}

void nm_free(void *h) {
    if (!h) return;
    Map *m = (Map *)h;
    free(m->slots);
    free(m->used);
    free(m);
}

// set: returns 0 ok, -1 allocation failure
int nm_set(void *h, uint64_t key, uint32_t off, uint32_t size) {
    Map *m = (Map *)h;
    uint32_t old = 0;
    int existed = raw_set(m, key, off, size, &old);
    if (existed < 0) return -1;
    if (existed) {
        if (old != TOMBSTONE) {       // overwrote a live entry
            m->deleted_count++;
            m->deleted_bytes += old;
        } else {
            m->live++;                // tombstone resurrected
        }
    } else {
        m->live++;
    }
    m->file_count++;
    if (off > m->max_off) m->max_off = off;
    if (key > m->max_key) m->max_key = key;
    return 0;
}

// delete: 1 when a live entry was tombstoned, 0 otherwise
int nm_delete(void *h, uint64_t key) {
    Map *m = (Map *)h;
    bool found;
    uint64_t i = probe_slot(m, key, &found);
    if (!found || m->slots[i].size == TOMBSTONE) return 0;
    m->deleted_count++;
    m->deleted_bytes += m->slots[i].size;
    m->slots[i].size = TOMBSTONE;
    m->live--;
    return 1;
}

// get: 1 when live, fills off/size
int nm_get(void *h, uint64_t key, uint32_t *off, uint32_t *size) {
    Map *m = (Map *)h;
    bool found;
    uint64_t i = probe_slot(m, key, &found);
    if (!found || m->slots[i].size == TOMBSTONE) return 0;
    *off = m->slots[i].off;
    *size = m->slots[i].size;
    return 1;
}

uint64_t nm_live(void *h) { return ((Map *)h)->live; }

void nm_stats(void *h, uint64_t *file_count, uint64_t *deleted_count,
              uint64_t *deleted_bytes, uint64_t *max_off,
              uint64_t *max_key) {
    Map *m = (Map *)h;
    *file_count = m->file_count;
    *deleted_count = m->deleted_count;
    *deleted_bytes = m->deleted_bytes;
    *max_off = m->max_off;
    *max_key = m->max_key;
}

// Dump up to max_n LIVE entries (unsorted) into parallel arrays;
// returns the count written.
uint64_t nm_dump_live(void *h, uint64_t *keys, uint32_t *offs,
                      uint32_t *sizes, uint64_t max_n) {
    Map *m = (Map *)h;
    uint64_t n = 0;
    for (uint64_t i = 0; i < m->cap && n < max_n; i++) {
        if (!m->used[i] || m->slots[i].size == TOMBSTONE) continue;
        keys[n] = m->slots[i].key;
        offs[n] = m->slots[i].off;
        sizes[n] = m->slots[i].size;
        n++;
    }
    return n;
}

// Replay n 16-byte BIG-ENDIAN .idx records (key u64, offset u32, size
// u32 — idx.go's on-disk layout). Returns records applied, or a value
// < n on allocation failure.
uint64_t nm_load_idx(void *h, const uint8_t *buf, uint64_t n) {
    for (uint64_t r = 0; r < n; r++) {
        const uint8_t *p = buf + 16 * r;
        uint64_t key = 0;
        for (int b = 0; b < 8; b++) key = (key << 8) | p[b];
        uint32_t off = ((uint32_t)p[8] << 24) | ((uint32_t)p[9] << 16) |
                       ((uint32_t)p[10] << 8) | p[11];
        uint32_t size = ((uint32_t)p[12] << 24) | ((uint32_t)p[13] << 16) |
                        ((uint32_t)p[14] << 8) | p[15];
        if (size == TOMBSTONE) {
            nm_delete(h, key);
        } else if (nm_set(h, key, off, size) != 0) {
            return r;
        }
    }
    return n;
}

}  // extern "C"
