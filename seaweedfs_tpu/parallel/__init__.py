"""Multi-chip execution: device meshes, sharded codec steps, collectives."""
