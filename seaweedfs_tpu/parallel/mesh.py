"""Sharded EC steps over a jax.sharding.Mesh.

The reference distributes EC work by placing the 14 shard files on
different servers and moving bytes with gRPC (SURVEY.md §2 "parallelism
strategies" table). The TPU-native equivalent keeps the math on a device
mesh instead:

* ``dp`` (volume/batch axis): independent volumes spread across chips —
  the analog of many volume servers encoding concurrently.
* ``sp`` (stripe axis): one volume's byte range split across chips — the
  analog of the reference striping one .dat over shard servers. The
  bitsliced codec is positionwise over 128-byte groups, so stripe-axis
  sharding needs NO communication for encode; only the global integrity
  checksum crosses chips (one psum over the mesh, riding ICI).

Steps are built with shard_map so the collective structure is explicit
and compiles to XLA collectives; the same code runs on a virtual CPU mesh
(tests, the driver's dry-run) and a real TPU pod slice.

Production routing (docs/mesh.md): the pipeline's encode/rebuild/batch
paths call :func:`routing_mesh` — an explicit ``[mesh]`` TOML section or
``-mesh dp,sp`` shell flag pins a mesh (virtual CPU meshes included, the
CI recipe), a multi-chip accelerator auto-shards adaptively, and
everything else stays on the single-device host fast path. The compute
stage splits into prepare (H2D shard placement — :func:`prepare_batch`)
and apply (the mesh step — :func:`apply_prepared`) so ``[pipeline]
double_buffer`` can overlap the next batch's transfer with the current
batch's collective.
"""

from __future__ import annotations

import collections
import contextlib
import math
import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map graduated from jax.experimental in newer releases; prefer
# the top-level API, fall back to the experimental home on older jax.
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops import bitslice
from ..ops.rs_jax import Encoder

GROUP = bitslice.GROUP_BYTES


def _auto_factor(n: int) -> tuple[int, int]:
    """Most-square (dp, sp) with sp >= dp (stripe parallelism is
    communication-free here, so over-sharding it is harmless)."""
    dp = 1
    for f in range(int(math.isqrt(n)), 0, -1):
        if n % f == 0:
            dp = f
            break
    return dp, n // dp


def make_mesh(devices=None, dp: Optional[int] = None,
              sp: Optional[int] = None) -> Mesh:
    """Build a (dp, sp) mesh over the given devices (default: all).

    Without explicit sizes, picks the most-square factorization with the
    stripe axis at least as large as the batch axis (stripe parallelism
    is communication-free here, so over-sharding it is harmless).

    An explicit request is honored or refused, never silently
    re-factored: any (dp, sp) that cannot tile the device count raises
    with the factorization that would.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if dp is not None and dp < 1 or sp is not None and sp < 1:
        raise ValueError(f"mesh axes must be positive, got dp={dp} sp={sp}")
    if dp is None and sp is None:
        dp, sp = _auto_factor(n)
    elif dp is None:
        if n % sp:
            raise ValueError(
                f"sp={sp} does not divide device count {n} "
                f"(auto factorization would be dp,sp = "
                f"{_auto_factor(n)[0]},{_auto_factor(n)[1]})")
        dp = n // sp
    elif sp is None:
        if n % dp:
            raise ValueError(
                f"dp={dp} does not divide device count {n} "
                f"(auto factorization would be dp,sp = "
                f"{_auto_factor(n)[0]},{_auto_factor(n)[1]})")
        sp = n // dp
    if dp * sp != n:
        raise ValueError(
            f"dp*sp = {dp}*{sp} = {dp * sp} != device count {n}: an "
            f"explicit mesh must tile ALL local devices (want dp*sp == "
            f"{n}, e.g. {_auto_factor(n)[0]},{_auto_factor(n)[1]})")
    dev_array = np.array(devices).reshape(dp, sp)
    return Mesh(dev_array, axis_names=("dp", "sp"))


# --------------------------------------------------------------------------
# configuration — the [mesh] TOML section / the -mesh shell flag
# --------------------------------------------------------------------------

class MeshConfigError(ValueError):
    """A [mesh]/-mesh request that cannot tile the local devices."""


@dataclass
class MeshConfig:
    """The ``[mesh]`` TOML section (docs/mesh.md): pin an EXPLICIT
    device mesh for the production encode/rebuild paths. Disabled (the
    default) keeps the auto routing — multi-chip accelerators shard
    adaptively, everything else takes the single-device host fast
    path. ``0`` for an axis means "derive" (most-square
    factorization). Flags > TOML > defaults, like every other
    subsystem (util/config.py)."""

    enabled: bool = False
    dp: int = 0
    sp: int = 0


_CONFIG = MeshConfig()


def current() -> MeshConfig:
    return _CONFIG


def configure(**kw) -> None:
    """Set config fields; None values keep their current setting."""
    for key, val in kw.items():
        if not hasattr(_CONFIG, key):
            raise TypeError(f"unknown mesh config key {key!r}")
        if val is not None:
            cur = getattr(_CONFIG, key)
            setattr(_CONFIG, key, type(cur)(val))


def configure_from(conf: dict) -> None:
    """Apply a loaded TOML dict's ``[mesh]`` block (missing keys keep
    their current values)."""
    from ..util import config as config_mod
    sect = config_mod.lookup(conf, "mesh")
    if not isinstance(sect, dict):
        return
    configure(**{k: sect.get(k) for k in ("enabled", "dp", "sp")})


def parse_spec(spec: str) -> tuple[int, int]:
    """``-mesh dp,sp`` -> (dp, sp); ``-mesh auto`` -> (0, 0), the
    most-square factorization of the local device count."""
    text = (spec or "").strip().lower()
    if text in ("auto", ""):
        return 0, 0
    parts = text.split(",")
    try:
        if len(parts) != 2:
            raise ValueError
        dp, sp = int(parts[0]), int(parts[1])
        if dp < 1 or sp < 1:
            raise ValueError
    except ValueError:
        raise MeshConfigError(
            f"bad mesh spec {spec!r}: want 'dp,sp' with positive "
            f"integers (e.g. '2,4') or 'auto'") from None
    return dp, sp


@contextlib.contextmanager
def scoped(spec: str):
    """Enable an explicit mesh for one command/job (the ``-mesh`` shell
    flag; the ec_encode job param): parse, validate against the local
    device count — a clear :class:`MeshConfigError` BEFORE any work
    starts — and restore the previous config on exit. Yields the Mesh."""
    dp, sp = parse_spec(spec)
    prev = (_CONFIG.enabled, _CONFIG.dp, _CONFIG.sp)
    _CONFIG.enabled, _CONFIG.dp, _CONFIG.sp = True, dp, sp
    try:
        yield configured_mesh()
    finally:
        _CONFIG.enabled, _CONFIG.dp, _CONFIG.sp = prev


_configured_cache: dict = {}   # (n_devices, dp, sp) -> Mesh


def configured_mesh() -> Optional[Mesh]:
    """The ``[mesh]``-configured Mesh over all local devices, or None
    when the section is disabled. An explicit (dp, sp) that cannot tile
    the device count is a :class:`MeshConfigError` — the request is
    honored or refused, never silently re-factored."""
    if not _CONFIG.enabled:
        return None
    n = len(jax.devices())
    key = (n, _CONFIG.dp, _CONFIG.sp)
    mesh = _configured_cache.get(key)
    if mesh is None:
        try:
            mesh = make_mesh(dp=_CONFIG.dp or None,
                             sp=_CONFIG.sp or None)
        except ValueError as e:
            auto = _auto_factor(n)
            raise MeshConfigError(
                f"mesh dp={_CONFIG.dp or 'auto'},"
                f"sp={_CONFIG.sp or 'auto'} cannot tile the {n} local "
                f"device(s): {e}. Pass -mesh dp,sp with dp*sp == {n} "
                f"(e.g. '{auto[0]},{auto[1]}'), or -mesh auto.") from e
        _configured_cache.clear()  # one live shape; drop stale counts
        _configured_cache[key] = mesh
    return mesh


#: Sentinel :func:`routing_mesh` returns for "shard, but let the auto
#: path adapt the mesh per batch" (multi-chip accelerators).
AUTO = object()


def routing_mesh():
    """What the production twin paths (pipeline encode / rebuild /
    coalescing batcher) should do: a Mesh when ``[mesh]`` is enabled
    (virtual CPU meshes included — the CI recipe), the :data:`AUTO`
    sentinel on a multi-chip accelerator (adaptive dp, Pallas
    kernels), or None for the single-device host fast path."""
    mesh = configured_mesh()
    if mesh is not None:
        return mesh
    from ..ops.rs_jax import _use_pallas
    if _use_pallas() and len(jax.devices()) > 1:
        return AUTO
    return None


def make_sharded_encode_step(encoder: Encoder, mesh: Mesh):
    """jitted (B, k, S) u8 -> ((B, m, S) parity, scalar checksum).

    Input sharded (dp, -, sp); parity keeps the same sharding; the
    checksum is the byte-sum of the parity **mod 2^32** (uint32
    accumulation), psum-reduced over BOTH axes so every chip holds the
    global value (the cross-chip integrity handshake a multi-server
    encode does over gRPC in the reference). Host-side verifiers must
    reduce mod 2^32 too.
    """
    coefs = encoder.parity_coefs

    def step(x):
        parity = bitslice.apply_gf_matrix(coefs, x)
        local = jnp.sum(parity.astype(jnp.uint32), dtype=jnp.uint32)
        total = jax.lax.psum(local, ("dp", "sp"))
        return parity, total

    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=(P("dp", None, "sp"), P()),
    )
    return jax.jit(mapped)


def make_sharded_train_step(encoder: Encoder, mesh: Mesh,
                            lost: tuple[int, ...] = (0,)):
    """The FULL EC 'training step' used by the driver's multi-chip dry run:
    encode -> drop ``lost`` shards -> reconstruct them -> verify they match
    the originals, returning ((B, m, S) parity, scalar mismatch count).

    Exercises the complete device-side math (both matrix applications) plus
    a global psum, all under one jit over the mesh.
    """
    k, m = encoder.data_shards, encoder.parity_shards
    total_n = encoder.total_shards
    parity_coefs = encoder.parity_coefs
    lost = tuple(sorted(lost))
    present = [i for i in range(total_n) if i not in lost]
    rebuild_coefs = encoder.decode_matrix_rows(present, list(lost))

    def step(x):
        parity = bitslice.apply_gf_matrix(parity_coefs, x)
        full = jnp.concatenate([x, parity], axis=1)
        originals = full[:, lost, :]
        survivors = full[:, present[:k], :]
        rebuilt = bitslice.apply_gf_matrix(rebuild_coefs, survivors)
        local_bad = jnp.sum((rebuilt != originals).astype(jnp.uint32),
                            dtype=jnp.uint32)
        mismatches = jax.lax.psum(local_bad, ("dp", "sp"))
        return parity, mismatches

    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=(P("dp", None, "sp"), P()),
    )
    return jax.jit(mapped)


def make_sharded_rebuild_step(encoder: Encoder, mesh: Mesh,
                              present, wanted):
    """jitted survivors (B, k, S) u8 -> ((B, len(wanted), S) rebuilt,
    scalar u32 byte-sum checksum psum-reduced over the mesh).

    The sp axis shards the BYTE RANGE of real shard files: the decode
    matrix application is positionwise over 128-byte groups, so each
    chip rebuilds its slice of the lost shards from its slice of the
    survivors with no communication — the cross-chip part is only the
    integrity psum. ``present`` may be ANY survivor set (uneven mixes
    of data and parity ids; the first k are used), matching how
    ec.rebuild reads whichever shards are still alive (SURVEY §3.3)."""
    rows = encoder.decode_matrix_rows(list(present), list(wanted))

    def step(surv):
        rebuilt = bitslice.apply_gf_matrix(rows, surv)
        local = jnp.sum(rebuilt.astype(jnp.uint32), dtype=jnp.uint32)
        return rebuilt, jax.lax.psum(local, ("dp", "sp"))

    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=(P("dp", None, "sp"), P()),
    )
    return jax.jit(mapped)


_auto_meshes: dict = {}       # dp-choice -> Mesh over all devices
_auto_n_devices = 0
#: (mesh shape, coefs shape, coefs bytes) -> jitted step; LRU-bounded —
#: rebuilds mint one decode matrix per loss pattern, and a long-lived
#: repair daemon must not accumulate an executable per pattern forever.
_auto_steps: "collections.OrderedDict" = collections.OrderedDict()
_AUTO_STEPS_CAP = 32


def _make_apply_only_step(coefs: np.ndarray, mesh: Mesh):
    """Checksum-free coefficient-rows application for the production
    paths (encode: parity rows; rebuild: decode rows): the integrity
    psum belongs to the verify-style steps, not to every data batch —
    paying a full reduction plus a both-axes collective per batch would
    be wasted ICI traffic. On an accelerator the per-shard math is the
    fused Pallas kernel; elsewhere the XLA network.

    The input shards are donated when the donation knob engages
    (rs_jax.donation_enabled — real accelerators only): every caller
    feeds a freshly device_put array that is never reused, so XLA may
    release the input HBM inside the computation — the same early-free
    win the single-device word-form path gets from _jitted_apply."""
    from ..ops import rs_jax, rs_pallas
    if _real_accelerator():
        def step(x):
            return rs_pallas.apply_gf_matrix(coefs, x)
    else:
        def step(x):
            return bitslice.apply_gf_matrix(coefs, x)
    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=P("dp", None, "sp"),
    )
    donate = (0,) if rs_jax.donation_enabled() else ()
    return jax.jit(mapped, donate_argnums=donate)


def _real_accelerator() -> bool:
    """The REAL backend decides kernel + granule (Mosaic only lowers on
    TPU) — deliberately decoupled from rs_jax._use_pallas, which the
    routing gates (and their tests) may override."""
    return jax.default_backend() in ("tpu", "axon")


def _granule(sp: int) -> int:
    """Per-shard S granule for the auto-sharded encode: the Pallas
    kernel needs SEG_BYTES per device shard; the XLA network only the
    packing group. Follows the REAL backend, like the step kernel."""
    from ..ops import rs_pallas
    return sp * (rs_pallas.SEG_BYTES if _real_accelerator() else GROUP)


# --------------------------------------------------------------------------
# telemetry — pipe.compute split into dispatch (H2D shard placement)
# vs collective (the mesh step) time, plus per-axis gauges
# --------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()
_TOTALS = {"batches": 0, "bytes_in": 0, "bytes_out": 0,
           "dispatch_seconds": 0.0, "collective_seconds": 0.0}
_LAST_SHAPE = {"dp": 0, "sp": 0}
#: the closed stage vocabulary — prepare is "dispatch", the mesh step
#: is "collective"; nothing else ever reaches _observe
_STAGE_NAMES = {"dispatch": "pipe.compute.dispatch",
                "collective": "pipe.compute.collective"}
#: stage suffix -> (latency histogram, bytes counter); cached like
#: pipe._STAGE_INSTRUMENTS — a rare double-create just wins the same
#: registry entry.
_INSTRUMENTS: dict = {}


def _observe(kind: str, seconds: float, nbytes: int, mesh: Mesh) -> None:
    """Fold one prepare ("dispatch") or step ("collective") measurement
    into the module totals, the shared ``request_stage_seconds{stage=
    pipe.compute.<kind>}`` tracing series (the PR 6 pipeline split),
    and the per-axis ``seaweed_mesh_axis_size`` gauges."""
    from ..util import tracing
    tup = _INSTRUMENTS.get(kind)
    if tup is None:
        stage = _STAGE_NAMES[kind]
        tup = (tracing.METRICS.histogram("request_stage_seconds",
                                         stage=stage),
               tracing.METRICS.counter("stage_bytes_total",
                                       stage=stage))
        _INSTRUMENTS[kind] = tup
    tup[0].observe(seconds)
    if nbytes:
        tup[1].inc(nbytes)
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    shape_changed = False
    with _STATS_LOCK:
        _TOTALS[f"{kind}_seconds"] += seconds
        if kind == "dispatch":
            _TOTALS["batches"] += 1
            _TOTALS["bytes_in"] += nbytes
        else:
            _TOTALS["bytes_out"] += nbytes
        if (_LAST_SHAPE["dp"], _LAST_SHAPE["sp"]) != (dp, sp):
            _LAST_SHAPE["dp"], _LAST_SHAPE["sp"] = dp, sp
            shape_changed = True
    if shape_changed:
        for axis, size in (("dp", dp), ("sp", sp)):
            tracing.METRICS.gauge("mesh_axis_size", axis=axis).set(size)


def debug_payload() -> dict:
    """``/debug/vars`` "mesh" section (util/varz.py): the configured
    shape plus the cumulative dispatch/collective split."""
    with _STATS_LOCK:
        out = {k: (round(v, 6) if isinstance(v, float) else v)
               for k, v in _TOTALS.items()}
        out["axes"] = dict(_LAST_SHAPE)
    out["configured"] = {"enabled": _CONFIG.enabled,
                         "dp": _CONFIG.dp, "sp": _CONFIG.sp}
    return out


def reset_telemetry() -> None:
    """Drop the cumulative mesh-stage totals (tests)."""
    with _STATS_LOCK:
        for k in _TOTALS:
            _TOTALS[k] = 0 if isinstance(_TOTALS[k], int) else 0.0
        _LAST_SHAPE["dp"] = _LAST_SHAPE["sp"] = 0


# --------------------------------------------------------------------------
# the production host-batch path: prepare (H2D) / apply (mesh step)
# --------------------------------------------------------------------------

class Prepared:
    """A host batch already placed on the mesh: the (possibly padded)
    async sharded device array plus the original (b, s) so apply can
    slice the padding back off lazily."""

    __slots__ = ("arr", "b", "s", "mesh")

    def __init__(self, arr, b: int, s: int, mesh: Mesh):
        self.arr = arr
        self.b = b
        self.s = s
        self.mesh = mesh


def _auto_mesh_for(b: int) -> Mesh:
    """The adaptive auto mesh: small B (the rebuild path streams B=1
    chunks) drops to an sp-only mesh so every device holds a stripe
    slice instead of (dp-1)/dp of them computing zero padding."""
    global _auto_n_devices
    n_dev = len(jax.devices())
    if _auto_n_devices != n_dev:
        _auto_meshes.clear()
        _auto_steps.clear()  # steps bake their mesh into shard_map
        # device-count memo: jax.devices() is stable per process, so
        # every writer stores the same value and a racing re-clear
        # only costs a mesh rebuild
        # seaweedlint: disable=SW801 — idempotent memo
        _auto_n_devices = n_dev
    dp_auto, _ = _auto_factor(n_dev)
    dp = dp_auto if b >= dp_auto else 1
    mesh = _auto_meshes.get(dp)
    if mesh is None:
        mesh = make_mesh(dp=dp)
        _auto_meshes[dp] = mesh
    return mesh


def _step_for(coefs: np.ndarray, mesh: Mesh):
    """LRU-cached apply-only step for (mesh shape, coefs). Keyed by
    shape, not Mesh identity: every mesh here spans all local devices
    in enumeration order, so equal shapes are interchangeable."""
    key = (mesh.shape["dp"], mesh.shape["sp"],
           coefs.shape, coefs.tobytes())
    step = _auto_steps.get(key)
    if step is None:
        step = _make_apply_only_step(coefs, mesh)
        _auto_steps[key] = step
        while len(_auto_steps) > _AUTO_STEPS_CAP:
            _auto_steps.popitem(last=False)
    else:
        _auto_steps.move_to_end(key)
    return step


def prepare_batch(batch: np.ndarray, mesh=None) -> Prepared:
    """Pad a HOST (B, n_in, S) u8 batch to the mesh geometry and start
    its H2D transfer with (dp, -, sp) NamedSharding.

    Rows pad to the dp multiple and S to the kernel granule — zero
    rows/columns map to zero output and are sliced off lazily by
    :func:`apply_prepared`. With ``mesh=None`` (or :data:`AUTO`) the
    adaptive auto mesh is used; an explicit Mesh is honored AS GIVEN —
    an uneven batch pads rather than re-factoring the mesh. The
    placement time lands in the ``pipe.compute.dispatch`` stage, which
    is what ``[pipeline] double_buffer`` overlaps with the previous
    batch's collective."""
    from ..pipeline import flight
    t0 = time.perf_counter()
    flight.record(flight.EV_H2D_SUBMIT)
    b, n_in, s = batch.shape
    if mesh is None or mesh is AUTO:
        mesh = _auto_mesh_for(b)
    dp = mesh.shape["dp"]
    sp = mesh.shape["sp"]
    gran = _granule(sp)
    b_pad = -(-b // dp) * dp
    s_pad = -(-s // gran) * gran
    if b_pad != b or s_pad != s:
        padded = np.zeros((b_pad, n_in, s_pad), dtype=np.uint8)
        padded[:b, :, :s] = batch
        batch = padded
    arr = shard_batch(batch, mesh)
    _observe("dispatch", time.perf_counter() - t0, batch.nbytes, mesh)
    # READY means the async device_put is ISSUED (transfer in flight),
    # not landed — the landing is observed by the batch's sync span.
    flight.record(flight.EV_H2D_READY, arg=batch.nbytes)
    return Prepared(arr, b, s, mesh)


def apply_prepared(coefs: np.ndarray, prep: Prepared):
    """Apply coefficient rows to a prepared (sharded) batch; returns
    the async device (b, n_out, s) result sliced back to the original
    extents (np.asarray materializes it — callers in the 3-stage
    pipeline keep their D2H on the writer thread). The step-enqueue
    time lands in the ``pipe.compute.collective`` stage."""
    t0 = time.perf_counter()
    coefs = np.ascontiguousarray(coefs, dtype=np.uint8)
    step = _step_for(coefs, prep.mesh)
    out = step(prep.arr)[:prep.b, :, :prep.s]  # lazy slice; no sync
    _observe("collective", time.perf_counter() - t0, out.nbytes,
             prep.mesh)
    return out


def encode_step_fns(encoder: Encoder, mesh=None):
    """(prepare_fn, apply_fn) pair for the pipeline's split compute
    stage (pipe.run_pipeline's ``prepare_fn``): prepare starts the H2D
    shard placement, apply runs the mesh parity step on the prepared
    array — the split that lets ``[pipeline] double_buffer`` overlap
    the next batch's transfer with the current batch's collective."""
    coefs = encoder.parity_coefs

    def prep(batch: np.ndarray) -> Prepared:
        return prepare_batch(batch, mesh)

    def apply(prepared: Prepared):
        return apply_prepared(coefs, prepared)

    return prep, apply


def _apply_host_sharded(coefs: np.ndarray, batch: np.ndarray, mesh=None):
    """Apply coefficient rows to a HOST (B, n_in, S) u8 batch over a
    mesh spanning ALL local devices; returns an async device
    (B, n_out, S) result. ``mesh=None``/:data:`AUTO` adapts the mesh
    to the batch; an explicit Mesh is honored as given (rows pad, the
    mesh never silently re-factors). The prepare/apply split is the
    same one the pipeline uses for double buffering."""
    return apply_prepared(coefs, prepare_batch(batch, mesh))


def encode_parity_host_sharded(encoder: Encoder, batch: np.ndarray,
                               mesh=None):
    """Production multi-chip encode: HOST (B, k, S) u8 -> async
    (B, m, S) parity over all local devices. This is the entry the
    coalescing batcher uses when routing_mesh() says to shard — the
    8-device CPU mesh in tests, the driver's dryrun, an explicit
    [mesh]/-mesh config, and real multi-chip accelerators (the
    single-chip tunnel env never takes it). ``mesh``: None/AUTO for
    the adaptive auto mesh, or the explicit Mesh to honor."""
    return _apply_host_sharded(encoder.parity_coefs, batch, mesh)


def reconstruct_host_sharded(encoder: Encoder, survivors: np.ndarray,
                             present, wanted, mesh=None):
    """Production multi-chip rebuild: decode rows for (present ->
    wanted) applied to HOST survivor chunks over the whole mesh — the
    multi-device form of reconstruct_batch_host that the rebuild
    pipeline uses when routing_mesh() says to shard. ``survivors``:
    (B, len(present), S) u8, first k used. ``mesh`` as in
    :func:`encode_parity_host_sharded`."""
    rows = encoder.decode_matrix_rows(list(present), list(wanted))
    chosen = survivors[:, :encoder.data_shards, :]
    if not chosen.flags.c_contiguous:
        chosen = np.ascontiguousarray(chosen)
    return _apply_host_sharded(rows, chosen, mesh)


def shard_batch(x: np.ndarray, mesh: Mesh, pad: bool = False):
    """Device-put a (B, k, S) batch with (dp, -, sp) sharding.

    Validates divisibility (rows must divide dp; S per chip must stay
    a multiple of the 128-byte packing group) — or, with ``pad=True``,
    zero-pads the row axis to the dp multiple and S to the sp*group
    granule instead (zero rows/columns encode to zero output; callers
    slice by the ORIGINAL extents, as prepare_batch/apply_prepared
    do)."""
    dp = mesh.shape["dp"]
    sp = mesh.shape["sp"]
    b, n_in, s = x.shape
    if pad and (b % dp or s % (sp * GROUP)):
        b_pad = -(-b // dp) * dp
        s_pad = -(-s // (sp * GROUP)) * (sp * GROUP)
        padded = np.zeros((b_pad, n_in, s_pad), dtype=np.uint8)
        padded[:b, :, :s] = x
        x = padded
        b, s = b_pad, s_pad
    if b % dp:
        raise ValueError(f"batch {b} not divisible by dp={dp}")
    if s % (sp * GROUP):
        raise ValueError(
            f"shard length {s} not divisible by sp*{GROUP} = {sp * GROUP}")
    sharding = NamedSharding(mesh, P("dp", None, "sp"))
    return jax.device_put(x, sharding)
