"""Sharded EC steps over a jax.sharding.Mesh.

The reference distributes EC work by placing the 14 shard files on
different servers and moving bytes with gRPC (SURVEY.md §2 "parallelism
strategies" table). The TPU-native equivalent keeps the math on a device
mesh instead:

* ``dp`` (volume/batch axis): independent volumes spread across chips —
  the analog of many volume servers encoding concurrently.
* ``sp`` (stripe axis): one volume's byte range split across chips — the
  analog of the reference striping one .dat over shard servers. The
  bitsliced codec is positionwise over 128-byte groups, so stripe-axis
  sharding needs NO communication for encode; only the global integrity
  checksum crosses chips (one psum over the mesh, riding ICI).

Steps are built with shard_map so the collective structure is explicit
and compiles to XLA collectives; the same code runs on a virtual CPU mesh
(tests, the driver's dry-run) and a real TPU pod slice.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import bitslice
from ..ops.rs_jax import Encoder

GROUP = bitslice.GROUP_BYTES


def make_mesh(devices=None, dp: Optional[int] = None,
              sp: Optional[int] = None) -> Mesh:
    """Build a (dp, sp) mesh over the given devices (default: all).

    Without explicit sizes, picks the most-square factorization with the
    stripe axis at least as large as the batch axis (stripe parallelism
    is communication-free here, so over-sharding it is harmless).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if dp is None and sp is None:
        dp = 1
        for f in range(int(math.isqrt(n)), 0, -1):
            if n % f == 0:
                dp = f
                break
        sp = n // dp
    elif dp is None:
        if n % sp:
            raise ValueError(f"sp={sp} does not divide device count {n}")
        dp = n // sp
    elif sp is None:
        if n % dp:
            raise ValueError(f"dp={dp} does not divide device count {n}")
        sp = n // dp
    if dp * sp != n:
        raise ValueError(f"dp*sp = {dp}*{sp} != device count {n}")
    dev_array = np.array(devices).reshape(dp, sp)
    return Mesh(dev_array, axis_names=("dp", "sp"))


def make_sharded_encode_step(encoder: Encoder, mesh: Mesh):
    """jitted (B, k, S) u8 -> ((B, m, S) parity, scalar checksum).

    Input sharded (dp, -, sp); parity keeps the same sharding; the
    checksum is the byte-sum of the parity **mod 2^32** (uint32
    accumulation), psum-reduced over BOTH axes so every chip holds the
    global value (the cross-chip integrity handshake a multi-server
    encode does over gRPC in the reference). Host-side verifiers must
    reduce mod 2^32 too.
    """
    coefs = encoder.parity_coefs

    def step(x):
        parity = bitslice.apply_gf_matrix(coefs, x)
        local = jnp.sum(parity.astype(jnp.uint32), dtype=jnp.uint32)
        total = jax.lax.psum(local, ("dp", "sp"))
        return parity, total

    mapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=(P("dp", None, "sp"), P()),
    )
    return jax.jit(mapped)


def make_sharded_train_step(encoder: Encoder, mesh: Mesh,
                            lost: tuple[int, ...] = (0,)):
    """The FULL EC 'training step' used by the driver's multi-chip dry run:
    encode -> drop ``lost`` shards -> reconstruct them -> verify they match
    the originals, returning ((B, m, S) parity, scalar mismatch count).

    Exercises the complete device-side math (both matrix applications) plus
    a global psum, all under one jit over the mesh.
    """
    k, m = encoder.data_shards, encoder.parity_shards
    total_n = encoder.total_shards
    parity_coefs = encoder.parity_coefs
    lost = tuple(sorted(lost))
    present = [i for i in range(total_n) if i not in lost]
    rebuild_coefs = encoder.decode_matrix_rows(present, list(lost))

    def step(x):
        parity = bitslice.apply_gf_matrix(parity_coefs, x)
        full = jnp.concatenate([x, parity], axis=1)
        originals = full[:, lost, :]
        survivors = full[:, present[:k], :]
        rebuilt = bitslice.apply_gf_matrix(rebuild_coefs, survivors)
        local_bad = jnp.sum((rebuilt != originals).astype(jnp.uint32),
                            dtype=jnp.uint32)
        mismatches = jax.lax.psum(local_bad, ("dp", "sp"))
        return parity, mismatches

    mapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=(P("dp", None, "sp"), P()),
    )
    return jax.jit(mapped)


def make_sharded_rebuild_step(encoder: Encoder, mesh: Mesh,
                              present, wanted):
    """jitted survivors (B, k, S) u8 -> ((B, len(wanted), S) rebuilt,
    scalar u32 byte-sum checksum psum-reduced over the mesh).

    The sp axis shards the BYTE RANGE of real shard files: the decode
    matrix application is positionwise over 128-byte groups, so each
    chip rebuilds its slice of the lost shards from its slice of the
    survivors with no communication — the cross-chip part is only the
    integrity psum. ``present`` may be ANY survivor set (uneven mixes
    of data and parity ids; the first k are used), matching how
    ec.rebuild reads whichever shards are still alive (SURVEY §3.3)."""
    rows = encoder.decode_matrix_rows(list(present), list(wanted))

    def step(surv):
        rebuilt = bitslice.apply_gf_matrix(rows, surv)
        local = jnp.sum(rebuilt.astype(jnp.uint32), dtype=jnp.uint32)
        return rebuilt, jax.lax.psum(local, ("dp", "sp"))

    mapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=(P("dp", None, "sp"), P()),
    )
    return jax.jit(mapped)


_auto_mesh: "Mesh | None" = None
_auto_encode_steps: dict = {}


def _make_encode_only_step(encoder: Encoder, mesh: Mesh):
    """Checksum-free encode for the production batcher: the integrity
    psum belongs to the verify-style steps, not to every data batch —
    paying a full-parity reduction plus a both-axes collective per
    batch would be wasted ICI traffic. On an accelerator the per-shard
    math is the fused Pallas kernel; elsewhere the XLA network."""
    from ..ops import rs_jax, rs_pallas
    coefs = encoder.parity_coefs
    if rs_jax._use_pallas():
        def step(x):
            return rs_pallas.apply_gf_matrix(coefs, x)
    else:
        def step(x):
            return bitslice.apply_gf_matrix(coefs, x)
    mapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=P("dp", None, "sp"),
    )
    return jax.jit(mapped)


def _granule(sp: int) -> int:
    """Per-shard S granule for the auto-sharded encode: the Pallas
    kernel needs SEG_BYTES per device shard; the XLA network only the
    packing group."""
    from ..ops import rs_jax, rs_pallas
    return sp * (rs_pallas.SEG_BYTES if rs_jax._use_pallas() else GROUP)


def encode_parity_host_sharded(encoder: Encoder, batch: np.ndarray):
    """Production multi-chip encode: HOST (B, k, S) u8 -> async device
    (B, m, S) parity (np.asarray materializes it — callers in the
    3-stage pipeline keep their D2H on the writer thread), computed
    over a (dp, sp) mesh spanning ALL local devices.

    The batch is padded on the row axis to the dp multiple (zero rows
    encode to zero parity and are sliced off lazily) and on S to the
    kernel granule, then sharded (dp, -, sp) — stripe parallelism
    needs no communication. This is the entry the coalescing batcher
    uses when more than one device exists (the single-chip tunnel env
    never takes it; the 8-device CPU mesh in tests and the driver's
    dryrun do)."""
    global _auto_mesh
    if _auto_mesh is None or \
            _auto_mesh.devices.size != len(jax.devices()):
        _auto_mesh = make_mesh()
        _auto_encode_steps.clear()  # steps bake the mesh into shard_map
    mesh = _auto_mesh
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    b, k, s = batch.shape
    gran = _granule(sp)
    b_pad = -(-b // dp) * dp
    s_pad = -(-s // gran) * gran
    if b_pad != b or s_pad != s:
        padded = np.zeros((b_pad, k, s_pad), dtype=np.uint8)
        padded[:b, :, :s] = batch
        batch = padded
    key = (encoder.data_shards, encoder.parity_shards,
           encoder.parity_coefs.tobytes())
    step = _auto_encode_steps.get(key)
    if step is None:
        step = _make_encode_only_step(encoder, mesh)
        _auto_encode_steps[key] = step
    parity = step(shard_batch(batch, mesh))
    return parity[:b, :, :s]  # lazy device slice; no sync here


def shard_batch(x: np.ndarray, mesh: Mesh):
    """Device-put a (B, k, S) batch with (dp, -, sp) sharding; validates
    divisibility (S per chip must stay a multiple of the packing group)."""
    dp = mesh.shape["dp"]
    sp = mesh.shape["sp"]
    b, _, s = x.shape
    if b % dp:
        raise ValueError(f"batch {b} not divisible by dp={dp}")
    if s % (sp * GROUP):
        raise ValueError(
            f"shard length {s} not divisible by sp*{GROUP} = {sp * GROUP}")
    sharding = NamedSharding(mesh, P("dp", None, "sp"))
    return jax.device_put(x, sharding)
