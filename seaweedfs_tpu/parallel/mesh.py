"""Sharded EC steps over a jax.sharding.Mesh.

The reference distributes EC work by placing the 14 shard files on
different servers and moving bytes with gRPC (SURVEY.md §2 "parallelism
strategies" table). The TPU-native equivalent keeps the math on a device
mesh instead:

* ``dp`` (volume/batch axis): independent volumes spread across chips —
  the analog of many volume servers encoding concurrently.
* ``sp`` (stripe axis): one volume's byte range split across chips — the
  analog of the reference striping one .dat over shard servers. The
  bitsliced codec is positionwise over 128-byte groups, so stripe-axis
  sharding needs NO communication for encode; only the global integrity
  checksum crosses chips (one psum over the mesh, riding ICI).

Steps are built with shard_map so the collective structure is explicit
and compiles to XLA collectives; the same code runs on a virtual CPU mesh
(tests, the driver's dry-run) and a real TPU pod slice.
"""

from __future__ import annotations

import collections
import math
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map graduated from jax.experimental in newer releases; prefer
# the top-level API, fall back to the experimental home on older jax.
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops import bitslice
from ..ops.rs_jax import Encoder

GROUP = bitslice.GROUP_BYTES


def _auto_factor(n: int) -> tuple[int, int]:
    """Most-square (dp, sp) with sp >= dp (stripe parallelism is
    communication-free here, so over-sharding it is harmless)."""
    dp = 1
    for f in range(int(math.isqrt(n)), 0, -1):
        if n % f == 0:
            dp = f
            break
    return dp, n // dp


def make_mesh(devices=None, dp: Optional[int] = None,
              sp: Optional[int] = None) -> Mesh:
    """Build a (dp, sp) mesh over the given devices (default: all).

    Without explicit sizes, picks the most-square factorization with the
    stripe axis at least as large as the batch axis (stripe parallelism
    is communication-free here, so over-sharding it is harmless).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if dp is None and sp is None:
        dp, sp = _auto_factor(n)
    elif dp is None:
        if n % sp:
            raise ValueError(f"sp={sp} does not divide device count {n}")
        dp = n // sp
    elif sp is None:
        if n % dp:
            raise ValueError(f"dp={dp} does not divide device count {n}")
        sp = n // dp
    if dp * sp != n:
        raise ValueError(f"dp*sp = {dp}*{sp} != device count {n}")
    dev_array = np.array(devices).reshape(dp, sp)
    return Mesh(dev_array, axis_names=("dp", "sp"))


def make_sharded_encode_step(encoder: Encoder, mesh: Mesh):
    """jitted (B, k, S) u8 -> ((B, m, S) parity, scalar checksum).

    Input sharded (dp, -, sp); parity keeps the same sharding; the
    checksum is the byte-sum of the parity **mod 2^32** (uint32
    accumulation), psum-reduced over BOTH axes so every chip holds the
    global value (the cross-chip integrity handshake a multi-server
    encode does over gRPC in the reference). Host-side verifiers must
    reduce mod 2^32 too.
    """
    coefs = encoder.parity_coefs

    def step(x):
        parity = bitslice.apply_gf_matrix(coefs, x)
        local = jnp.sum(parity.astype(jnp.uint32), dtype=jnp.uint32)
        total = jax.lax.psum(local, ("dp", "sp"))
        return parity, total

    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=(P("dp", None, "sp"), P()),
    )
    return jax.jit(mapped)


def make_sharded_train_step(encoder: Encoder, mesh: Mesh,
                            lost: tuple[int, ...] = (0,)):
    """The FULL EC 'training step' used by the driver's multi-chip dry run:
    encode -> drop ``lost`` shards -> reconstruct them -> verify they match
    the originals, returning ((B, m, S) parity, scalar mismatch count).

    Exercises the complete device-side math (both matrix applications) plus
    a global psum, all under one jit over the mesh.
    """
    k, m = encoder.data_shards, encoder.parity_shards
    total_n = encoder.total_shards
    parity_coefs = encoder.parity_coefs
    lost = tuple(sorted(lost))
    present = [i for i in range(total_n) if i not in lost]
    rebuild_coefs = encoder.decode_matrix_rows(present, list(lost))

    def step(x):
        parity = bitslice.apply_gf_matrix(parity_coefs, x)
        full = jnp.concatenate([x, parity], axis=1)
        originals = full[:, lost, :]
        survivors = full[:, present[:k], :]
        rebuilt = bitslice.apply_gf_matrix(rebuild_coefs, survivors)
        local_bad = jnp.sum((rebuilt != originals).astype(jnp.uint32),
                            dtype=jnp.uint32)
        mismatches = jax.lax.psum(local_bad, ("dp", "sp"))
        return parity, mismatches

    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=(P("dp", None, "sp"), P()),
    )
    return jax.jit(mapped)


def make_sharded_rebuild_step(encoder: Encoder, mesh: Mesh,
                              present, wanted):
    """jitted survivors (B, k, S) u8 -> ((B, len(wanted), S) rebuilt,
    scalar u32 byte-sum checksum psum-reduced over the mesh).

    The sp axis shards the BYTE RANGE of real shard files: the decode
    matrix application is positionwise over 128-byte groups, so each
    chip rebuilds its slice of the lost shards from its slice of the
    survivors with no communication — the cross-chip part is only the
    integrity psum. ``present`` may be ANY survivor set (uneven mixes
    of data and parity ids; the first k are used), matching how
    ec.rebuild reads whichever shards are still alive (SURVEY §3.3)."""
    rows = encoder.decode_matrix_rows(list(present), list(wanted))

    def step(surv):
        rebuilt = bitslice.apply_gf_matrix(rows, surv)
        local = jnp.sum(rebuilt.astype(jnp.uint32), dtype=jnp.uint32)
        return rebuilt, jax.lax.psum(local, ("dp", "sp"))

    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=(P("dp", None, "sp"), P()),
    )
    return jax.jit(mapped)


_auto_meshes: dict = {}       # dp-choice -> Mesh over all devices
_auto_n_devices = 0
#: (mesh shape, coefs shape, coefs bytes) -> jitted step; LRU-bounded —
#: rebuilds mint one decode matrix per loss pattern, and a long-lived
#: repair daemon must not accumulate an executable per pattern forever.
_auto_steps: "collections.OrderedDict" = collections.OrderedDict()
_AUTO_STEPS_CAP = 32


def _make_apply_only_step(coefs: np.ndarray, mesh: Mesh):
    """Checksum-free coefficient-rows application for the production
    paths (encode: parity rows; rebuild: decode rows): the integrity
    psum belongs to the verify-style steps, not to every data batch —
    paying a full reduction plus a both-axes collective per batch would
    be wasted ICI traffic. On an accelerator the per-shard math is the
    fused Pallas kernel; elsewhere the XLA network."""
    from ..ops import rs_pallas
    if _real_accelerator():
        def step(x):
            return rs_pallas.apply_gf_matrix(coefs, x)
    else:
        def step(x):
            return bitslice.apply_gf_matrix(coefs, x)
    mapped = _shard_map(
        step, mesh=mesh,
        in_specs=P("dp", None, "sp"),
        out_specs=P("dp", None, "sp"),
    )
    return jax.jit(mapped)


def _real_accelerator() -> bool:
    """The REAL backend decides kernel + granule (Mosaic only lowers on
    TPU) — deliberately decoupled from rs_jax._use_pallas, which the
    routing gates (and their tests) may override."""
    return jax.default_backend() in ("tpu", "axon")


def _granule(sp: int) -> int:
    """Per-shard S granule for the auto-sharded encode: the Pallas
    kernel needs SEG_BYTES per device shard; the XLA network only the
    packing group. Follows the REAL backend, like the step kernel."""
    from ..ops import rs_pallas
    return sp * (rs_pallas.SEG_BYTES if _real_accelerator() else GROUP)


def _apply_host_sharded(coefs: np.ndarray, batch: np.ndarray):
    """Apply coefficient rows to a HOST (B, n_in, S) u8 batch over a
    mesh spanning ALL local devices; returns an async device
    (B, n_out, S) result (np.asarray materializes it — callers in the
    3-stage pipeline keep their D2H on the writer thread).

    Mesh shape adapts to the batch: small B (the rebuild path streams
    B=1 chunks) takes an sp-only mesh so every device holds a stripe
    slice instead of (dp-1)/dp of them computing zero padding. The
    batch is padded on the row axis to the dp multiple and on S to the
    kernel granule (zero rows/columns map to zero output, sliced off
    lazily), then sharded (dp, -, sp) — stripe parallelism needs no
    communication."""
    global _auto_n_devices
    n_dev = len(jax.devices())
    if _auto_n_devices != n_dev:
        _auto_meshes.clear()
        _auto_steps.clear()  # steps bake their mesh into shard_map
        _auto_n_devices = n_dev
    b, _n_in, s = batch.shape
    dp_auto, _ = _auto_factor(n_dev)
    dp = dp_auto if b >= dp_auto else 1
    mesh = _auto_meshes.get(dp)
    if mesh is None:
        mesh = make_mesh(dp=dp)
        _auto_meshes[dp] = mesh
    sp = mesh.shape["sp"]
    gran = _granule(sp)
    b_pad = -(-b // dp) * dp
    s_pad = -(-s // gran) * gran
    if b_pad != b or s_pad != s:
        padded = np.zeros((b_pad, _n_in, s_pad), dtype=np.uint8)
        padded[:b, :, :s] = batch
        batch = padded
    coefs = np.ascontiguousarray(coefs, dtype=np.uint8)
    key = (dp, sp, coefs.shape, coefs.tobytes())
    step = _auto_steps.get(key)
    if step is None:
        step = _make_apply_only_step(coefs, mesh)
        _auto_steps[key] = step
        while len(_auto_steps) > _AUTO_STEPS_CAP:
            _auto_steps.popitem(last=False)
    else:
        _auto_steps.move_to_end(key)
    out = step(shard_batch(batch, mesh))
    return out[:b, :, :s]  # lazy device slice; no sync here


def encode_parity_host_sharded(encoder: Encoder, batch: np.ndarray):
    """Production multi-chip encode: HOST (B, k, S) u8 -> async
    (B, m, S) parity over all local devices. This is the entry the
    coalescing batcher uses when more than one device exists (the
    single-chip tunnel env never takes it; the 8-device CPU mesh in
    tests and the driver's dryrun do)."""
    return _apply_host_sharded(encoder.parity_coefs, batch)


def reconstruct_host_sharded(encoder: Encoder, survivors: np.ndarray,
                             present, wanted):
    """Production multi-chip rebuild: decode rows for (present ->
    wanted) applied to HOST survivor chunks over the whole mesh — the
    multi-device form of reconstruct_batch_host that the rebuild
    pipeline uses when more than one device exists. ``survivors``:
    (B, len(present), S) u8, first k used."""
    rows = encoder.decode_matrix_rows(list(present), list(wanted))
    chosen = survivors[:, :encoder.data_shards, :]
    if not chosen.flags.c_contiguous:
        chosen = np.ascontiguousarray(chosen)
    return _apply_host_sharded(rows, chosen)


def shard_batch(x: np.ndarray, mesh: Mesh):
    """Device-put a (B, k, S) batch with (dp, -, sp) sharding; validates
    divisibility (S per chip must stay a multiple of the packing group)."""
    dp = mesh.shape["dp"]
    sp = mesh.shape["sp"]
    b, _, s = x.shape
    if b % dp:
        raise ValueError(f"batch {b} not divisible by dp={dp}")
    if s % (sp * GROUP):
        raise ValueError(
            f"shard length {s} not divisible by sp*{GROUP} = {sp * GROUP}")
    sharding = NamedSharding(mesh, P("dp", None, "sp"))
    return jax.device_put(x, sharding)
