"""The replicator: source filer meta-stream -> sink (weed/replication's
Replicator + filer.replicate command role).

Runs an optional bootstrap pass (recursive listing of the source tree,
applied as creates — covers history older than the meta-log window),
then follows ``SubscribeMetadata`` from just before the bootstrap
snapshot so nothing written during the walk is missed; the sink's
mtime/size idempotence absorbs the overlap. Reconnects with backoff on
stream failure, resuming from the last applied event timestamp.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .. import pb
from ..cluster.filer_client import FilerClient
from ..cluster.master import _grpc_port
from ..pb import filer_pb2
from ..util import glog
from .sinks import ReplicationSink
from ..util import tls as tls_mod


class Replicator:
    def __init__(self, source_filer_url: str, sink: ReplicationSink,
                 path_prefix: str = "/",
                 client_name: str = "replicator",
                 bootstrap: bool = True):
        self.source_url = source_filer_url
        self.sink = sink
        self.path_prefix = "/" + path_prefix.strip("/")
        self.client_name = client_name
        self.bootstrap = bootstrap
        self.last_ts_ns = 0
        self.applied = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._channel = None

    # ------------- lifecycle -------------

    def start(self) -> "Replicator":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="filer-replicator")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._channel is not None:
            self._channel.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.sink.close()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------- internals -------------

    def _stub(self) -> pb.Stub:
        import grpc

        if self._channel is None:
            ip, http_port = self.source_url.rsplit(":", 1)
            self._channel = tls_mod.dial(
                f"{ip}:{_grpc_port(int(http_port))}")
        return pb.filer_stub(self._channel)

    #: Clock-skew cushion for the bootstrap/stream seam: events are
    #: stamped by the SOURCE filer's clock, so the resume point backs
    #: off this much; the sink's signature idempotence makes the
    #: resulting over-replay free.
    SKEW_NS = 60 * 1_000_000_000

    def _run(self) -> None:
        need_bootstrap = self.bootstrap
        backoff = 0.2
        while not self._stop.is_set():
            try:
                if need_bootstrap:
                    # Resume point BEFORE the walk (minus skew cushion)
                    # so mutations racing the bootstrap are replayed.
                    self.last_ts_ns = time.time_ns() - self.SKEW_NS
                    self._bootstrap()
                    need_bootstrap = False
                self._follow()
                backoff = 0.2
            except Exception as e:  # noqa: BLE001 — reconnect
                if self._stop.is_set():
                    return
                if "re-sync required" in str(e):
                    # Source says replay cannot converge (meta-log
                    # window expired, or we lagged past the queue
                    # bound) — full re-sync, even for noBootstrap
                    # replicators.
                    glog.warning("replication: %s; re-syncing the "
                                 "tree", e)
                    need_bootstrap = True
                glog.v(1, "replication stream broke: %s", e)
                # the channel may be the casualty — dial fresh next time
                if self._channel is not None:
                    try:
                        self._channel.close()
                    except Exception:  # noqa: BLE001
                        pass
                    self._channel = None
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 5.0)

    def _bootstrap(self) -> None:
        src = FilerClient(self.source_url)
        try:
            stack = [self.path_prefix]
            while stack and not self._stop.is_set():
                d = stack.pop()
                for e in src.list(d):
                    p = (d.rstrip("/") + "/" + e.name)
                    self._apply(p, e)  # per-entry errors never abort
                    if e.is_directory:
                        stack.append(p)
        finally:
            src.close()

    def _apply(self, path: str, new_entry, old_entry=None) -> None:
        try:
            self.sink.apply(path, new_entry, old_entry)
            self.applied += 1
        except Exception as e:  # noqa: BLE001 — one bad entry, not all
            self.errors += 1
            glog.warning("replication: apply %s failed: %s", path, e)

    def _follow(self) -> None:
        # Resume one tick early: the filer's replay filter is strictly
        # ``>``, and two mutations can share a coarse-clock timestamp —
        # an equal-ts event after the last applied one must not be
        # skipped (re-applying the applied one is free via the sink's
        # signature check).
        stream = self._stub().SubscribeMetadata(
            filer_pb2.SubscribeMetadataRequest(
                client_name=self.client_name,
                path_prefix=self.path_prefix,
                since_ns=max(0, self.last_ts_ns - 1)))
        for resp in stream:
            if self._stop.is_set():
                return
            note = resp.event_notification
            new = note.new_entry if note.new_entry.name else None
            old = note.old_entry if note.old_entry.name else None
            name = (new or old).name if (new or old) else ""
            if not name:
                continue
            path = resp.directory.rstrip("/") + "/" + name
            self._apply(path, new, old)
            self.last_ts_ns = max(self.last_ts_ns, resp.ts_ns)


def main(argv: Optional[list[str]] = None) -> int:
    """``python -m seaweedfs_tpu filer.replicate`` — follow one filer
    into another (weed filer.replicate analog)."""
    import argparse

    from .sinks import FilerSink

    p = argparse.ArgumentParser(prog="filer.replicate")
    p.add_argument("-from", dest="src", required=True,
                   help="source filer host:port")
    p.add_argument("-to", dest="dst", required=True,
                   help="destination filer host:port")
    p.add_argument("-path", default="/",
                   help="replicate only this subtree")
    p.add_argument("-noBootstrap", action="store_true",
                   help="skip the initial full-tree sync")
    p.add_argument("-config", default="",
                   help="security.toml ([grpc.tls] client credentials)")
    args = p.parse_args(argv)
    from ..util import config as config_mod
    tls_mod.install_from_config(
        config_mod.load(args.config) if args.config else {})
    rep = Replicator(args.src, FilerSink(args.src, args.dst),
                     path_prefix=args.path,
                     bootstrap=not args.noBootstrap).start()
    glog.info("replicating %s -> %s (prefix %s)", args.src, args.dst,
              args.path)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        rep.stop()
    return 0
