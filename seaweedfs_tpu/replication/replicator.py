"""The replicator: source filer meta-stream -> sink (weed/replication's
Replicator + filer.replicate command role).

Attach-then-walk bootstrap: the ``SubscribeMetadata`` stream is opened
FIRST (live-only — never needs meta-log coverage, so a re-sync always
converges) and its hello marker, stamped by the source's clock under
its log lock, becomes the resume point; only then is the source tree
walked and applied as creates. History is covered by the walk, walk-
concurrent mutations by the already-open stream, and the sink's
mtime/size idempotence absorbs any overlap. Reconnects with backoff on
stream failure, resuming (with meta-log replay) from the last applied
event's source-clock timestamp; if the log window has expired, the
source errors and the follower re-syncs with a fresh attach-then-walk.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .. import pb
from ..cluster.filer_client import FilerClient
from ..cluster.master import _grpc_port
from ..pb import filer_pb2
from ..util import glog
from .sinks import ReplicationSink
from ..util import tls as tls_mod


class _WalkHold:
    """Runs the bootstrap walk on a side thread while the stream
    consumer keeps draining; walk-concurrent events are buffered and
    applied IN ORDER once the walk finishes (by the walker itself,
    under the lock), reproducing the safe walk-then-replay ordering —
    a live delete must not be overtaken by the walk's stale create.

    The buffer is bounded: a walk so long that MAX_BUFFER events land
    during it cannot preserve ordering in memory, so the hold errors
    with a re-sync (same contract as the source's own queue bound).
    A failed walk CANCELS the stream — on a quiet source no further
    event would otherwise arrive to surface the failure, leaving the
    replicator healthy-looking but missing most of the tree."""

    MAX_BUFFER = 10_000

    def __init__(self, rep: "Replicator", walk_fn, cancel_stream=None):
        self._rep = rep
        self._lock = threading.Lock()
        self._buffer: list = []
        self._done = False
        self._overflow = False
        self._err: Optional[BaseException] = None

        def run():
            err: Optional[BaseException] = None
            try:
                walk_fn()
            except BaseException as e:  # noqa: BLE001 — surfaced below
                err = e
            with self._lock:
                self._done = True
                if err is None and self._overflow:
                    err = RuntimeError(
                        "bootstrap event buffer overflow; full "
                        "re-sync required")
                self._err = err
                if err is None:
                    for path, new, old, ts, sigs in self._buffer:
                        rep._apply(path, new, old, sigs)
                        # the watermark advances only on the single
                        # filer-replicator thread; bootstrap hands
                        # off before the live stream starts consuming
                        # seaweedlint: disable=SW801 — single thread
                        rep.last_ts_ns = max(rep.last_ts_ns, ts)
                self._buffer.clear()
            if err is not None:
                glog.warning("replication bootstrap failed: %s", err)
                if cancel_stream is not None:
                    try:
                        cancel_stream()
                    except Exception as ce:  # noqa: BLE001 — best effort
                        glog.v(1, "bootstrap stream cancel failed: %s",
                               ce)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="replicator-bootstrap")
        self._thread.start()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Join the walk — a reconnecting _follow must not start a
        second walk while this one still runs (double applies, and the
        old flush would interleave with the new attach)."""
        self._thread.join(timeout)

    def offer(self, path, new, old, ts_ns, signatures=()) -> bool:
        """Buffer an event if the walk is still running; False once the
        walk (and the buffered flush) completed."""
        with self._lock:
            if not self._done:
                if len(self._buffer) >= self.MAX_BUFFER:
                    self._overflow = True
                else:
                    self._buffer.append((path, new, old, ts_ns,
                                         tuple(signatures)))
                return True
            return False

    def raise_if_failed(self) -> None:
        if self._err is not None:
            raise self._err


class Replicator:
    def __init__(self, source_filer_url: str, sink: ReplicationSink,
                 path_prefix: str = "/",
                 client_name: str = "replicator",
                 bootstrap: bool = True,
                 exclude_signatures: tuple = ()):
        self.source_url = source_filer_url
        self.sink = sink
        self.path_prefix = "/" + path_prefix.strip("/")
        self.client_name = client_name
        self.bootstrap = bootstrap
        #: Events whose chain contains any of these are skipped — a
        #: filer.sync leg passes its TARGET's signature so changes the
        #: other leg applied are not echoed back (the source also
        #: filters server-side; this is the client-side belt).
        self.exclude_signatures = tuple(exclude_signatures)
        #: The source filer's own signature (fetched at dial): the
        #: bootstrap walk stamps applies with it so walk-copied
        #: entries carry a truthful origin chain too.
        self.source_signature = 0
        #: Source-clock resume point: the ts of the newest applied event
        #: or, before any event, the hello stamp adopted at attach (the
        #: source filer's clock under its log lock) — never this host's
        #: clock, so no skew cushion is needed anywhere.
        self.last_ts_ns = 0
        self.applied = 0
        self.errors = 0
        #: Notified after EVERY sink apply (success or error) — tests
        #: and operators wait on this instead of sleep-polling the sink.
        self.applied_cond = threading.Condition()
        #: Set once a subscribe stream is attached (hello received);
        #: events from that instant on are guaranteed delivered/replayed.
        self.attached = threading.Event()
        #: Set once the backup/replication state is walk-complete: at
        #: start for bootstrap=False followers, else when the first
        #: bootstrap walk finishes. Consumers persisting a resume
        #: point must wait for it — a point saved mid-walk would skip
        #: the rest of the tree forever on restart.
        self.bootstrap_done = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._channel = None

    # ------------- lifecycle -------------

    def start(self, wait_attach: float = 10.0) -> "Replicator":
        """Start following. Blocks (up to ``wait_attach`` seconds) until
        the meta stream is attached, so a mutation made after start()
        returns is guaranteed to replicate even without bootstrap — the
        attach barrier is the source's hello stamp, not a clock guess.
        With the source down this times out and the follower keeps
        retrying in the background."""
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="filer-replicator")
        self._thread.start()
        if wait_attach:
            self.attached.wait(wait_attach)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._channel is not None:
            self._channel.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.sink.close()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------- internals -------------

    def _stub(self) -> pb.Stub:
        import grpc

        if self._channel is None:
            ip, http_port = self.source_url.rsplit(":", 1)
            # dialed and torn down only on the single filer-replicator
            # thread
            # seaweedlint: disable=SW802 — single replicator thread
            self._channel = tls_mod.dial(
                f"{ip}:{_grpc_port(int(http_port))}")
        return pb.filer_stub(self._channel)

    def _run(self) -> None:
        need_bootstrap = self.bootstrap
        backoff = 0.2
        while not self._stop.is_set():
            try:
                if not need_bootstrap:
                    self.bootstrap_done.set()
                if need_bootstrap:
                    # Attach the LIVE stream first (never needs log
                    # coverage, so a re-sync always converges), adopt
                    # its hello stamp as the resume point, THEN walk
                    # the tree: history is covered by the walk, walk-
                    # concurrent mutations by the already-open stream.
                    def _walk_done():
                        nonlocal need_bootstrap
                        self._bootstrap()
                        need_bootstrap = False
                        self.bootstrap_done.set()
                    self.last_ts_ns = 0
                    self._follow(on_attach=_walk_done)
                else:
                    self._follow()
                backoff = 0.2
            except Exception as e:  # noqa: BLE001 — reconnect
                if self._stop.is_set():
                    return
                if "re-sync required" in str(e):
                    # Source says replay cannot converge (meta-log
                    # window expired, or we lagged past the queue
                    # bound) — full re-sync, even for noBootstrap
                    # replicators. The walk-complete flag drops with
                    # it: a resume point persisted during the recovery
                    # walk would skip the unwalked remainder forever.
                    glog.warning("replication: %s; re-syncing the "
                                 "tree", e)
                    need_bootstrap = True
                    self.bootstrap_done.clear()
                glog.v(1, "replication stream broke: %s", e)
                # the channel may be the casualty — dial fresh next time
                if self._channel is not None:
                    try:
                        self._channel.close()
                    except Exception as ce:  # noqa: BLE001
                        glog.v(2, "stale channel close failed: %s", ce)
                    # seaweedlint: disable=SW802 — single thread
                    self._channel = None
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 5.0)

    def _bootstrap(self) -> None:
        src = FilerClient(self.source_url)
        origin = (self.source_signature,) if self.source_signature \
            else ()
        try:
            stack = [self.path_prefix]
            while stack and not self._stop.is_set():
                d = stack.pop()
                for e in src.list(d):
                    p = (d.rstrip("/") + "/" + e.name)
                    # per-entry errors never abort
                    self._apply(p, e, signatures=origin)
                    if e.is_directory:
                        stack.append(p)
        finally:
            src.close()

    def _apply(self, path: str, new_entry, old_entry=None,
               signatures: tuple = ()) -> None:
        try:
            self.sink.apply(path, new_entry, old_entry,
                            signatures=signatures)
            with self.applied_cond:
                self.applied += 1
                self.applied_cond.notify_all()
        except Exception as e:  # noqa: BLE001 — one bad entry, not all
            with self.applied_cond:
                self.errors += 1
                self.applied_cond.notify_all()
            glog.warning("replication: apply %s failed: %s", path, e)

    def wait_converged(self, pred, timeout: float = 45.0) -> bool:
        """Event-driven convergence wait: re-check ``pred`` after every
        applied event (waking immediately via applied_cond) until it
        holds or ``timeout`` elapses. Returns whether it held — the
        deadline is a failsafe, not the synchronization mechanism.

        ``pred`` (often slow I/O — a sink lookup) runs OUTSIDE the
        condition lock so the apply path's lock hold stays O(1); the
        counter re-check under the lock closes the missed-notify gap."""
        deadline = time.monotonic() + timeout
        while True:
            with self.applied_cond:
                n = self.applied + self.errors
            if pred():
                return True
            left = deadline - time.monotonic()
            if left <= 0:
                return bool(pred())
            with self.applied_cond:
                self.applied_cond.wait_for(
                    lambda: self.applied + self.errors != n,
                    timeout=min(left, 1.0))

    def _follow(self, on_attach=None) -> None:
        # Resume one tick early: the filer's replay filter is strictly
        # ``>``, and two mutations can share a coarse-clock timestamp —
        # an equal-ts event after the last applied one must not be
        # skipped (re-applying the applied one is free via the sink's
        # signature check). last_ts_ns == 0 means attach live-only and
        # adopt the hello stamp (the source's clock at registration).
        live_only = self.last_ts_ns == 0
        stub = self._stub()
        if not self.source_signature:
            try:
                self.source_signature = stub.GetFilerConfiguration(
                    filer_pb2.GetFilerConfigurationRequest()).signature
            except Exception:  # noqa: BLE001 — older source; walk
                pass           # applies then carry an empty chain
        stream = stub.SubscribeMetadata(
            filer_pb2.SubscribeMetadataRequest(
                client_name=self.client_name,
                path_prefix=self.path_prefix,
                since_ns=0 if live_only else max(0, self.last_ts_ns - 1),
                signatures=list(self.exclude_signatures)))
        hold: Optional[_WalkHold] = None
        try:
            for resp in stream:
                if self._stop.is_set():
                    return
                note = resp.event_notification
                new = note.new_entry if note.new_entry.name else None
                old = note.old_entry if note.old_entry.name else None
                name = (new or old).name if (new or old) else ""
                if not name:
                    # hello marker: stream is attached. Its ts only
                    # becomes the resume point on a live-only attach —
                    # during a replay it is newer than the queued
                    # history and would skip it on the next break.
                    if live_only:
                        self.last_ts_ns = max(self.last_ts_ns,
                                              resp.ts_ns)
                    self.attached.set()  # before any walk: attached
                    # means "stream open", not "bootstrap finished"
                    if on_attach is not None:
                        # walk on a SIDE thread while this loop keeps
                        # draining the stream: a long walk must not let
                        # the source's bounded subscriber queue overflow
                        # (that would force a re-sync of the very walk
                        # in progress — a livelock on big trees under
                        # sustained writes)
                        hold = _WalkHold(self, on_attach,
                                         cancel_stream=stream.cancel)
                        on_attach = None
                    continue
                path = resp.directory.rstrip("/") + "/" + name
                sigs = tuple(note.signatures)
                if self.exclude_signatures and \
                        set(self.exclude_signatures) & set(sigs):
                    # belt to the server-side filter: never apply a
                    # change that already visited the target
                    self.last_ts_ns = max(self.last_ts_ns, resp.ts_ns)
                    continue
                if hold is not None:
                    if hold.offer(path, new, old, resp.ts_ns, sigs):
                        continue  # buffered; applied after the walk
                    hold.raise_if_failed()
                    hold = None
                self._apply(path, new, old, sigs)
                self.last_ts_ns = max(self.last_ts_ns, resp.ts_ns)
        finally:
            # the walk survives a stream break (it rides its own HTTP
            # client); finish it before any reconnect so a second walk
            # can never run concurrently with this one — and surface
            # its failure/overflow even when the stream ended first
            # (the overflow error carries "re-sync required" so _run
            # re-walks instead of resuming over dropped events)
            if hold is not None:
                hold.wait()
                if not self._stop.is_set():
                    hold.raise_if_failed()


def main(argv: Optional[list[str]] = None) -> int:
    """``python -m seaweedfs_tpu filer.replicate`` — follow one filer
    into another (weed filer.replicate analog)."""
    import argparse

    from .sinks import FilerSink

    p = argparse.ArgumentParser(prog="filer.replicate")
    p.add_argument("-from", dest="src", required=True,
                   help="source filer host:port")
    p.add_argument("-to", dest="dst", required=True,
                   help="destination filer host:port")
    p.add_argument("-path", default="/",
                   help="replicate only this subtree")
    p.add_argument("-noBootstrap", action="store_true",
                   help="skip the initial full-tree sync")
    p.add_argument("-config", default="",
                   help="security.toml ([grpc.tls] client credentials)")
    args = p.parse_args(argv)
    from ..util import config as config_mod
    tls_mod.install_from_config(
        config_mod.load(args.config) if args.config else {})
    rep = Replicator(args.src, FilerSink(args.src, args.dst),
                     path_prefix=args.path,
                     bootstrap=not args.noBootstrap).start()
    glog.info("replicating %s -> %s (prefix %s)", args.src, args.dst,
              args.path)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        rep.stop()
    return 0
