"""Bidirectional (active-active) filer synchronization.

The ``weed filer.sync`` analog (reference: weed/command/filer_sync.go):
two :class:`~seaweedfs_tpu.replication.replicator.Replicator` legs, one
per direction, with the reference's signature-chain loop prevention —
every mutation event carries the ids of the filers it has visited
(``EventNotification.signatures``), each leg subscribes excluding its
TARGET's signature, and sinks forward the chain on apply so the target
filer appends itself. A change born on A therefore travels A→B once and
dies at B→A's subscribe filter; same for B-born changes mirrored.

Conflict policy matches the reference's default: last-writer-wins per
path at apply time (each leg simply applies what it sees; there is no
vector-clock merge), which is convergent for the common
distinct-paths/active-standby cases and documented as such.
"""

from __future__ import annotations

import time
from typing import Optional

from ..cluster.filer_client import FilerClient
from ..util import glog
from ..util import tls as tls_mod
from .replicator import Replicator
from .sinks import FilerSink


def _signature_of(filer_url: str) -> int:
    c = FilerClient(filer_url)
    try:
        return c.configuration().signature
    finally:
        c.close()


class FilerSync:
    """Two replicator legs joined by their peers' signatures."""

    def __init__(self, filer_a: str, filer_b: str,
                 path_prefix: str = "/",
                 bootstrap: bool = True):
        self.filer_a = filer_a
        self.filer_b = filer_b
        sig_a = _signature_of(filer_a)
        sig_b = _signature_of(filer_b)
        if sig_a == sig_b:
            raise RuntimeError(
                f"filers {filer_a} and {filer_b} share signature "
                f"{sig_a}; refusing to sync a filer with itself")
        self.a2b = Replicator(
            filer_a, FilerSink(filer_a, filer_b),
            path_prefix=path_prefix, client_name=f"sync->{filer_b}",
            bootstrap=bootstrap, exclude_signatures=(sig_b,))
        self.b2a = Replicator(
            filer_b, FilerSink(filer_b, filer_a),
            path_prefix=path_prefix, client_name=f"sync->{filer_a}",
            bootstrap=bootstrap, exclude_signatures=(sig_a,))
        # One condition serves both legs so wait_converged wakes on
        # applies from EITHER direction (each leg notifies its own
        # applied_cond; aliasing them pre-start makes that one object).
        self.b2a.applied_cond = self.a2b.applied_cond

    def start(self, wait_attach: float = 10.0) -> "FilerSync":
        self.a2b.start(wait_attach=wait_attach)
        self.b2a.start(wait_attach=wait_attach)
        return self

    def stop(self) -> None:
        self.a2b.stop()
        self.b2a.stop()

    def wait_converged(self, pred, timeout: float = 45.0) -> bool:
        """Re-check ``pred`` after applies on EITHER leg (both legs
        notify the shared applied_cond); the deadline is a failsafe,
        not the synchronization mechanism."""
        cond = self.a2b.applied_cond

        def total():
            return (self.a2b.applied + self.a2b.errors
                    + self.b2a.applied + self.b2a.errors)

        deadline = time.monotonic() + timeout
        while True:
            with cond:
                n = total()
            if pred():
                return True
            left = deadline - time.monotonic()
            if left <= 0:
                return bool(pred())
            with cond:
                cond.wait_for(lambda: total() != n,
                              timeout=min(left, 1.0))


def main(argv: Optional[list[str]] = None) -> int:
    """``python -m seaweedfs_tpu filer.sync`` — keep two filers in
    active-active sync."""
    import argparse

    p = argparse.ArgumentParser(prog="filer.sync")
    p.add_argument("-a", required=True, help="first filer host:port")
    p.add_argument("-b", required=True, help="second filer host:port")
    p.add_argument("-path", default="/", help="sync only this subtree")
    p.add_argument("-noBootstrap", action="store_true",
                   help="skip the initial two-way tree walk")
    p.add_argument("-config", default="",
                   help="security.toml ([grpc.tls] client credentials)")
    args = p.parse_args(argv)
    from ..util import config as config_mod
    tls_mod.install_from_config(
        config_mod.load(args.config) if args.config else {})
    sync = FilerSync(args.a, args.b, path_prefix=args.path,
                     bootstrap=not args.noBootstrap).start()
    glog.info("filer.sync: %s <-> %s (prefix %s)", args.a, args.b,
              args.path)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        sync.stop()
    return 0
