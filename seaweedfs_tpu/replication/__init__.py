"""Cross-cluster filer replication (weed/replication analog).

A Replicator subscribes to a source filer's metadata stream
(``SubscribeMetadata``, with since-ns replay through the filer's
meta-log window) and applies each mutation to a sink. The first sink is
another filer (``FilerSink``) — the reference's filer sink — copying
file CONTENT, so the destination owns fresh chunks in its own cluster.
"""

from .replicator import Replicator
from .sinks import FilerSink, ReplicationSink, S3Sink

__all__ = ["FilerSink", "ReplicationSink", "Replicator", "S3Sink"]
