"""Replication sinks (weed/replication/sink analog).

``ReplicationSink`` is the seam the reference fans out to (filer, S3,
GCS, Azure...); ``FilerSink`` is the filer->filer implementation: it
mirrors namespace mutations and copies file content so destination
entries own fresh chunks in the destination cluster — replicating raw
chunk fids would point into the SOURCE cluster's volumes and turn a
source-side vacuum or volume loss into silent remote data loss.
"""

from __future__ import annotations

from typing import Optional

from ..cluster.filer_client import FilerClient, FilerClientError
from ..util import glog, retry


def _as_filer_client(c: "FilerClient | str") -> FilerClient:
    return c if isinstance(c, FilerClient) else FilerClient(c)


def _entry_size(entry) -> int:
    return max(entry.attributes.file_size,
               max((c.offset + c.size for c in entry.chunks),
                   default=0))


def _src_signature(entry) -> bytes:
    """Identity of the SOURCE entry's content: its chunk manifest.
    Chunk fids change on every source write (appends mint new fids), so
    this distinguishes same-size same-second overwrites that an
    (mtime, size) check cannot."""
    sig = ";".join(f"{c.file_id}@{c.offset}+{c.size}"
                   for c in entry.chunks)
    return sig.encode()


class ReplicationSink:
    """One replication target. ``apply`` receives the source path, the
    entry's new state (None = deleted), and the mutation's signature
    chain (the filers it has already visited) — sinks that mutate
    another filer forward the chain so loops die at the subscribe
    filter."""

    def apply(self, path: str, new_entry, old_entry=None,
              signatures: tuple = ()) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FilerSink(ReplicationSink):
    def __init__(self, source: FilerClient | str,
                 destination: FilerClient | str,
                 dst_prefix: str = "/"):
        self.src = _as_filer_client(source)
        self.dst = _as_filer_client(destination)
        self.dst_prefix = "/" + dst_prefix.strip("/")

    def _dst_path(self, path: str) -> str:
        if self.dst_prefix == "/":
            return path
        return self.dst_prefix + path

    def apply(self, path: str, new_entry, old_entry=None,
              signatures: tuple = ()) -> None:
        dst_path = self._dst_path(path)
        if new_entry is None:
            try:
                self.dst.delete_data(dst_path, signatures=signatures)
            except FilerClientError as e:
                glog.v(1, "replication: delete %s: %s", dst_path, e)
            return
        d, _, n = dst_path.rpartition("/")
        if new_entry.is_directory:
            self.dst.mkdir(d or "/", n, signatures=signatures)
            # carry the directory's mode/xattrs like the file path does
            dup = self.dst.lookup(d or "/", n)
            if dup is not None and (new_entry.attributes.file_mode
                                    or new_entry.extended):
                if new_entry.attributes.file_mode:
                    dup.attributes.file_mode = \
                        new_entry.attributes.file_mode
                for k, v in new_entry.extended.items():
                    dup.extended[k] = v
                self.dst.create(d or "/", dup, signatures=signatures)
            return
        size = _entry_size(new_entry)
        # Idempotence: the destination entry remembers which source
        # chunk manifest it was copied from; matching signature = same
        # content, skip (bootstrap + replay overlap is then free).
        sig = _src_signature(new_entry)
        existing = self.dst.lookup(d or "/", n)
        if existing is not None and not existing.is_directory:
            if existing.extended.get("replication.src_sig") == sig:
                return
            # Reverse link: the SOURCE entry is itself a copy of what
            # the destination holds right now (its src_sig names the
            # destination's chunk manifest) — same bytes, skip. This
            # keeps a filer.sync bootstrap walk from re-copying every
            # entry the opposite leg just delivered.
            if new_entry.extended.get("replication.src_sig") == \
                    _src_signature(existing):
                return
        data = self.src.get_data(path) if size else b""
        self.dst.put_data(dst_path, data,
                          mime=new_entry.attributes.mime,
                          signatures=signatures)
        # carry attributes (mode, mtime) + the signature onto the entry
        dup = self.dst.lookup(d or "/", n)
        if dup is not None:
            dup.attributes.file_mode = new_entry.attributes.file_mode
            dup.attributes.mtime = new_entry.attributes.mtime
            for k, v in new_entry.extended.items():
                dup.extended[k] = v
            dup.extended["replication.src_sig"] = sig
            self.dst.create(d or "/", dup, signatures=signatures)

    def close(self) -> None:
        self.src.close()
        self.dst.close()


class S3Sink(ReplicationSink):
    """Replicate filer mutations into an S3 bucket (the reference's
    weed/replication/sink/s3sink role): files become objects keyed by
    their filer path (under ``key_prefix``), deletes remove the object.
    Works against any SigV4 endpoint — including this project's own S3
    gateway. Directories have no S3 analog and are skipped (prefixes
    materialize through object keys)."""

    def __init__(self, source: FilerClient | str, endpoint: str,
                 bucket: str, access_key: str = "",
                 secret_key: str = "", key_prefix: str = "",
                 region: str = "us-east-1"):
        self.src = _as_filer_client(source)
        # honor an explicit scheme; bare host:port defaults to http
        # (the in-cluster gateway case)
        ep = endpoint.rstrip("/")
        if "://" not in ep:
            ep = "http://" + ep
        self.endpoint = ep
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.key_prefix = key_prefix.strip("/")
        self.region = region
        #: path -> last pushed source signature. Absorbs the
        #: replicator's deliberate overlap (bootstrap skew, window
        #: re-sync) within this process — S3 has no cheap server-side
        #: equivalent of the FilerSink's extended-attribute check.
        self._pushed: dict[str, bytes] = {}

    def _url(self, path: str) -> str:
        key = path.lstrip("/")
        if self.key_prefix:
            key = f"{self.key_prefix}/{key}"
        import urllib.parse as up
        return f"{self.endpoint}/{self.bucket}/" + up.quote(key)

    def _request(self, method: str, path: str, body: bytes = b"",
                 mime: str = "") -> None:
        import urllib.error

        url = self._url(path)
        headers = {"Content-Type": mime} if mime else {}
        if self.access_key:
            from ..gateway.s3_auth import sign_request_headers
            headers = sign_request_headers(method, url, headers, body,
                                           self.access_key,
                                           self.secret_key,
                                           region=self.region)
        try:
            retry.http_request(url,
                               data=body if method == "PUT" else None,
                               method=method, headers=headers,
                               point="sink.s3")
        except urllib.error.HTTPError as e:
            if method == "DELETE" and e.code == 404:
                return
            raise FilerClientError(
                f"s3 {method} {url}: {e.code}") from e

    def apply(self, path: str, new_entry, old_entry=None,
              signatures: tuple = ()) -> None:
        # signatures unused: an S3 endpoint emits no meta events, so
        # nothing can loop back through it
        if new_entry is None:
            self._pushed.pop(path, None)
            self._request("DELETE", path)
            return
        if new_entry.is_directory:
            return  # prefixes materialize through object keys
        sig = _src_signature(new_entry)
        if self._pushed.get(path) == sig:
            return  # replay/bootstrap overlap: already pushed
        data = self.src.get_data(path) if _entry_size(new_entry) else b""
        self._request("PUT", path, data,
                      mime=new_entry.attributes.mime)
        self._pushed[path] = sig

    def close(self) -> None:
        self.src.close()
