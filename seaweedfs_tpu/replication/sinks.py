"""Replication sinks (weed/replication/sink analog).

``ReplicationSink`` is the seam the reference fans out to (filer, S3,
GCS, Azure...); ``FilerSink`` is the filer->filer implementation: it
mirrors namespace mutations and copies file content so destination
entries own fresh chunks in the destination cluster — replicating raw
chunk fids would point into the SOURCE cluster's volumes and turn a
source-side vacuum or volume loss into silent remote data loss.
"""

from __future__ import annotations

from typing import Optional

from ..cluster.filer_client import FilerClient, FilerClientError
from ..util import glog


class ReplicationSink:
    """One replication target. ``apply`` receives the source path and
    the entry's new state (None = deleted)."""

    def apply(self, path: str, new_entry, old_entry=None) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FilerSink(ReplicationSink):
    def __init__(self, source: FilerClient | str,
                 destination: FilerClient | str,
                 dst_prefix: str = "/"):
        self.src = source if isinstance(source, FilerClient) \
            else FilerClient(source)
        self.dst = destination if isinstance(destination, FilerClient) \
            else FilerClient(destination)
        self.dst_prefix = "/" + dst_prefix.strip("/")

    def _dst_path(self, path: str) -> str:
        if self.dst_prefix == "/":
            return path
        return self.dst_prefix + path

    @staticmethod
    def _src_signature(entry) -> bytes:
        """Identity of the SOURCE entry's content: its chunk manifest.
        Chunk fids change on every source write (appends mint new fids),
        so this distinguishes same-size same-second overwrites that an
        (mtime, size) check cannot."""
        sig = ";".join(f"{c.file_id}@{c.offset}+{c.size}"
                       for c in entry.chunks)
        return sig.encode()

    def apply(self, path: str, new_entry, old_entry=None) -> None:
        dst_path = self._dst_path(path)
        if new_entry is None:
            try:
                self.dst.delete_data(dst_path)
            except FilerClientError as e:
                glog.v(1, "replication: delete %s: %s", dst_path, e)
            return
        d, _, n = dst_path.rpartition("/")
        if new_entry.is_directory:
            self.dst.mkdir(d or "/", n)
            # carry the directory's mode/xattrs like the file path does
            dup = self.dst.lookup(d or "/", n)
            if dup is not None and (new_entry.attributes.file_mode
                                    or new_entry.extended):
                if new_entry.attributes.file_mode:
                    dup.attributes.file_mode = \
                        new_entry.attributes.file_mode
                for k, v in new_entry.extended.items():
                    dup.extended[k] = v
                self.dst.create(d or "/", dup)
            return
        size = max(new_entry.attributes.file_size,
                   max((c.offset + c.size for c in new_entry.chunks),
                       default=0))
        # Idempotence: the destination entry remembers which source
        # chunk manifest it was copied from; matching signature = same
        # content, skip (bootstrap + replay overlap is then free).
        sig = self._src_signature(new_entry)
        existing = self.dst.lookup(d or "/", n)
        if existing is not None and not existing.is_directory and \
                existing.extended.get("replication.src_sig") == sig:
            return
        data = self.src.get_data(path) if size else b""
        self.dst.put_data(dst_path, data,
                          mime=new_entry.attributes.mime)
        # carry attributes (mode, mtime) + the signature onto the entry
        dup = self.dst.lookup(d or "/", n)
        if dup is not None:
            dup.attributes.file_mode = new_entry.attributes.file_mode
            dup.attributes.mtime = new_entry.attributes.mtime
            for k, v in new_entry.extended.items():
                dup.extended[k] = v
            dup.extended["replication.src_sig"] = sig
            self.dst.create(d or "/", dup)

    def close(self) -> None:
        self.src.close()
        self.dst.close()
