"""Continuous filer metadata backup into a local store.

The ``weed filer.meta.backup`` analog (reference:
weed/command/filer_meta_backup.go): follow a filer's metadata stream
into a local sqlite store — a full tree walk first, then live events,
with the resume point persisted in the store so a restarted backup
continues where it left off (an expired meta-log window triggers the
replicator's built-in full re-walk). ``--restore`` replays the store
into a filer: metadata only, like ``fs.meta.load`` — chunk manifests
are preserved, blob data must still exist on the volume servers.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..cluster.filer_client import FilerClient
from ..filer.entry import normalize_path, split_path
from ..filer.stores import SqliteStore
from ..util import glog
from ..util import tls as tls_mod
from .replicator import Replicator
from .sinks import ReplicationSink

#: kv key holding the source-clock resume point (per path prefix —
#: a db reused with a different -path must re-walk the new subtree).
def _ts_key(prefix: str) -> str:
    return f"meta_backup.since_ns:{prefix}"


#: kv key holding the source filer's process epoch: a mismatch means
#: the in-memory meta-log restarted and a gap-free resume is
#: impossible — re-walk instead of silently skipping the gap.
def _epoch_key(prefix: str) -> str:
    return f"meta_backup.source_epoch:{prefix}"


class MetaBackupSink(ReplicationSink):
    """Applies metadata events to a local :class:`SqliteStore`."""

    def __init__(self, store: SqliteStore):
        self.store = store

    def apply(self, path: str, new_entry, old_entry=None,
              signatures: tuple = ()) -> None:
        from ..cluster.filer_server import pb_to_entry

        path = normalize_path(path)
        if new_entry is None:
            try:
                self.store.delete_entry(path)
            except KeyError:
                pass
            return
        d, _name = split_path(path)
        entry = pb_to_entry(d, new_entry)
        # parents must exist for listings of the backup to make sense
        self.store.ensure_parents(path)
        if self.store.find_entry(path) is None:
            self.store.insert_entry(entry)
        else:
            self.store.update_entry(entry)

    def close(self) -> None:
        self.store.close()


class MetaBackup:
    """A Replicator wired to a MetaBackupSink, with the resume point
    persisted through the store's kv seam."""

    def __init__(self, filer_url: str, db_path: str,
                 path_prefix: str = "/"):
        self.store = SqliteStore(db_path)
        self.prefix = "/" + path_prefix.strip("/")
        resume = self.store.kv_get(_ts_key(self.prefix))
        since_ns = int(resume.decode()) if resume else 0
        # a source restart wipes its in-memory meta-log: the persisted
        # resume point cannot be gap-free, so force a full re-walk
        saved_epoch = self.store.kv_get(_epoch_key(self.prefix))
        self.source_epoch = self._source_epoch(filer_url)
        if since_ns and (saved_epoch is None or
                         saved_epoch.decode() !=
                         str(self.source_epoch)):
            glog.info("meta.backup: source filer restarted (epoch "
                      "changed); re-walking the tree")
            since_ns = 0
        self.rep = Replicator(
            filer_url, MetaBackupSink(self.store),
            path_prefix=self.prefix, client_name="meta-backup",
            bootstrap=since_ns == 0)
        if since_ns:
            self.rep.last_ts_ns = since_ns
        self._stop = threading.Event()
        self._persister: Optional[threading.Thread] = None

    @staticmethod
    def _source_epoch(filer_url: str) -> int:
        """The source's process epoch. An UNREACHABLE source raises
        (after retries) rather than returning a fake epoch: a 0 here
        would both force a spurious full re-walk now and poison the
        stored epoch into forcing another on the next restart. A
        pre-started_ns source genuinely returns 0 (proto default) —
        that stays consistent across restarts, so no churn."""
        last: Exception | None = None
        for attempt in range(3):
            c = FilerClient(filer_url)
            try:
                return c.configuration().started_ns
            except Exception as e:  # noqa: BLE001 — retry below
                last = e
                if attempt < 2:
                    time.sleep(0.5)
            finally:
                c.close()
        raise RuntimeError(
            f"filer {filer_url} unreachable while reading its epoch: "
            f"{last}")

    def _persist_loop(self) -> None:
        last = 0
        while not self._stop.wait(1.0):
            if not self.rep.bootstrap_done.is_set():
                # a resume point saved mid-walk would permanently skip
                # the unwalked rest of the tree on restart
                continue
            ts = self.rep.last_ts_ns
            if ts != last:
                self.store.kv_put(_ts_key(self.prefix),
                                  str(ts).encode())
                self.store.kv_put(_epoch_key(self.prefix),
                                  str(self.source_epoch).encode())
                last = ts

    def start(self, wait_attach: float = 10.0) -> "MetaBackup":
        self.rep.start(wait_attach=wait_attach)
        self._persister = threading.Thread(
            target=self._persist_loop, daemon=True,
            name="meta-backup-ts")
        self._persister.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._persister is not None:
            self._persister.join(timeout=3)
        if self.rep.bootstrap_done.is_set():
            ts = self.rep.last_ts_ns
            if ts:
                self.store.kv_put(_ts_key(self.prefix),
                                  str(ts).encode())
                self.store.kv_put(_epoch_key(self.prefix),
                                  str(self.source_epoch).encode())
        self.rep.stop()  # closes the sink (and with it the store)

    def wait_converged(self, pred, timeout: float = 45.0) -> bool:
        return self.rep.wait_converged(pred, timeout=timeout)


def restore(db_path: str, filer_url: str,
            path_prefix: str = "/") -> int:
    """Replay a backup store into a filer (metadata only); returns the
    number of entries created."""
    from ..cluster.filer_server import entry_to_pb

    store = SqliteStore(db_path)
    fc = FilerClient(filer_url)
    n = 0
    try:
        stack = [normalize_path(path_prefix)]
        while stack:
            d = stack.pop()
            for e in store.list_entries(d):
                # directories restore through create too: mkdir would
                # discard their backed-up mode/owners/xattrs
                fc.create(d, entry_to_pb(e))
                if e.is_dir:
                    stack.append(e.path)
                n += 1
    finally:
        fc.close()
        store.close()
    return n


def main(argv: Optional[list[str]] = None) -> int:
    """``python -m seaweedfs_tpu filer.meta.backup``."""
    import argparse

    p = argparse.ArgumentParser(prog="filer.meta.backup")
    p.add_argument("-filer", required=True, help="filer host:port")
    p.add_argument("-db", required=True,
                   help="local sqlite backup file")
    p.add_argument("-path", default="/", help="subtree to back up")
    p.add_argument("-restore", action="store_true",
                   help="replay the backup INTO the filer and exit")
    p.add_argument("-config", default="",
                   help="security.toml ([grpc.tls] client credentials)")
    args = p.parse_args(argv)
    from ..util import config as config_mod
    tls_mod.install_from_config(
        config_mod.load(args.config) if args.config else {})
    if args.restore:
        n = restore(args.db, args.filer, path_prefix=args.path)
        print(f"filer.meta.backup: restored {n} entries to "
              f"{args.filer}")
        return 0
    mb = MetaBackup(args.filer, args.db, path_prefix=args.path).start()
    glog.info("filer.meta.backup: %s -> %s (prefix %s)", args.filer,
              args.db, args.path)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        mb.stop()
    return 0
