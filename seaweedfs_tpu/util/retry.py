"""Unified resilience policy layer: retry + deadline + circuit breaker.

Every HTTP call the cluster clients make (``cluster/operation.py``,
``cluster/wdclient.py``, ``cluster/filer_client.py``, the replication
sinks, the volume server's replica fan-out) goes through
:func:`http_request` instead of a bare ``urllib.request.urlopen``:

* **Retry** — capped exponential backoff with full jitter, but only for
  errors :func:`retryable` classifies as transient (connection faults,
  timeouts, 5xx/429, injected :class:`~.faults.FaultError`). A 4xx is
  the server speaking clearly and is raised immediately.
* **Deadline budgets** — each request runs under a
  :class:`Deadline`. An ingress handler that received an
  ``X-Seaweed-Deadline`` header (sent alongside ``X-Seaweed-Trace``)
  adopts the caller's remaining budget via :func:`deadline_scope`, so a
  client's 60s budget bounds the filer's downstream volume reads too —
  retries never outlive the caller's patience.
* **Circuit breaker** — per-endpoint (host:port) failure tracking:
  after ``breaker_threshold`` consecutive failures the breaker opens
  and calls fail fast with :class:`BreakerOpenError` (a ``URLError``,
  so replica-failover loops treat it as one more dead replica) until a
  half-open probe succeeds after ``breaker_cooldown`` seconds. State
  surfaces in :data:`METRICS` and every server's ``/debug/vars``.

Fault points (:mod:`seaweedfs_tpu.util.faults`) are compiled in: the
armed point fires before the wire call and its data actions mangle the
response body, so injected chaos exercises exactly this machinery.

The module also owns the ``seaweed_degraded_reads_total`` counter —
each hop of the graceful read-degradation ladder (replica -> replica ->
EC decode) calls :func:`record_degraded`.

Config lives in a ``[retry]`` TOML block (see ``config.SCAFFOLDS``).
"""

from __future__ import annotations

import http.client
import io
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from . import faults, stats, tracing

DEADLINE_HEADER = "X-Seaweed-Deadline"

#: Resilience metrics (``seaweed_retries_total``,
#: ``seaweed_degraded_reads_total``, ``seaweed_breaker_state`` ...).
#: Servers append ``METRICS.render()`` to their ``/metrics`` output.
METRICS = stats.Metrics(namespace="seaweed")

#: HTTP statuses worth retrying: the server (or an LB in front of it)
#: says "not right now", not "never".
RETRYABLE_STATUSES = frozenset((429, 500, 502, 503, 504))


class DeadlineExceeded(TimeoutError):
    pass


class Deadline:
    """A monotonic spend-down budget for one logical request."""

    __slots__ = ("budget", "_until")

    def __init__(self, budget_seconds: float):
        self.budget = float(budget_seconds)
        self._until = time.monotonic() + self.budget

    def remaining(self) -> float:
        return self._until - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def header_value(self) -> str:
        return f"{max(0.0, self.remaining()):.3f}"


class RetryPolicy:
    """Backoff shape + attempt/time budgets. ``backoff(attempt)`` is
    full-jitter: uniform in [0, min(max_delay, base * 2^attempt)] —
    the AWS-style spread that keeps retry storms from synchronizing."""

    __slots__ = ("max_attempts", "base_delay", "max_delay", "timeout",
                 "failover_budget", "breaker_threshold",
                 "breaker_cooldown")

    def __init__(self, max_attempts: int = 4, base_delay: float = 0.05,
                 max_delay: float = 2.0, timeout: float = 60.0,
                 failover_budget: float = 5.0,
                 breaker_threshold: int = 5,
                 breaker_cooldown: float = 5.0):
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        #: Default per-request deadline budget (seconds) when no
        #: ambient deadline is active — the config-driven replacement
        #: for the old hardcoded ``urlopen(timeout=60)`` literals.
        self.timeout = timeout
        #: Cap on master leader-failover loops waiting out an election.
        self.failover_budget = failover_budget
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown

    def backoff(self, attempt: int, rng=random) -> float:
        return rng.uniform(
            0, min(self.max_delay, self.base_delay * (2 ** attempt)))


_POLICY = RetryPolicy()


def policy() -> RetryPolicy:
    return _POLICY


def configure(**kw) -> None:
    """Override individual :class:`RetryPolicy` fields at runtime."""
    for k, v in kw.items():
        if v is None:
            continue
        if not hasattr(_POLICY, k):
            raise AttributeError(f"no retry policy field {k!r}")
        setattr(_POLICY, k, v)


def configure_from(conf: dict) -> None:
    """Apply a loaded TOML dict's ``[retry]`` block (missing keys keep
    their current values)."""
    from . import config as config_mod
    configure(
        max_attempts=config_mod.lookup(conf, "retry.max_attempts"),
        base_delay=config_mod.lookup(conf, "retry.base_delay_seconds"),
        max_delay=config_mod.lookup(conf, "retry.max_delay_seconds"),
        timeout=config_mod.lookup(conf, "retry.request_timeout_seconds"),
        failover_budget=config_mod.lookup(
            conf, "retry.failover_budget_seconds"),
        breaker_threshold=config_mod.lookup(
            conf, "retry.breaker.failure_threshold"),
        breaker_cooldown=config_mod.lookup(
            conf, "retry.breaker.cooldown_seconds"))
    v = config_mod.lookup(conf, "retry.pool.max_idle_per_host")
    if v is not None:
        _POOL.max_idle_per_host = int(v)
    v = config_mod.lookup(conf, "retry.pool.idle_seconds")
    if v is not None:
        _POOL.idle_seconds = float(v)


# --------------------------------------------------------------------------
# deadline propagation
# --------------------------------------------------------------------------

_STATE = threading.local()


def current_deadline() -> Optional[Deadline]:
    st = getattr(_STATE, "deadlines", None)
    return st[-1] if st else None


class _DeadlineScope:
    """Context manager pushing a deadline for this thread; ``None``
    budgets are a no-op so ingress handlers can pass whatever the
    header parse produced without branching."""

    __slots__ = ("_dl",)

    def __init__(self, dl: Optional[Deadline]):
        self._dl = dl

    def __enter__(self) -> Optional[Deadline]:
        if self._dl is not None:
            st = getattr(_STATE, "deadlines", None)
            if st is None:
                st = _STATE.deadlines = []
            st.append(self._dl)
        return self._dl

    def __exit__(self, *exc) -> bool:
        if self._dl is not None:
            st = _STATE.deadlines
            if st and st[-1] is self._dl:
                st.pop()
        return False


def deadline_scope(budget) -> _DeadlineScope:
    """``budget`` is seconds, a :class:`Deadline`, or None (no-op)."""
    if budget is None or isinstance(budget, Deadline):
        return _DeadlineScope(budget)
    return _DeadlineScope(Deadline(float(budget)))


def deadline_from_headers(headers) -> Optional[Deadline]:
    """Adopt the caller's remaining budget from ``X-Seaweed-Deadline``
    (a relative seconds value — absolute stamps would need synchronized
    clocks). Returns None when absent/garbled."""
    val = headers.get(DEADLINE_HEADER) if headers is not None else None
    if not val:
        return None
    try:
        return Deadline(max(0.0, float(val)))
    except (TypeError, ValueError):
        return None


def inject(headers: dict, deadline: Optional[Deadline] = None) -> dict:
    """Stamp trace context + remaining deadline onto outgoing headers."""
    tracing.inject(headers)
    dl = deadline or current_deadline()
    if dl is not None:
        headers[DEADLINE_HEADER] = dl.header_value()
    return headers


# --------------------------------------------------------------------------
# error classification
# --------------------------------------------------------------------------

def retryable(exc: BaseException) -> bool:
    """Is this error worth another attempt? HTTP 4xx means the request
    itself is wrong — never retried; everything that smells like a
    transport or server-side transient is."""
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in RETRYABLE_STATUSES
    return isinstance(exc, (urllib.error.URLError, ConnectionError,
                            TimeoutError, faults.FaultError, OSError))


# --------------------------------------------------------------------------
# circuit breaker
# --------------------------------------------------------------------------

class BreakerOpenError(urllib.error.URLError):
    """Raised instead of dialing while a breaker is open. A URLError,
    so replica-failover loops skip to the next location."""

    def __init__(self, endpoint: str):
        super().__init__(f"circuit breaker open for {endpoint}")
        self.endpoint = endpoint


class CircuitBreaker:
    """Consecutive-failure breaker with a half-open probe.

    closed -> (threshold consecutive failures) -> open
    open   -> (cooldown elapses) -> half-open: ONE probe call allowed
    half-open -> success -> closed | failure -> open (timer resets)
    """

    __slots__ = ("key", "threshold", "cooldown", "failures", "state",
                 "opened_at", "open_count", "_probing", "_lock")

    def __init__(self, key: str, threshold: Optional[int] = None,
                 cooldown: Optional[float] = None):
        self.key = key
        self.threshold = threshold if threshold is not None \
            else _POLICY.breaker_threshold
        self.cooldown = cooldown if cooldown is not None \
            else _POLICY.breaker_cooldown
        self.failures = 0
        self.state = "closed"
        self.opened_at = 0.0
        self.open_count = 0
        self._probing = False
        self._lock = threading.Lock()

    def _gauge(self) -> None:
        # closed=0, half_open=0.5, open=1 — graphable as "how broken"
        val = {"closed": 0.0, "half_open": 0.5, "open": 1.0}[self.state]
        METRICS.gauge("breaker_state", endpoint=self.key).set(val)

    def allow(self) -> bool:
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if time.monotonic() - self.opened_at < self.cooldown:
                    return False
                self.state = "half_open"
                self._probing = True
                self._gauge()
                return True
            # half-open: exactly one in-flight probe
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            self._probing = False
            if self.state != "closed":
                self.state = "closed"
                self._gauge()

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            self._probing = False
            if self.state == "half_open" or (
                    self.state == "closed"
                    and self.failures >= self.threshold):
                self.state = "open"
                self.opened_at = time.monotonic()
                self.open_count += 1
                self._gauge()
                METRICS.counter("breaker_open_total",
                                endpoint=self.key).inc()
            elif self.state == "open":
                self.opened_at = time.monotonic()

    def to_dict(self) -> dict:
        with self._lock:
            return {"endpoint": self.key, "state": self.state,
                    "consecutive_failures": self.failures,
                    "open_count": self.open_count,
                    "threshold": self.threshold,
                    "cooldown_seconds": self.cooldown}


_BREAKERS: dict[str, CircuitBreaker] = {}
_BREAKERS_LOCK = threading.Lock()


def breaker_for(endpoint: str) -> CircuitBreaker:
    brk = _BREAKERS.get(endpoint)
    if brk is None:
        with _BREAKERS_LOCK:
            brk = _BREAKERS.setdefault(endpoint,
                                       CircuitBreaker(endpoint))
    return brk


def breakers_payload() -> list[dict]:
    """The breakers section of ``/debug/vars``."""
    with _BREAKERS_LOCK:
        brks = list(_BREAKERS.values())
    return [b.to_dict() for b in brks]


def reset_breakers() -> None:
    """Forget all breaker state (tests, ``fault.clear -breakers``)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()


# --------------------------------------------------------------------------
# client connection pooling
# --------------------------------------------------------------------------

class _IdleConn:
    __slots__ = ("conn", "last_used")

    def __init__(self, conn):
        self.conn = conn
        self.last_used = time.monotonic()


class ConnectionPool:
    """Persistent ``http.client`` connections keyed by ``host:port``.

    Every intra-cluster hop (gateway -> filer -> master -> volume)
    used to pay a fresh TCP handshake per request because urllib sends
    ``Connection: close``. With the ingress core speaking real
    HTTP/1.1 keep-alive, the client side can finally hold sockets
    open: release() parks a clean connection, acquire() hands it back
    for the next request to the same endpoint. Stale sockets (server
    reaped the idle connection first) surface as an immediate
    RemoteDisconnected and cost one transparent redial, never a
    user-visible failure.
    """

    def __init__(self, max_idle_per_host: int = 4,
                 idle_seconds: float = 30.0):
        self.max_idle_per_host = max_idle_per_host
        self.idle_seconds = idle_seconds
        self._idle: dict[str, list[_IdleConn]] = {}
        self._lock = threading.Lock()

    def acquire(self, netloc: str, timeout: float):
        """-> (connection, reused). The caller owns the connection
        until release()/discard()."""
        now = time.monotonic()
        while True:
            with self._lock:
                stack = self._idle.get(netloc)
                ic = stack.pop() if stack else None
            if ic is None:
                break
            conn = ic.conn
            if now - ic.last_used > self.idle_seconds \
                    or conn.sock is None:
                self.discard(conn)
                continue
            try:
                conn.sock.settimeout(timeout)
            except OSError:
                self.discard(conn)
                continue
            METRICS.counter("pool_reuse_total").inc()
            return conn, True
        host, _, port = netloc.partition(":")
        conn = http.client.HTTPConnection(
            host, int(port) if port else 80, timeout=timeout)
        METRICS.counter("pool_dial_total").inc()
        return conn, False

    def release(self, netloc: str, conn) -> None:
        """Park a connection whose response was fully read."""
        with self._lock:
            stack = self._idle.setdefault(netloc, [])
            if len(stack) < self.max_idle_per_host:
                stack.append(_IdleConn(conn))
                return
        self.discard(conn)

    def discard(self, conn) -> None:
        try:
            conn.close()
        except Exception:  # noqa: BLE001  # seaweedlint: disable=SW301 — discarding a dead connection
            pass

    def clear(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, {}
        for stack in idle.values():
            for ic in stack:
                self.discard(ic.conn)

    def idle_count(self, netloc: Optional[str] = None) -> int:
        with self._lock:
            if netloc is not None:
                return len(self._idle.get(netloc, ()))
            return sum(len(s) for s in self._idle.values())

    def payload(self) -> dict:
        """The ``http_pool`` section of ``/debug/vars``."""
        with self._lock:
            return {"max_idle_per_host": self.max_idle_per_host,
                    "idle_seconds": self.idle_seconds,
                    "idle": {k: len(v) for k, v in self._idle.items()
                             if v}}


_POOL = ConnectionPool()

#: Sockets that died between requests (server closed the idle keep-
#: alive first): retried once on a fresh dial without consuming an
#: attempt or tripping the breaker — the endpoint never saw it.
_STALE_ERRORS = (http.client.RemoteDisconnected,
                 http.client.CannotSendRequest, ConnectionResetError,
                 ConnectionAbortedError, BrokenPipeError)


def pool() -> ConnectionPool:
    return _POOL


def close_pool() -> None:
    """Drop every idle pooled connection (tests, fault drills)."""
    _POOL.clear()


def _pooled_request(url: str, netloc: str, selector: str, method: str,
                    data: Optional[bytes], hdrs: dict,
                    timeout: float, point: str):
    """One wire exchange over a pooled connection. Returns
    ``(status, headers, body)``; raises ``HTTPError`` for >= 400 (body
    attached, connection still reusable — the endpoint answered) and
    wraps transport errors in ``URLError`` so callers' existing
    ``except urllib.error.*`` clauses keep working."""
    stale_redial = False
    while True:
        conn, reused = _POOL.acquire(netloc, timeout)
        try:
            # The armed fault point fires while holding the pooled
            # connection: a ``drop`` kills *this* socket, exactly like
            # a peer reset would, instead of poisoning the pool.
            faults.check(point)
            conn.request(method, selector, body=data, headers=hdrs)
            resp = conn.getresponse()
            body = resp.read()
        except faults.FaultError:
            _POOL.discard(conn)
            raise
        except Exception as e:  # noqa: BLE001 — transport layer
            _POOL.discard(conn)
            if reused and not stale_redial \
                    and isinstance(e, _STALE_ERRORS):
                stale_redial = True
                METRICS.counter("pool_stale_redial_total").inc()
                continue
            if isinstance(e, urllib.error.URLError):
                raise
            raise urllib.error.URLError(e) from e
        mangled = faults.mangle(point, body)
        if mangled is not body:
            # truncate/corrupt actions simulate a wire cut mid-body;
            # a connection that "lost" bytes must not serve the next
            # pipelined request.
            _POOL.discard(conn)
            body = mangled
        elif resp.will_close:
            _POOL.discard(conn)
        else:
            _POOL.release(netloc, conn)
        if resp.status >= 400:
            raise urllib.error.HTTPError(
                url, resp.status, resp.reason, resp.headers,
                io.BytesIO(body))
        return resp.status, resp.headers, body


# --------------------------------------------------------------------------
# degraded-read accounting
# --------------------------------------------------------------------------

def record_degraded(stage: str) -> None:
    """Count one hop of the read-degradation ladder
    (``seaweed_degraded_reads_total{stage=...}``) and tag the active
    span so the hop shows up in the request's trace."""
    METRICS.counter("degraded_reads_total", stage=stage).inc()
    sp = tracing.current_span()
    if sp is not None:
        sp.tag(degraded=stage)


# --------------------------------------------------------------------------
# the HTTP call everyone makes
# --------------------------------------------------------------------------

class HttpResponse:
    __slots__ = ("status", "headers", "data")

    def __init__(self, status: int, headers, data: bytes):
        self.status = status
        self.headers = headers
        self.data = data


def http_request(url: str, data: Optional[bytes] = None,
                 method: Optional[str] = None,
                 headers: Optional[dict] = None, *,
                 point: str = "", jwt: str = "",
                 timeout: Optional[float] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 use_breaker: bool = True) -> HttpResponse:
    """One resilient HTTP request.

    Runs under the thread's ambient :class:`Deadline` when one is
    active (ingress-adopted budgets bound the whole downstream fan-out)
    or a fresh one of ``timeout`` / the policy's default budget.
    Transient failures retry with full-jitter backoff while attempts
    and budget remain; the endpoint's circuit breaker fails fast when
    it is open. Non-retryable ``HTTPError`` raises immediately
    (and counts as breaker *success* — the endpoint answered).
    On exhaustion the last underlying error is re-raised, so callers'
    existing ``except urllib.error.*`` clauses keep working.
    """
    pol = retry_policy or _POLICY
    dl = current_deadline()
    if dl is None:
        dl = Deadline(pol.timeout if timeout is None else timeout)
    brk = breaker_for(urllib.parse.urlsplit(url).netloc) \
        if use_breaker else None
    label = point or "other"
    last: Optional[BaseException] = None
    attempt = 0
    while True:
        if brk is not None and not brk.allow():
            METRICS.counter("breaker_rejected_total",
                            point=label).inc()
            raise BreakerOpenError(brk.key) from last
        try:
            hdrs = dict(headers) if headers else {}
            inject(hdrs, dl)
            if jwt:
                hdrs["Authorization"] = f"BEARER {jwt}"
            att_timeout = min(pol.timeout if timeout is None
                              else timeout, dl.remaining())
            if att_timeout <= 0:
                raise DeadlineExceeded(
                    f"deadline exhausted before attempt {attempt + 1} "
                    f"of {method or 'GET'} {url}")
            parts = urllib.parse.urlsplit(url)
            if parts.scheme == "http":
                selector = parts.path or "/"
                if parts.query:
                    selector += "?" + parts.query
                status, resp_headers, body = _pooled_request(
                    url, parts.netloc, selector,
                    method or ("POST" if data is not None else "GET"),
                    data, hdrs, att_timeout, point)
            else:
                faults.check(point)
                req = urllib.request.Request(
                    url, data=data, method=method, headers=hdrs)
                with urllib.request.urlopen(
                        req, timeout=att_timeout) as r:
                    body = r.read()
                    status = r.status
                    resp_headers = r.headers
                body = faults.mangle(point, body)
            if brk is not None:
                brk.record_success()
            return HttpResponse(status, resp_headers, body)
        except DeadlineExceeded:
            if last is not None:
                raise last
            raise
        except Exception as e:  # noqa: BLE001 — classified below
            if not retryable(e):
                if brk is not None and isinstance(
                        e, urllib.error.HTTPError):
                    brk.record_success()  # endpoint alive, spoke HTTP
                raise
            if brk is not None:
                brk.record_failure()
            METRICS.counter("request_failures_total", point=label).inc()
            last = e
            attempt += 1
            if attempt >= pol.max_attempts:
                break
            delay = pol.backoff(attempt)
            if dl.remaining() <= delay:
                break
            METRICS.counter("retries_total", point=label).inc()
            time.sleep(delay)
    assert last is not None
    raise last
