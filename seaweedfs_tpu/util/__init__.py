"""Cross-cutting utilities: leveled logging, JWT auth, metrics, config.

Mirrors the reference's weed/util + weed/security + weed/stats cluster
(SURVEY.md §2 "Security", "Stats", "Util"): glog-style verbosity-leveled
logging, HMAC-signed write tokens, Prometheus-text metrics, and a
flags > TOML > defaults configuration loader.
"""
