"""Dapper-style end-to-end request tracing.

Every request entering the system (S3/WebDAV verb, FUSE op, shell
command) opens a *trace*: a tree of spans identified by
``trace_id / span_id / parent_id``. The context crosses process hops in
an ``X-Seaweed-Trace`` HTTP header and the ``x-seaweed-trace`` gRPC
metadata key, so one S3 GET leaves spans on the gateway, the filer, the
master, and the volume server, each recording wall time, bytes moved,
and outcome.

Per-process state is deliberately simple — every server in this
codebase handles one request per thread (ThreadingHTTPServer and the
gRPC ThreadPoolExecutor), so the active span stack is a
``threading.local`` and needs no locks. Completed traces land in a
bounded ring buffer served as JSON from each server's ``/debug/traces``
endpoint and summarized by the ``trace.status`` / ``trace.dump`` shell
commands; stage latencies feed the ``trace_request_stage_seconds``
histogram family in :data:`METRICS`. Traces slower than the configured
threshold emit a one-line span-tree summary through ``glog``.

Config lives in a ``[tracing]`` TOML block (see ``config.SCAFFOLDS``):
``enabled``, ``ring_size``, ``slow_threshold_seconds``.
"""

from __future__ import annotations

import functools
import json
import os
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Optional, Union

from . import glog, stats

TRACE_HEADER = "X-Seaweed-Trace"
GRPC_METADATA_KEY = "x-seaweed-trace"

#: Process-wide stage metrics (``trace_request_stage_seconds{stage=..}``
#: etc.). Servers append ``METRICS.render()`` to their ``/metrics``
#: output so the family is scraped everywhere without merging registries.
METRICS = stats.Metrics(namespace="trace")

_ENABLED = True
_SLOW_THRESHOLD = 1.0
_RING: deque = deque(maxlen=256)
#: Slow-request ring: compact summaries of every trace that crossed
#: the slow threshold, served by each server's ``/debug/vars``. Kept
#: separate from ``_RING`` so slow outliers survive long after the
#: main ring has churned past them.
_SLOW_RING: deque = deque(maxlen=64)

#: HTTP paths never traced — scrapes and debug polls would otherwise
#: flood the ring buffer with single-span traces.
_UNTRACED_PATHS = frozenset(("/metrics", "/status", "/healthz"))
_UNTRACED_PREFIXES = ("/debug/", "/cluster/", "/dir/status", "/raft/")

# -- tail-sampled collection ------------------------------------------------
#: Push target for completed local roots that are slow or errored:
#: either an HTTP URL string ("host:port" of the master — the bundle is
#: POSTed to /cluster/traces through the resilient retry layer) or a
#: callable taking the payload dict (the master ingests its own traces
#: in-process instead of dialing itself). None disables pushing.
_PUSH_TARGET: Union[str, Callable, None] = None
_PUSH_NODE = ""           # this process's advertised host:port
_PUSH_COMPONENT = ""      # master / volume / filer / s3 / webdav
_PUSH_THRESHOLD: Optional[float] = None  # None -> slow threshold
#: Bounded hand-off queue to the push worker; the request thread only
#: appends — a slow or absent master must never block the data path.
_PUSH_QUEUE: deque = deque(maxlen=64)
_PUSH_WAKE = threading.Event()
_PUSH_THREAD: Optional[threading.Thread] = None
_PUSH_STATS = {"pushed": 0, "errors": 0, "dropped": 0}


class Span:
    """One timed stage; ``bytes``/``status``/``tags`` are caller-set."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "start", "end", "n_bytes", "status", "tags")

    def __init__(self, trace_id: str, span_id: str, parent_id: str,
                 name: str, tags: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = time.time()
        self.end = 0.0
        self.n_bytes = 0
        self.status = "ok"
        self.tags = tags

    @property
    def duration(self) -> float:
        return (self.end or time.time()) - self.start

    def tag(self, **kv) -> "Span":
        if self.tags is None:
            self.tags = {}
        self.tags.update({k: str(v) for k, v in kv.items()})
        return self

    def to_dict(self) -> dict:
        d = {"span_id": self.span_id, "parent_id": self.parent_id,
             "name": self.name, "start": self.start,
             "duration_seconds": round(self.duration, 6),
             "bytes": self.n_bytes, "status": self.status}
        if self.tags:
            d["tags"] = dict(self.tags)
        return d


#: Sink for span mutations made inside disabled/trace-less sections;
#: never read, so concurrent writes are harmless.
_NULL_SPAN = Span("", "", "", "null")


#: Plain C-level ``threading.local`` — NOT a subclass with
#: ``__init__``: subclass locals re-run ``__init__`` under a lock on
#: each new thread's first touch, which every HTTP request pays (one
#: thread per request). Attributes are created lazily in
#: :func:`_stack` instead.
_STATE = threading.local()


def _stack() -> list:
    st = getattr(_STATE, "stack", None)
    if st is None:
        st = []
        _STATE.stack = st
        _STATE.finished = []
    return st


#: Span-id generator. A PRNG seeded from the OS, not os.urandom per id:
#: ids only need uniqueness, and the syscall per span is measurable on
#: the cached-read hot path. getrandbits on the shared instance is a
#: single C call, so it is atomic under the GIL.
_RNG = random.Random(os.urandom(16))


def _new_id() -> str:
    return "%016x" % _RNG.getrandbits(64)


# --------------------------------------------------------------------------
# configuration
# --------------------------------------------------------------------------

def configure(enabled: Optional[bool] = None,
              ring_size: Optional[int] = None,
              slow_threshold_seconds: Optional[float] = None) -> None:
    global _ENABLED, _SLOW_THRESHOLD, _RING
    if enabled is not None:
        _ENABLED = bool(enabled)
    if ring_size is not None and ring_size != _RING.maxlen:
        _RING = deque(_RING, maxlen=max(1, int(ring_size)))
    if slow_threshold_seconds is not None:
        _SLOW_THRESHOLD = float(slow_threshold_seconds)


def configure_from(conf: dict) -> None:
    """Apply a loaded TOML dict's ``[tracing]`` block (missing keys keep
    their current values)."""
    from . import config as config_mod
    configure(
        enabled=config_mod.lookup(conf, "tracing.enabled"),
        ring_size=config_mod.lookup(conf, "tracing.ring_size"),
        slow_threshold_seconds=config_mod.lookup(
            conf, "tracing.slow_threshold_seconds"))
    global _PUSH_THRESHOLD
    thr = config_mod.lookup(conf, "tracing.push_threshold_seconds")
    if thr is not None:
        _PUSH_THRESHOLD = float(thr)
    url = config_mod.lookup(conf, "tracing.collector_url")
    if url:
        configure_push(url)


def configure_push(target: Union[str, Callable, None],
                   node: Optional[str] = None,
                   component: Optional[str] = None,
                   threshold_seconds: Optional[float] = None) -> None:
    """Enable (or disable, with ``target=None``) tail-sampled pushing
    of slow/errored local roots. ``target`` is the master's
    ``host:port`` (POSTed to ``/cluster/traces``) or a callable payload
    sink (the master's own in-process collector)."""
    global _PUSH_TARGET, _PUSH_NODE, _PUSH_COMPONENT, _PUSH_THRESHOLD
    _PUSH_TARGET = target
    if node is not None:
        _PUSH_NODE = node
    if component is not None:
        _PUSH_COMPONENT = component
    if threshold_seconds is not None:
        _PUSH_THRESHOLD = float(threshold_seconds)
    if target is not None:
        _ensure_push_worker()


def push_threshold() -> float:
    return (_PUSH_THRESHOLD if _PUSH_THRESHOLD is not None
            else _SLOW_THRESHOLD)


def _ensure_push_worker() -> None:
    global _PUSH_THREAD
    if _PUSH_THREAD is not None and _PUSH_THREAD.is_alive():
        return
    t = threading.Thread(target=_push_loop, daemon=True,
                         name="trace-push")
    _PUSH_THREAD = t
    t.start()


def _push_loop() -> None:
    while True:
        _PUSH_WAKE.wait()
        _PUSH_WAKE.clear()
        while _PUSH_QUEUE:
            try:
                payload = _PUSH_QUEUE.popleft()
            except IndexError:
                break
            target = _PUSH_TARGET
            if target is None:
                continue
            try:
                if callable(target):
                    target(payload)
                else:
                    from . import retry
                    retry.http_request(
                        f"http://{target}/cluster/traces",
                        data=json.dumps(payload).encode(),
                        method="POST",
                        headers={"Content-Type": "application/json"},
                        point="trace.push", timeout=5.0,
                        use_breaker=False)
                _PUSH_STATS["pushed"] += 1
            except Exception:  # noqa: BLE001 — collection is best-effort
                _PUSH_STATS["errors"] += 1


def _enqueue_push(root: Span, spans: list, reason: str) -> None:
    if len(_PUSH_QUEUE) >= (_PUSH_QUEUE.maxlen or 0):
        _PUSH_STATS["dropped"] += 1
    _PUSH_QUEUE.append({
        "node": _PUSH_NODE,
        "component": _PUSH_COMPONENT,
        "reason": reason,
        "bundle": _bundle(root, spans),
    })
    _PUSH_WAKE.set()


def push_stats() -> dict:
    return dict(_PUSH_STATS,
                queued=len(_PUSH_QUEUE),
                target=(_PUSH_TARGET if isinstance(_PUSH_TARGET, str)
                        else bool(_PUSH_TARGET)))


def enabled() -> bool:
    return _ENABLED


def slow_threshold() -> float:
    return _SLOW_THRESHOLD


def reset() -> None:
    """Drop ring-buffer contents and this thread's state (tests)."""
    _RING.clear()
    _SLOW_RING.clear()
    _STATE.stack = []
    _STATE.finished = []


# --------------------------------------------------------------------------
# context propagation
# --------------------------------------------------------------------------

def current_span() -> Optional[Span]:
    st = _stack()
    return st[-1] if st else None


def active() -> bool:
    """True when this thread is inside a trace — the hot-path guard
    callers use to skip span bookkeeping entirely."""
    if not _ENABLED:
        return False
    try:
        return bool(_STATE.stack)
    except AttributeError:
        return False


def outbound_value() -> Optional[str]:
    """``trace_id-span_id`` for the active span, else None."""
    sp = current_span()
    return f"{sp.trace_id}-{sp.span_id}" if sp is not None else None


def inject(headers: dict) -> dict:
    """Add the trace header to an outgoing HTTP header dict in place."""
    val = outbound_value()
    if val is not None:
        headers[TRACE_HEADER] = val
    return headers


def parse_value(value: Optional[str]) -> tuple[Optional[str], str]:
    """Header/metadata value -> (trace_id, parent_span_id)."""
    if not value:
        return None, ""
    trace_id, sep, parent = value.partition("-")
    if not sep or not trace_id:
        return None, ""
    return trace_id, parent


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------

#: stage name -> (latency histogram, ok/error span counters, bytes
#: counter). The registry lookup rebuilds a sorted label tuple under a
#: lock every call; caching the instruments here keeps the per-span
#: cost to plain attribute work. Plain dict: assignment is atomic and
#: a rare double-create just wins the same registry entry.
_INSTRUMENTS: dict = {}


def _instruments(name: str) -> tuple:
    tup = _INSTRUMENTS.get(name)
    if tup is None:
        tup = (METRICS.histogram("request_stage_seconds", stage=name),
               METRICS.counter("spans_total", stage=name, status="ok"),
               METRICS.counter("spans_total", stage=name,
                               status="error"),
               METRICS.counter("stage_bytes_total", stage=name))
        _INSTRUMENTS[name] = tup
    return tup


def _record(sp: Span) -> None:
    hist, ok, err, nbytes = _instruments(sp.name)
    # The trace id rides the histogram bucket as an exemplar: a scrape
    # showing a fat p99 bucket names the exact trace to pull from
    # /cluster/traces (one slot per bucket, no cardinality growth).
    hist.observe(sp.duration, exemplar=sp.trace_id)
    (ok if sp.status == "ok" else err).inc()
    if sp.n_bytes:
        nbytes.inc(sp.n_bytes)


def _finish(sp: Span, exc: Optional[BaseException]) -> None:
    # Child-span close must stay minimal: it runs BEFORE the response
    # is written (the root's close runs after), so metrics recording
    # and ring bundling are all deferred to the root close below.
    sp.end = time.time()
    if exc is not None and sp.status == "ok":
        sp.status = f"error:{type(exc).__name__}"
    st = _STATE
    if st.stack and st.stack[-1] is sp:
        st.stack.pop()
    st.finished.append(sp)
    if not st.stack:  # local root closed — record + bundle the trace
        spans, st.finished = st.finished, []
        for s in spans:
            _record(s)
        _RING.append((sp, spans))  # dict form built lazily on read
        if sp.duration >= _SLOW_THRESHOLD:
            summary = summarize_spans(spans)
            _SLOW_RING.append({
                "ts": sp.end, "trace_id": sp.trace_id,
                "name": sp.name,
                "duration_seconds": round(sp.duration, 6),
                "status": sp.status, "spans": len(spans),
                "summary": summary,
            })
            glog.warning("slow trace %s %s %.3fs: %s", sp.trace_id,
                         sp.name, sp.duration, summary)
        if _PUSH_TARGET is not None:
            # Tail sampling: only roots that turned out slow or errored
            # leave the process — the head-sampled firehose stays local.
            slow = sp.duration >= push_threshold()
            errored = sp.status != "ok"
            if slow or errored:
                _enqueue_push(sp, spans,
                              "slow" if slow else "error")


class _SpanHandle:
    """Context manager for one span. Hand-rolled (not
    ``@contextmanager``) because the generator machinery costs more
    than the span bookkeeping itself on the cached-read hot path."""

    __slots__ = ("_name", "_tags", "_header", "_root", "_sp")

    def __init__(self, name: str, tags: Optional[dict],
                 header: Optional[str] = None, root: bool = False):
        self._name = name
        self._tags = tags
        self._header = header
        self._root = root
        self._sp = _NULL_SPAN

    def __enter__(self) -> Span:
        st = _stack()
        if not _ENABLED or not (st or self._root):
            return _NULL_SPAN
        tags = self._tags
        if tags:
            tags = {k: str(v) for k, v in tags.items()}
        if st:  # child of the active span (roots degrade too)
            parent = st[-1]
            sp = Span(parent.trace_id, _new_id(), parent.span_id,
                      self._name, tags or None)
        else:  # local trace root, continuing any upstream context
            trace_id, parent_id = parse_value(self._header)
            sp = Span(trace_id or _new_id(), _new_id(), parent_id,
                      self._name, tags or None)
        st.append(sp)
        self._sp = sp
        return sp

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._sp is not _NULL_SPAN:
            _finish(self._sp, exc)
        return False


def span(name: str, **tags) -> _SpanHandle:
    """Child span of the active trace; a cheap no-op outside one."""
    return _SpanHandle(name, tags or None)


def start_trace(name: str, header: Optional[str] = None,
                **tags) -> _SpanHandle:
    """Open a local trace root at an ingress point. ``header`` is the
    upstream ``X-Seaweed-Trace`` value (continues that trace) or None
    (mints a fresh trace id). Nested calls degrade to child spans."""
    return _SpanHandle(name, tags or None, header=header, root=True)


def traced(name: str, **tags):
    """Decorator form of :func:`start_trace` for entry-point methods
    (FUSE ops, shell commands)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not _ENABLED:
                return fn(*a, **kw)
            with start_trace(name, **tags):
                return fn(*a, **kw)
        return wrapper
    return deco


# --------------------------------------------------------------------------
# inspection: ring buffer, /debug/traces payload, summaries
# --------------------------------------------------------------------------

def _bundle(root: Span, spans: list) -> dict:
    return {
        "trace_id": root.trace_id,
        "name": root.name,
        "start": spans[0].start if spans else root.start,
        "duration_seconds": round(root.duration, 6),
        "span_count": len(spans),
        "remote_parent": root.parent_id,
        "status": root.status,
        "spans": [s.to_dict() for s in spans],
    }


def recent_traces(limit: Optional[int] = None) -> list[dict]:
    """Most recent completed traces, newest last."""
    entries = list(_RING)
    if limit is not None and limit >= 0:
        entries = entries[-limit:] if limit else []
    return [_bundle(root, spans) for root, spans in entries]


def slow_requests(limit: Optional[int] = None) -> list[dict]:
    """Most recent slow-trace summaries, newest last (the
    ``/debug/vars`` slow-request ring)."""
    entries = list(_SLOW_RING)
    if limit is not None and limit >= 0:
        entries = entries[-limit:] if limit else []
    return entries


def debug_payload(limit: Optional[int] = None) -> dict:
    """The ``/debug/traces`` JSON body."""
    return {
        "enabled": _ENABLED,
        "ring_size": _RING.maxlen,
        "slow_threshold_seconds": _SLOW_THRESHOLD,
        "count": len(_RING),  # total held, regardless of limit
        "traces": recent_traces(limit),
    }


def summarize_spans(spans: list) -> str:
    """One-line span tree: ``root 1.2s{child 0.9s{leaf 0.1s}}``.
    Accepts Span objects or their ``to_dict()`` form."""
    ds = [s.to_dict() if isinstance(s, Span) else s for s in spans]
    by_parent: dict[str, list[dict]] = {}
    ids = {d["span_id"] for d in ds}
    roots = []
    for d in ds:
        if d["parent_id"] in ids:
            by_parent.setdefault(d["parent_id"], []).append(d)
        else:
            roots.append(d)

    def fmt(d: dict) -> str:
        base = f"{d['name']} {d['duration_seconds']:.3f}s"
        if d.get("bytes"):
            base += f" {d['bytes']}B"
        if d.get("status", "ok") != "ok":
            base += f" !{d['status']}"
        kids = sorted(by_parent.get(d["span_id"], ()),
                      key=lambda k: k["start"])
        if kids:
            base += "{" + ",".join(fmt(k) for k in kids) + "}"
        return base

    return ",".join(fmt(r) for r in sorted(roots,
                                           key=lambda r: r["start"]))


def render_trace(trace: dict) -> str:
    """Multi-line indented span tree for ``trace.dump``."""
    ds = trace.get("spans", [])
    by_parent: dict[str, list[dict]] = {}
    ids = {d["span_id"] for d in ds}
    roots = []
    for d in ds:
        if d["parent_id"] in ids:
            by_parent.setdefault(d["parent_id"], []).append(d)
        else:
            roots.append(d)
    lines = [f"trace {trace['trace_id']} {trace['name']} "
             f"{trace['duration_seconds']:.3f}s "
             f"({trace['span_count']} spans)"]

    def walk(d: dict, depth: int) -> None:
        extra = f" {d['bytes']}B" if d.get("bytes") else ""
        if d.get("status", "ok") != "ok":
            extra += f" !{d['status']}"
        tags = d.get("tags")
        if tags:
            extra += " " + ",".join(f"{k}={v}" for k, v in
                                    sorted(tags.items()))
        lines.append(f"{'  ' * (depth + 1)}{d['name']} "
                     f"{d['duration_seconds']:.3f}s{extra}")
        for k in sorted(by_parent.get(d["span_id"], ()),
                        key=lambda k: k["start"]):
            walk(k, depth + 1)

    for r in sorted(roots, key=lambda r: r["start"]):
        walk(r, 0)
    return "\n".join(lines)


# --------------------------------------------------------------------------
# master-side tail-sampled trace collection
# --------------------------------------------------------------------------

class TraceCollector:
    """Cluster-wide store for tail-sampled traces.

    Every server pushes its slow/errored local roots here (HTTP POST
    ``/cluster/traces``, or a direct call for the master's own traces);
    bundles sharing a trace id are stitched into ONE cross-process
    trace, so ``/cluster/traces`` shows the gateway, filer, master and
    volume legs of a bad request together. Bounded two ways: at most
    ``ring_size`` traces (oldest evicted) and ``max_spans`` spans per
    trace (extra spans counted, not stored). Span-id dedup makes
    re-delivery through the retry layer idempotent.
    """

    MAX_SPANS = 512

    def __init__(self, ring_size: int = 256):
        self.ring_size = max(1, int(ring_size))
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self.ingested = 0
        self.rejected = 0

    def ingest(self, payload: dict) -> None:
        """Fold one pushed ``{node, component, reason, bundle}`` in."""
        bundle = (payload or {}).get("bundle") or {}
        trace_id = bundle.get("trace_id")
        spans = bundle.get("spans") or []
        if not trace_id or not isinstance(spans, list):
            self.rejected += 1
            return
        node = str(payload.get("node") or "")
        component = str(payload.get("component") or "")
        source = f"{component}@{node}" if component or node else "?"
        reason = str(payload.get("reason") or "slow")
        is_root = not bundle.get("remote_parent")
        with self._lock:
            e = self._traces.get(trace_id)
            if e is None:
                e = {"trace_id": trace_id, "name": bundle.get("name"),
                     "first_ts": bundle.get("start"),
                     "last_ts": bundle.get("start"),
                     "duration_seconds": 0.0, "status": "ok",
                     "reasons": [], "sources": {}, "spans": [],
                     "span_count": 0, "has_root": False,
                     "_span_ids": set()}
                self._traces[trace_id] = e
                while len(self._traces) > self.ring_size:
                    self._traces.popitem(last=False)
            else:
                self._traces.move_to_end(trace_id)
            self.ingested += 1
            start = bundle.get("start")
            if start is not None:
                if e["first_ts"] is None or start < e["first_ts"]:
                    e["first_ts"] = start
                if e["last_ts"] is None or start > e["last_ts"]:
                    e["last_ts"] = start
            # The true root bundle (no upstream context) names the
            # trace and sets its end-to-end duration; until one lands,
            # the longest local root stands in.
            dur = float(bundle.get("duration_seconds") or 0.0)
            if is_root and not e["has_root"]:
                e["has_root"] = True
                e["name"] = bundle.get("name")
                e["duration_seconds"] = dur
            elif is_root == e["has_root"] and dur > e["duration_seconds"]:
                if not e["has_root"]:
                    e["name"] = bundle.get("name")
                e["duration_seconds"] = dur
            st = bundle.get("status", "ok")
            if st != "ok" and e["status"] == "ok":
                e["status"] = st
            if reason not in e["reasons"]:
                e["reasons"].append(reason)
            for s in spans:
                sid = s.get("span_id")
                if sid in e["_span_ids"]:
                    continue
                e["_span_ids"].add(sid)
                e["span_count"] += 1
                e["sources"][source] = e["sources"].get(source, 0) + 1
                if len(e["spans"]) < self.MAX_SPANS:
                    s = dict(s)
                    s["node"] = source
                    e["spans"].append(s)

    @staticmethod
    def _public(e: dict) -> dict:
        return {k: v for k, v in e.items() if not k.startswith("_")}

    def traces(self, limit: Optional[int] = None) -> list[dict]:
        """Stitched traces, most recently touched last."""
        with self._lock:
            entries = [self._public(e) for e in self._traces.values()]
        if limit is not None and limit >= 0:
            entries = entries[-limit:] if limit else []
        return entries

    def top(self, limit: int = 10) -> list[dict]:
        """Worst traces first (errored above slow, then by duration),
        each with a per-stage time breakdown — the ``trace.top`` view."""
        with self._lock:
            entries = [self._public(e) for e in self._traces.values()]
        for e in entries:
            stages: dict[str, float] = {}
            for s in e["spans"]:
                stages[s["name"]] = (stages.get(s["name"], 0.0)
                                     + float(s.get("duration_seconds")
                                             or 0.0))
            e["stages"] = dict(sorted(stages.items(),
                                      key=lambda kv: kv[1],
                                      reverse=True))
        entries.sort(key=lambda e: (e["status"] == "ok",
                                    -e["duration_seconds"]))
        return entries[:max(0, int(limit))]

    def payload(self, limit: Optional[int] = None) -> dict:
        """The ``/cluster/traces`` JSON body."""
        with self._lock:
            count = len(self._traces)
        return {
            "ring_size": self.ring_size,
            "count": count,
            "ingested": self.ingested,
            "rejected": self.rejected,
            "traces": self.traces(limit),
        }


# --------------------------------------------------------------------------
# HTTP server instrumentation
# --------------------------------------------------------------------------

def _http_untraced(path: str) -> bool:
    p = path.split("?", 1)[0]
    # startswith takes the whole prefix tuple in one C call
    return p in _UNTRACED_PATHS or p.startswith(_UNTRACED_PREFIXES)


def instrument_http_handler(cls, component: str):
    """Wrap every ``do_*`` verb of a BaseHTTPRequestHandler subclass in
    a trace root named ``<component>.<VERB>`` that continues any
    upstream ``X-Seaweed-Trace`` context."""
    for attr in dir(cls):
        if attr.startswith("do_"):
            setattr(cls, attr,
                    _wrap_http_verb(getattr(cls, attr), component,
                                    attr[3:]))
    return cls


def _wrap_http_verb(fn, component: str, verb: str):
    name = f"{component}.{verb}"

    @functools.wraps(fn)
    def handler(self):
        if not _ENABLED or _http_untraced(self.path):
            return fn(self)
        hdr = self.headers.get(TRACE_HEADER)
        with start_trace(name, header=hdr, path=self.path):
            return fn(self)

    return handler


# --------------------------------------------------------------------------
# gRPC propagation (mirrors util/security.py's interceptor plumbing)
# --------------------------------------------------------------------------

def grpc_trace_channel(channel):
    """Wrap a channel so every call carries the active trace context in
    metadata. Calls made outside a trace add nothing."""
    import grpc

    from .security import _ClientCallDetails

    class _Attach(grpc.UnaryUnaryClientInterceptor,
                  grpc.UnaryStreamClientInterceptor,
                  grpc.StreamUnaryClientInterceptor,
                  grpc.StreamStreamClientInterceptor):
        def _details(self, cd):
            val = outbound_value()
            if val is None:
                return cd
            md = list(cd.metadata or [])
            md.append((GRPC_METADATA_KEY, val))
            return _ClientCallDetails(cd, md)

        def intercept_unary_unary(self, cont, cd, req):
            return cont(self._details(cd), req)

        def intercept_unary_stream(self, cont, cd, req):
            return cont(self._details(cd), req)

        def intercept_stream_unary(self, cont, cd, it):
            return cont(self._details(cd), it)

        def intercept_stream_stream(self, cont, cd, it):
            return cont(self._details(cd), it)

    return grpc.intercept_channel(channel, _Attach())


def grpc_metadata_value(context) -> Optional[str]:
    try:
        md = dict(context.invocation_metadata() or ())
    except Exception:  # noqa: BLE001 — non-grpc test doubles
        return None
    return md.get(GRPC_METADATA_KEY)


def wrap_grpc_unary(fn, rpc_name: str):
    """Server-side: run a unary handler under a ``grpc.<Method>`` span
    continuing the caller's context from invocation metadata."""
    name = f"grpc.{rpc_name}"

    @functools.wraps(fn)
    def handler(request, context):
        if not _ENABLED:
            return fn(request, context)
        with start_trace(name, header=grpc_metadata_value(context)):
            return fn(request, context)

    return handler


def wrap_grpc_stream(fn, rpc_name: str):
    """Server-side: span around a server-streaming handler; the span
    stays open until the response generator is exhausted (the sync gRPC
    server drains it on the same worker thread)."""
    name = f"grpc.{rpc_name}"

    @functools.wraps(fn)
    def handler(request, context):
        if not _ENABLED:
            yield from fn(request, context)
            return
        with start_trace(name, header=grpc_metadata_value(context)):
            yield from fn(request, context)

    return handler
