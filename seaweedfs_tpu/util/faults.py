"""Deterministic, seedable fault-injection plane.

Named *fault points* are compiled into every HTTP/gRPC/disk I/O path:
``faults.check("volume.read")`` runs before the operation and may raise,
sleep, or drop the call; ``faults.mangle("ec.shard_read", buf)`` runs on
the bytes an operation returned and may truncate or corrupt them. With
no faults armed — the default — both are one module-flag test, so the
hot path pays a dict-is-empty check and nothing else (``bench.py
--fault-overhead`` holds that under 2%).

A fault *spec* is a compact string::

    action[@probability][:param][#count]

    error            raise FaultError on every call
    drop             raise FaultDrop (simulated dropped connection)
    delay:0.2        sleep 0.2s, then proceed
    delay:0.2@0.5    ... on a seeded coin-flip half the time
    truncate:0.5     mangle() returns the first half of the bytes
    corrupt          mangle() flips bytes at seeded positions
    crash            power-cut the process at the point (os._exit), or
                     raise SimulatedCrash under a crashfs recording
    error@0.3#5      30% of calls, at most 5 injections total

Coin flips come from a per-spec ``random.Random`` seeded from the
global seed and the point name, so a chaos run replays identically:
same seed, same injection schedule. Specs arm at runtime through
:func:`inject` (the ``fault.inject`` shell command), the
``SEAWEED_FAULTS`` environment variable (``point=spec;point=spec``),
or a ``[faults]`` TOML block; :func:`debug_payload` surfaces armed
specs and per-point hit counts in every server's ``/debug/vars``.

The resilience layer (:mod:`seaweedfs_tpu.util.retry`) classifies
:class:`FaultError` as retryable, so injected transient faults exercise
the same backoff/breaker/degradation machinery a real flaky disk or
dead peer would.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Optional

#: Fault points compiled into the tree, for ``fault.list`` and docs.
#: Arming an unknown name still works (points are matched by string),
#: but the catalog is what operators discover.
CATALOG = (
    "volume.read",     # client GET of a needle from a volume server
    "volume.write",    # client POST of a needle
    "volume.delete",   # client DELETE of a needle
    "master.assign",   # fid assignment through the master
    "master.rpc",      # raft vote/append-entries between masters
    "master.proxy",    # follower-master HTTP proxy to the leader
    "replica.push",    # volume server fanning a write to a replica
    "ec.shard_read",   # one shard-interval read (local disk or peer)
    "filer.meta",      # filer metadata gRPC (lookup/create/delete)
    "filer.data",      # filer HTTP data path (chunked GET/PUT)
    "sink.s3",         # replication S3 sink pushes
    "notify.webhook",  # notification webhook POSTs
    "tier.copy",       # volume tier upload/download transfers
    # Crashpoints (docs/robustness.md "Crash consistency"): named
    # commit-path instants where a `crash` spec kills the process (or,
    # under util/crashfs.py, raises SimulatedCrash and freezes the
    # recorded op log for torn-prefix replay).
    "crash.append.dat",      # needle appended to .dat, .idx not yet
    "crash.append.idx",      # .idx journaled, ack not yet returned
    "crash.vacuum.compact",  # mid-compact: .cpd/.cpx partially built
    "crash.vacuum.precommit",  # compact done, neither rename applied
    "crash.vacuum.midcommit",  # .cpd renamed over .dat, .cpx not yet
    "crash.disktier.append",   # disk-cache segment record written
    "crash.tier.download",     # .dat.part complete, not yet renamed
    "crash.ckpt.save",         # shards written, manifest not yet PUT
    "crash.ec.writeback",      # EC shard slice positioned-write issued
)


class FaultError(OSError):
    """An injected failure. Subclasses OSError so the retry layer's
    transient-error classification treats it like a real I/O fault."""


class FaultDrop(FaultError):
    """An injected dropped call (connection reset mid-flight)."""


class FaultSpecError(ValueError):
    pass


class FaultSpec:
    """One armed fault: parsed action + seeded coin-flip state."""

    __slots__ = ("point", "action", "probability", "param", "remaining",
                 "spec", "rng", "hits")

    ACTIONS = ("error", "drop", "delay", "truncate", "corrupt", "crash")

    def __init__(self, point: str, spec: str, seed: Optional[int] = None):
        self.point = point
        self.spec = spec
        body = spec.strip()
        self.remaining = -1  # -1 = unbounded
        if "#" in body:
            body, _, cnt = body.rpartition("#")
            try:
                self.remaining = int(cnt)
            except ValueError:
                raise FaultSpecError(
                    f"bad count in fault spec {spec!r}") from None
        self.probability = 1.0
        if "@" in body:
            body, _, prob = body.partition("@")
            try:
                self.probability = float(prob)
            except ValueError:
                raise FaultSpecError(
                    f"bad probability in fault spec {spec!r}") from None
        action, _, param = body.partition(":")
        action = action.strip()
        if action not in self.ACTIONS:
            raise FaultSpecError(
                f"unknown fault action {action!r}; "
                f"have {', '.join(self.ACTIONS)}")
        self.action = action
        if param:
            try:
                self.param = float(param)
            except ValueError:
                raise FaultSpecError(
                    f"bad param in fault spec {spec!r}") from None
        else:
            self.param = {"delay": 0.05, "truncate": 0.5}.get(action, 0.0)
        base = _SEED if seed is None else seed
        # Stable per-point stream: replaying the same seed + spec set
        # reproduces the exact injection schedule.
        self.rng = random.Random(f"{base}:{point}:{spec}")
        self.hits = 0

    def fire(self) -> bool:
        """Seeded coin flip + count budget; True = inject this call."""
        if self.remaining == 0:
            return False
        if self.probability < 1.0 and self.rng.random() >= self.probability:
            return False
        if self.remaining > 0:
            self.remaining -= 1
        self.hits += 1
        return True

    def to_dict(self) -> dict:
        return {"point": self.point, "spec": self.spec,
                "action": self.action, "probability": self.probability,
                "param": self.param, "remaining": self.remaining,
                "hits": self.hits}


_LOCK = threading.Lock()
_SPECS: dict[str, FaultSpec] = {}
#: Installed by util/crashfs.py while a crash recording is active: a
#: callable(point) expected to raise (SimulatedCrash). When None, a
#: fired `crash` spec hard-exits the process (os._exit) instead.
_CRASH_HANDLER = None
_SEED = 0
_ENABLED = True
#: Hot-path flag: True only when enabled AND at least one spec is
#: armed. check()/mangle() test this one name and return.
_ACTIVE = False


def _recompute_active() -> None:
    global _ACTIVE
    _ACTIVE = _ENABLED and bool(_SPECS)


def configure(enabled: Optional[bool] = None,
              seed: Optional[int] = None) -> None:
    global _ENABLED, _SEED
    with _LOCK:
        if enabled is not None:
            _ENABLED = bool(enabled)
        if seed is not None:
            _SEED = int(seed)
        _recompute_active()


def configure_from(conf: dict) -> None:
    """Apply a loaded TOML dict's ``[faults]`` block: ``enabled``,
    ``seed``, and an ``inject`` string of ``point=spec`` pairs joined
    by ``;`` (same syntax as ``SEAWEED_FAULTS``)."""
    from . import config as config_mod
    configure(enabled=config_mod.lookup(conf, "faults.enabled"),
              seed=config_mod.lookup(conf, "faults.seed"))
    inject_all(config_mod.lookup(conf, "faults.inject", "") or "")


def configure_from_env(environ=os.environ) -> None:
    """Arm faults named in ``SEAWEED_FAULTS`` (and seed from
    ``SEAWEED_FAULTS_SEED``). Servers call this at start so a chaos
    harness can inject into subprocesses it cannot reach by API."""
    seed = environ.get("SEAWEED_FAULTS_SEED")
    if seed:
        configure(seed=int(seed))
    inject_all(environ.get("SEAWEED_FAULTS", ""))


def inject_all(pairs: str) -> None:
    for part in pairs.split(";"):
        part = part.strip()
        if not part:
            continue
        point, eq, spec = part.partition("=")
        if not eq:
            raise FaultSpecError(
                f"bad fault pair {part!r}, want point=spec")
        inject(point.strip(), spec.strip())


def inject(point: str, spec: str, seed: Optional[int] = None) -> FaultSpec:
    """Arm (or replace) the fault at ``point``. Returns the parsed
    spec; raises :class:`FaultSpecError` on a malformed one."""
    fs = FaultSpec(point, spec, seed=seed)
    with _LOCK:
        _SPECS[point] = fs
        _recompute_active()
    return fs


def clear(point: Optional[str] = None) -> None:
    """Disarm one point, or all of them."""
    with _LOCK:
        if point is None:
            _SPECS.clear()
        else:
            _SPECS.pop(point, None)
        _recompute_active()


def specs() -> list[dict]:
    with _LOCK:
        return [fs.to_dict() for fs in _SPECS.values()]


def active() -> bool:
    return _ACTIVE


def set_crash_handler(handler) -> None:
    """Route fired `crash` specs to ``handler(point)`` instead of
    ``os._exit``. crashfs installs one for in-process torn-prefix
    simulation; pass None to restore process-exit semantics."""
    global _CRASH_HANDLER
    _CRASH_HANDLER = handler


def debug_payload() -> dict:
    """The faults section of ``/debug/vars``."""
    return {"enabled": _ENABLED, "seed": _SEED, "specs": specs()}


def check(point: str) -> None:
    """Control-path fault point: may raise FaultError/FaultDrop or
    sleep. A no-op (one flag test) when nothing is armed."""
    if not _ACTIVE:
        return
    fs = _SPECS.get(point)
    # data actions fire in mangle() only — consuming their coin-flip
    # stream here would halve the armed count/schedule
    if fs is None or fs.action in ("truncate", "corrupt") \
            or not fs.fire():
        return
    if fs.action == "delay":
        time.sleep(fs.param)
    elif fs.action == "drop":
        raise FaultDrop(f"injected drop at {point}")
    elif fs.action == "crash":
        handler = _CRASH_HANDLER
        if handler is not None:
            handler(point)  # in-process simulation (util/crashfs.py)
        # Real crash semantics: no atexit, no finally blocks, no
        # buffered-file flushes — exactly what power loss looks like
        # to everything this process had not fsynced.
        os._exit(86)
    else:
        raise FaultError(f"injected fault at {point}")


def mangle(point: str, data: bytes) -> bytes:
    """Data-path fault point: may truncate or corrupt ``data``. The
    spec's coin flip happens in :func:`check` only when the action is
    control-path; data actions flip here."""
    if not _ACTIVE:
        return data
    fs = _SPECS.get(point)
    if fs is None or fs.action not in ("truncate", "corrupt") \
            or not fs.fire():
        return data
    if fs.action == "truncate":
        return data[:int(len(data) * fs.param)]
    if not data:
        return data
    buf = bytearray(data)
    n = max(1, len(buf) // 1024)
    for _ in range(n):
        i = fs.rng.randrange(len(buf))
        buf[i] ^= 0xFF
    return bytes(buf)


# Arm anything the environment asks for as soon as the module loads, so
# subprocess servers (chaos_smoke.sh, bench helpers) need no API call.
if os.environ.get("SEAWEED_FAULTS"):
    configure_from_env()
