"""ASan-style runtime checker for pooled host buffers.

The static rules (seaweedlint SW5xx) prove what they can see; this is
the dynamic half, exactly as lockcheck.py is for the lock rules. Under
``SEAWEED_BUFCHECK=1`` every ``pipeline.pipe.HostBufferPool`` buffer
is generation-tagged:

- ``release`` bumps the buffer's generation and *poisons* the slab
  with a repeating magic pattern, so any consumer still holding a view
  reads garbage-that-screams instead of silently-stale bytes;
- the positioned-write pool (pipeline/writeback.py) captures each
  submitted row's (root buffer, generation) at submit time and
  re-verifies it in the worker immediately before AND after the
  ``pwritev`` — a generation mismatch means the pooled buffer was
  recycled while the write still viewed it, raising
  :class:`DanglingViewError` with both sites. This is precisely the
  PR 12 ``np.ascontiguousarray``-view race, caught deterministically
  at test time instead of as rare shard corruption;
- ``SEAWEED_BUFCHECK=protect`` additionally mprotects the whole slab
  ``PROT_NONE`` while it sits in the free list (mmap regions are
  page-aligned by construction), so ANY touch through a dangling view
  faults immediately — the hard mode; falls back to poison-only when
  libc/mprotect is unavailable.

Views are matched to their owning slab by data-pointer range (so tags
survive arbitrary slicing/reshaping, and copies — which allocate
elsewhere — correctly escape tracking, copies being the safe case). All
hooks are behind a module-level enabled flag and cost nothing when
off. tests/conftest.py arms record mode for the whole tier-1 suite,
like lockcheck.

Static counterpart: ``python -m seaweedfs_tpu.analysis`` (SW501/502).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

import numpy as np

__all__ = ["install_from_env", "install", "uninstall", "enabled",
           "protect_mode", "register", "on_acquire", "on_release",
           "tag_rows", "verify_rows", "is_poisoned", "violations",
           "reset", "DanglingViewError"]

#: 32-byte poison magic; recognizable in hexdumps and checkable from
#: any offset (see :func:`is_poisoned`).
MAGIC = (b"\xa5\x1f\xee\xd5\xa5\x1f" + b"SWBUFCHK:dead-view!!"
         + b"\xa5\x1f\xee\xd5\xa5\x1f")
assert len(MAGIC) == 32

_PROT_NONE = 0
_PROT_RW = 3  # PROT_READ | PROT_WRITE


class DanglingViewError(AssertionError):
    """A write consumed a view of a pooled buffer that was recycled
    (released + generation-bumped) while the write was in flight."""


@dataclass
class _BufInfo:
    gen: int
    addr: int
    nbytes: int
    arr: np.ndarray          # the full registered slab array
    protected: bool = False


@dataclass
class _State:
    registry: dict = field(default_factory=dict)   # id(mmap) -> _BufInfo
    violations_list: list = field(default_factory=list)
    lock: threading.Lock = field(default_factory=threading.Lock)


_STATE = _State()
_enabled = False
_protect = False
_libc = None


def enabled() -> bool:
    return _enabled


def protect_mode() -> bool:
    return _enabled and _protect


def install(protect: bool = False) -> None:
    """Arm the checker (idempotent). Pools created before install are
    not tracked — arm before building pipelines (conftest does)."""
    global _enabled, _protect
    _enabled = True
    _protect = protect and _load_libc()


def uninstall() -> None:
    global _enabled, _protect
    for info in list(_STATE.registry.values()):
        if info.protected:
            _mprotect(info, _PROT_RW)
    _enabled = False
    _protect = False


def install_from_env() -> bool:
    """Honor SEAWEED_BUFCHECK: "1"/"on"/"record" poisons + verifies,
    "protect" additionally PROT_NONEs free slabs."""
    mode = os.environ.get("SEAWEED_BUFCHECK", "").strip().lower()
    if mode in ("1", "true", "on", "record", "poison"):
        install(protect=False)
    elif mode == "protect":
        install(protect=True)
    return _enabled


def violations() -> list:
    return list(_STATE.violations_list)


def reset(violations_only: bool = False) -> None:
    """Clear recorded state. Tests that deliberately provoke a
    violation pass ``violations_only=True`` so live pools created by
    other tests stay tracked."""
    with _STATE.lock:
        if not violations_only:
            _STATE.registry.clear()
        _STATE.violations_list.clear()


# --------------------------------------------------------------------------
# pool integration (pipeline/pipe.HostBufferPool)
# --------------------------------------------------------------------------

def register(arr: np.ndarray, mm) -> None:
    """Track one pool slab (the full np.frombuffer(mmap) array)."""
    if not _enabled:
        return
    with _STATE.lock:
        _STATE.registry[id(mm)] = _BufInfo(
            gen=0, addr=arr.ctypes.data, nbytes=arr.nbytes, arr=arr)


def _root(arr) -> _BufInfo | None:
    """The registered slab ``arr``'s data lives in, by address range.

    Address lookup (not a ``.base`` chain walk — ``np.frombuffer``
    roots at a throwaway memoryview, not the mmap) is what makes the
    semantics right: any view into the slab matches however it was
    sliced/reshaped, while a COPY allocates elsewhere and correctly
    escapes tracking — copies are exactly the safe case (the PR 12
    fix)."""
    addr = arr.ctypes.data
    for info in _STATE.registry.values():
        if info.addr <= addr < info.addr + info.nbytes:
            return info
    return None


def on_acquire(buf: np.ndarray) -> None:
    if not _enabled:
        return
    info = _root(buf)
    if info is not None and info.protected:
        _mprotect(info, _PROT_RW)


def on_release(buf: np.ndarray) -> None:
    """Generation-bump + poison (callers put the buffer back on the
    free list afterwards; consumers still holding views now read
    poison, and tagged writes detect the bump)."""
    if not _enabled:
        return
    info = _root(buf)
    if info is None:
        return
    with _STATE.lock:
        info.gen += 1
    _poison(info.arr)
    if _protect:
        _mprotect(info, _PROT_NONE)


# --------------------------------------------------------------------------
# writeback integration (pipeline/writeback.WriterPool)
# --------------------------------------------------------------------------

def tag_rows(rows) -> list | None:
    """Capture (root slab, generation) for every row that views a
    tracked pool buffer; None when disabled or nothing is pooled."""
    if not _enabled:
        return None
    tags = []
    for r in rows:
        if isinstance(r, np.ndarray):
            info = _root(r)
            if info is not None:
                tags.append((info, info.gen))
    return tags or None


def verify_rows(tags, where: str = "") -> None:
    """Raise :class:`DanglingViewError` if any tagged buffer was
    recycled since its tag was taken."""
    if not tags:
        return
    for info, gen in tags:
        if info.gen != gen:
            msg = (f"pwritev consumed a view of a recycled pooled "
                   f"buffer (generation {gen} -> {info.gen}"
                   f"{', ' + where if where else ''}): the buffer was "
                   f"released while a positioned write still viewed "
                   f"it — the PR 12 ascontiguousarray-view race. Copy "
                   f"rows that outlive the batch (flatten()) or gate "
                   f"the release on a BatchToken.")
            _STATE.violations_list.append(msg)
            raise DanglingViewError(msg)


def is_poisoned(arr: np.ndarray) -> bool:
    """True when the first bytes of ``arr`` carry the recycle poison
    (offset-independent: the pattern repeats every 32 bytes)."""
    flat = np.ascontiguousarray(arr).view(np.uint8).reshape(-1)
    probe = bytes(flat[:len(MAGIC)].tobytes())
    return len(probe) > 0 and probe in MAGIC * 2


# --------------------------------------------------------------------------
# internals
# --------------------------------------------------------------------------

def _poison(arr: np.ndarray) -> None:
    n = arr.nbytes
    reps = -(-n // len(MAGIC))
    arr[...] = np.frombuffer((MAGIC * reps)[:n], dtype=np.uint8)


def _load_libc() -> bool:
    global _libc
    if _libc is not None:
        return True
    try:
        import ctypes
        _libc = ctypes.CDLL(None, use_errno=True)
        _libc.mprotect.restype = ctypes.c_int
        return True
    except OSError:  # pragma: no cover — no libc (non-POSIX)
        _libc = None
        return False


def _mprotect(info: _BufInfo, prot: int) -> None:
    if _libc is None:
        return
    import ctypes
    rc = _libc.mprotect(ctypes.c_void_p(info.addr),
                        ctypes.c_size_t(info.nbytes), prot)
    if rc == 0:
        info.protected = prot == _PROT_NONE
