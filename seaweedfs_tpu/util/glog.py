"""glog-style leveled logging.

Mirrors the reference's weed/glog wrapper (SURVEY.md §5
"Tracing/profiling"): ``glog.v(n, ...)`` messages print only when the
process verbosity is >= n (reference flag ``-v=N``); info/warning/error
always print, each stamped with severity, time, and caller. Implemented
on the stdlib logging module so tests can capture records normally.
"""

from __future__ import annotations

import logging
import os
import sys

_logger = logging.getLogger("seaweedfs_tpu")
if not _logger.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "%(levelname).1s%(asctime)s %(name)s] %(message)s",
        datefmt="%m%d %H:%M:%S"))
    _logger.addHandler(h)
    _logger.setLevel(logging.INFO)
    _logger.propagate = False

#: Process verbosity, like the reference's -v flag; env override for tests.
VERBOSITY = int(os.environ.get("WEED_V", "0"))


def set_verbosity(n: int) -> None:
    global VERBOSITY
    VERBOSITY = n


def _trace_suffix() -> str:
    """`` trace=<id> span=<id>`` when this thread is inside an active
    span, else "" — the glue that lets ``trace.top`` output grep
    straight into server logs. Looked up through sys.modules because
    tracing imports glog (never the other way around); until tracing is
    loaded there is no span to correlate anyway."""
    tracing = sys.modules.get("seaweedfs_tpu.util.tracing")
    if tracing is None or not tracing.active():
        return ""
    sp = tracing.current_span()
    if sp is None:
        return ""
    return f" trace={sp.trace_id} span={sp.span_id}"


def v(level: int, fmt: str, *args) -> None:
    if VERBOSITY >= level:
        _logger.info(fmt + _trace_suffix(), *args)


def info(fmt: str, *args) -> None:
    _logger.info(fmt + _trace_suffix(), *args)


def warning(fmt: str, *args) -> None:
    _logger.warning(fmt + _trace_suffix(), *args)


def error(fmt: str, *args) -> None:
    _logger.error(fmt + _trace_suffix(), *args)
