"""Shared overload-resilient ingress core for every HTTP server.

The reference runs each server (master, volume, filer, S3, WebDAV) on
Go's ``net/http`` — goroutine-per-connection with keep-alive, idle
timeouts, and backpressure for free. The stdlib analog this repo grew
up on, ``ThreadingHTTPServer``, has none of that: an unbounded thread
per connection, connections torn down after every request, and under
overload the process fails by accident (thread exhaustion, queue
collapse) instead of by policy. :class:`IngressHTTPServer` is the
drop-in replacement that turns overload into policy:

* **Bounded worker pool** — ``workers`` threads service parsed
  requests off one dispatch queue; the thread count never grows with
  concurrency. The accept loop only registers connections (cheap), so
  a connection flood cannot stack threads.
* **Keep-alive discipline** — HTTP/1.1 persistent connections do NOT
  pin workers: after each response an idle connection is *parked* on a
  selector thread and re-dispatched when readable. Idle connections
  past ``keepalive_idle_seconds`` are reaped; ``max_connections``
  caps the per-server connection census (beyond it, new connections
  get an immediate 429 and close).
* **Admission control** — before the application verb runs, requests
  whose ``X-Seaweed-Deadline`` budget is already spent are answered
  504 (the caller stopped waiting; doing the work is pure waste), and
  when dispatch-queue pressure passes ``shed_watermark`` requests are
  shed with 429 + ``Retry-After`` instead of queueing toward
  collapse. Apply with :func:`admission_gate` *under* the tracing
  wrapper so shed decisions are tagged on the request's span.
* **Per-tenant QoS** — :class:`QosEngine` (S3 gateway) maps the
  SigV4-authenticated identity to a priority class with token-bucket
  rate and concurrency limits. Under pressure, low-priority classes
  shed first (priority ``p`` sheds at ``watermark ** p``); a
  priority-0 class is never pressure-shed, so a guaranteed tenant
  rides out another tenant's overload with zero failures.

Every decision is observable: ``seaweed_ingress_*`` metrics (rendered
on ``/metrics`` next to the retry/tracing planes), an ``ingress``
section in ``/debug/vars`` (:func:`debug_payload`), and ``shed=...``
tags on trace spans. Config lives in ``[ingress]`` / ``[qos]`` TOML
blocks (see ``config.SCAFFOLDS``); ``bench.py --ingress-overhead``
holds the admission path under 2% on warm cached reads.
"""

from __future__ import annotations

import json
import math
import queue
import selectors
import socket
import socketserver
import threading
import time
import weakref
from http.server import HTTPServer
from typing import Optional

from . import glog, stats, tracing

DEADLINE_HEADER = "X-Seaweed-Deadline"


def parse_range(header, size: int):
    """RFC 7233 single-range parse: (offset, length) or None to serve
    the full body with 200 (unknown units and malformed values are
    ignored, suffix ranges bytes=-N mean the LAST N bytes). Shared by
    the filer, volume-server and S3 read paths so every tier slices a
    ``bytes=a-b`` identically."""
    if not header or not header.startswith("bytes="):
        return None
    spec = header[6:].split(",")[0].strip()
    lo, sep, hi = spec.partition("-")
    if not sep:
        return None
    try:
        if not lo:  # suffix: last N bytes
            n = int(hi)
            if n <= 0:
                return None
            offset = max(0, size - n)
            return offset, size - offset
        offset = int(lo)
        stop = int(hi) + 1 if hi else size
    except ValueError:
        return None
    if offset >= size:
        return None
    return offset, max(0, min(stop, size) - offset)

#: Ingress metrics (``seaweed_ingress_shed_total{reason,class}``,
#: ``seaweed_ingress_requests_total`` ...). Servers append
#: ``METRICS.render()`` to their ``/metrics`` output.
METRICS = stats.Metrics(namespace="seaweed")

#: Admission-plane master switch (the structural pool/keep-alive core
#: is always on). ``bench.py --ingress-overhead`` toggles this to
#: price the per-request checks.
_ENABLED = True

#: Paths never shed by pressure: shedding the endpoints an operator
#: uses to see *why* the server sheds would be self-defeating.
_EXEMPT_PREFIXES = ("/debug/", "/metrics", "/status", "/healthz",
                    "/cluster/status")

_SHED_LOCK = threading.Lock()
_SHED_COUNTS: dict[tuple[str, str], int] = {}

_SERVERS: "weakref.WeakSet[IngressHTTPServer]" = weakref.WeakSet()


class IngressConfig:
    """Tuning for one server's ingress core (``[ingress]`` TOML)."""

    __slots__ = ("workers", "queue_depth", "max_connections",
                 "keepalive_idle_seconds", "keepalive_max_requests",
                 "request_read_timeout", "shed_watermark",
                 "retry_after_seconds", "min_deadline_seconds")

    def __init__(self, workers: int = 16, queue_depth: int = 64,
                 max_connections: int = 512,
                 keepalive_idle_seconds: float = 15.0,
                 keepalive_max_requests: int = 1000,
                 request_read_timeout: float = 30.0,
                 shed_watermark: float = 0.75,
                 retry_after_seconds: float = 1.0,
                 min_deadline_seconds: float = 0.0):
        self.workers = int(workers)
        self.queue_depth = int(queue_depth)
        self.max_connections = int(max_connections)
        self.keepalive_idle_seconds = float(keepalive_idle_seconds)
        self.keepalive_max_requests = int(keepalive_max_requests)
        self.request_read_timeout = float(request_read_timeout)
        self.shed_watermark = float(shed_watermark)
        self.retry_after_seconds = float(retry_after_seconds)
        self.min_deadline_seconds = float(min_deadline_seconds)

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


_DEFAULT = IngressConfig()


def default_config() -> IngressConfig:
    return _DEFAULT


def configure(enabled: Optional[bool] = None, **fields) -> None:
    """Flip the admission switch and/or override default-config
    fields (None values keep current)."""
    global _ENABLED
    if enabled is not None:
        _ENABLED = bool(enabled)
    for k, v in fields.items():
        if v is None:
            continue
        if k not in IngressConfig.__slots__:
            raise AttributeError(f"no ingress config field {k!r}")
        setattr(_DEFAULT, k, type(getattr(_DEFAULT, k))(v))


def configure_from(conf: dict) -> None:
    """Apply a loaded TOML dict's ``[ingress]`` block."""
    sec = (conf or {}).get("ingress")
    if not isinstance(sec, dict):
        return
    configure(
        enabled=sec.get("enabled"),
        workers=sec.get("workers"),
        queue_depth=sec.get("queue_depth"),
        max_connections=sec.get("max_connections"),
        keepalive_idle_seconds=sec.get("keepalive_idle_seconds"),
        keepalive_max_requests=sec.get("keepalive_max_requests"),
        request_read_timeout=sec.get("request_read_timeout_seconds"),
        shed_watermark=sec.get("shed_watermark"),
        retry_after_seconds=sec.get("retry_after_seconds"),
        min_deadline_seconds=sec.get("min_deadline_seconds"))


def _count_shed(reason: str, cls_name: str) -> None:
    METRICS.counter("ingress_shed_total", reason=reason,
                    **{"class": cls_name}).inc()
    with _SHED_LOCK:
        _SHED_COUNTS[(reason, cls_name)] = \
            _SHED_COUNTS.get((reason, cls_name), 0) + 1
    sp = tracing.current_span()
    if sp is not None:
        sp.tag(shed=reason)


def shed_counts() -> dict[str, int]:
    """``{"reason|class": n}`` snapshot (``/debug/vars``, smokes)."""
    with _SHED_LOCK:
        return {f"{r}|{c}": n for (r, c), n in _SHED_COUNTS.items()}


# --------------------------------------------------------------------------
# the server core
# --------------------------------------------------------------------------

class _Conn:
    """One accepted connection moving between queue, worker, parker."""

    __slots__ = ("sock", "addr", "handler", "requests", "parked_at",
                 "opened_at")

    def __init__(self, sock, addr):
        self.sock = sock
        self.addr = addr
        self.handler = None
        self.requests = 0
        self.parked_at = 0.0
        self.opened_at = time.monotonic()


def _one_shot(cls):
    """Subclass whose __init__ only runs setup(): the worker drives
    ``handle_one_request`` explicitly so one handler object survives
    across parks (its rfile buffer may hold a pipelined request)."""
    return type("_Ingress" + cls.__name__, (cls,),
                {"handle": lambda self: None,
                 "finish": lambda self: None})


class _Parker(threading.Thread):
    """Selector thread holding idle keep-alive connections so they
    never pin a worker; readable ones re-enter the dispatch queue,
    idle ones past the keep-alive window are reaped."""

    def __init__(self, server: "IngressHTTPServer"):
        super().__init__(
            name=f"ingress-{server.component}-parker", daemon=True)
        self.server = server
        self._sel = selectors.DefaultSelector()
        self._rsock, self._wsock = socket.socketpair()
        self._rsock.setblocking(False)
        self._sel.register(self._rsock, selectors.EVENT_READ, None)
        self._incoming: list[_Conn] = []
        self._lock = threading.Lock()
        self._stopped = False

    def park(self, conn: _Conn) -> None:
        # a _Conn has exactly one owner at any moment (selector loop
        # OR one worker), handed off through the parked queue; no two
        # threads hold it at once
        # seaweedlint: disable=SW801 — single-owner handoff
        conn.parked_at = time.monotonic()
        with self._lock:
            if self._stopped:
                self.server._close(conn)
                return
            self._incoming.append(conn)
        self._wake()

    def parked(self) -> int:
        # minus the always-registered wake pipe; a closed selector
        # (server shut down) has no map and parks nothing
        try:
            m = self._sel.get_map()
        except RuntimeError:
            return 0
        return max(0, len(m) - 1) if m is not None else 0

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
        self._wake()

    def _wake(self) -> None:
        try:
            self._wsock.send(b"x")
        except OSError:  # seaweedlint: disable=SW301 — wake pipe already closed; parker is exiting anyway
            pass

    def run(self) -> None:
        srv = self.server
        while True:
            with self._lock:
                if self._stopped:
                    break
                newly, self._incoming = self._incoming, []
            for conn in newly:
                try:
                    self._sel.register(
                        conn.sock, selectors.EVENT_READ, conn)
                except (KeyError, ValueError, OSError):
                    srv._close(conn)
            wait = max(0.05, min(
                1.0, srv.config.keepalive_idle_seconds / 4))
            try:
                events = self._sel.select(wait)
            except OSError:
                events = []
            for key, _ in events:
                if key.data is None:
                    try:
                        while self._rsock.recv(4096):
                            pass
                    except (BlockingIOError, OSError):  # seaweedlint: disable=SW301 — wake-pipe drain; empty is the normal exit
                        pass
                    continue
                try:
                    self._sel.unregister(key.fileobj)
                except (KeyError, ValueError):  # seaweedlint: disable=SW301 — socket raced to close; dispatch still owns the conn
                    pass
                srv._dispatch.put(key.data)
            now = time.monotonic()
            idle = srv.config.keepalive_idle_seconds
            for key in list(self._sel.get_map().values()):
                conn = key.data
                if conn is None or now - conn.parked_at < idle:
                    continue
                try:
                    self._sel.unregister(key.fileobj)
                except (KeyError, ValueError):  # seaweedlint: disable=SW301 — socket raced to close; reap proceeds
                    pass
                METRICS.counter("ingress_idle_reaped_total",
                                component=srv.component).inc()
                srv._close(conn)
        for key in list(self._sel.get_map().values()):
            if key.data is not None:
                self.server._close(key.data)
        with self._lock:
            leftover, self._incoming = self._incoming, []
        for conn in leftover:
            self.server._close(conn)
        try:
            self._sel.close()
        except OSError:  # seaweedlint: disable=SW301 — final teardown; nothing left to leak
            pass
        self._rsock.close()
        self._wsock.close()


class IngressHTTPServer(HTTPServer):
    """Drop-in ``ThreadingHTTPServer`` replacement (same constructor
    shape, ``serve_forever``/``shutdown``/``server_close`` surface)
    with the bounded-pool + keep-alive + admission core."""

    daemon_threads = True
    allow_reuse_address = True
    request_queue_size = 128  # kernel listen() backlog

    def __init__(self, server_address, HandlerClass, *,
                 config: Optional[IngressConfig] = None,
                 component: str = "http"):
        super().__init__(server_address, HandlerClass)
        self.config = config or _DEFAULT
        self.component = component
        self.admission = AdmissionController(self)
        #: Optional QosEngine — when set (S3 gateway), pressure
        #: shedding is class-aware and happens post-auth in the
        #: handler, not in the generic admission gate.
        self.qos: Optional[QosEngine] = None
        self._handler_cls = _one_shot(HandlerClass)
        self._dispatch: "queue.Queue[Optional[_Conn]]" = queue.Queue()
        self._conns: set[_Conn] = set()
        self._lock = threading.Lock()
        self._busy = 0
        self._served = 0
        self._closing = False
        self._workers = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"ingress-{component}-w{i}")
            for i in range(self.config.workers)]
        for t in self._workers:
            t.start()
        self._parker = _Parker(self)
        self._parker.start()
        from . import racecheck
        racecheck.register(self, f"httpserver.Ingress[{component}]")
        _SERVERS.add(self)

    # -- accept path (runs on the serve_forever thread) ------------------

    def process_request(self, request, client_address):
        cfg = self.config
        with self._lock:
            over = self._closing or len(self._conns) >= cfg.max_connections
            if not over:
                conn = _Conn(request, client_address)
                self._conns.add(conn)
        if over:
            _count_shed("connections", "anonymous")
            try:
                request.settimeout(1.0)
                request.sendall(
                    b"HTTP/1.1 429 Too Many Requests\r\n"
                    b"Retry-After: %d\r\nContent-Length: 0\r\n"
                    b"Connection: close\r\n\r\n"
                    % max(1, int(cfg.retry_after_seconds)))
            except OSError:  # seaweedlint: disable=SW301 — best-effort courtesy 429; peer may already be gone
                pass
            self.shutdown_request(request)
            return
        try:
            request.settimeout(cfg.request_read_timeout)
        except OSError:  # seaweedlint: disable=SW301 — socket died at accept; worker read will surface it
            pass
        METRICS.counter("ingress_connections_total",
                        component=self.component).inc()
        self._dispatch.put(conn)

    # -- worker pool ------------------------------------------------------

    def _work(self) -> None:
        while True:
            conn = self._dispatch.get()
            if conn is None:
                return
            with self._lock:
                self._busy += 1
            try:
                self._service(conn)
            except Exception as e:  # noqa: BLE001 — conn dies, pool lives
                glog.v(1, "ingress %s: connection from %s died: %s: %s",
                       self.component, conn.addr, type(e).__name__, e)
                self._close(conn)
            finally:
                with self._lock:
                    self._busy -= 1

    def _service(self, conn: _Conn) -> None:
        cfg = self.config
        if conn.handler is None:
            try:
                # seaweedlint: disable=SW801 — single-owner handoff
                conn.handler = self._handler_cls(
                    conn.sock, conn.addr, self)
            except Exception:  # noqa: BLE001 — setup failed, drop it
                self._close(conn)
                return
        h = conn.handler
        while True:
            h.close_connection = True
            try:
                h.handle_one_request()
            except (ConnectionError, TimeoutError, OSError):
                self._close(conn)
                return
            with self._lock:
                self._served += 1
            if getattr(h, "_ingress_drop", False) or h.close_connection:
                self._close(conn)
                return
            # seaweedlint: disable=SW801 — single-owner handoff
            conn.requests += 1
            if conn.requests >= cfg.keepalive_max_requests:
                self._close(conn)
                return
            state = self._pending(conn)
            if state == "data":
                if self._dispatch.qsize() == 0:
                    continue  # nothing else waiting; stay inline
                self._dispatch.put(conn)  # yield between pipelined reqs
                return
            if state == "idle":
                self._parker.park(conn)
                return
            self._close(conn)  # eof / error
            return

    def _pending(self, conn: _Conn) -> str:
        """After a response: 'data' (next request bytes already here),
        'idle' (park it), or 'eof' (peer gone). Checks the handler's
        rfile buffer first — a pipelined request may have been pulled
        off the wire by a buffered readline — then MSG_PEEKs the
        socket to distinguish idle from EOF."""
        sock = conn.sock
        try:
            sock.setblocking(False)
            try:
                buf = conn.handler.rfile.peek(1)
            except (BlockingIOError, InterruptedError):
                buf = b""
            if buf:
                return "data"
            try:
                probe = sock.recv(1, socket.MSG_PEEK)
                return "data" if probe else "eof"
            except (BlockingIOError, InterruptedError):
                return "idle"
        except (OSError, ValueError):
            return "eof"
        finally:
            try:
                sock.settimeout(self.config.request_read_timeout)
            except OSError:  # seaweedlint: disable=SW301 — peer closed mid-probe; next read reports eof
                pass

    def _close(self, conn: _Conn) -> None:
        with self._lock:
            self._conns.discard(conn)
        if conn.handler is not None:
            try:
                socketserver.StreamRequestHandler.finish(conn.handler)
            except Exception:  # noqa: BLE001  # seaweedlint: disable=SW301 — flush on an already-dead socket
                pass
            conn.handler = None
        try:
            self.shutdown_request(conn.sock)
        except Exception:  # noqa: BLE001  # seaweedlint: disable=SW301 — close on an already-dead socket
            pass

    # -- pressure + introspection ----------------------------------------

    def pressure(self) -> float:
        """Dispatch-queue fill against the configured logical depth
        (can exceed 1.0 — the physical bound is max_connections)."""
        return self._dispatch.qsize() / max(1, self.config.queue_depth)

    def stats_payload(self) -> dict:
        with self._lock:
            busy, conns, served = self._busy, len(self._conns), \
                self._served
        return {"component": self.component,
                "workers": self.config.workers, "busy": busy,
                "queued": self._dispatch.qsize(),
                "queue_depth": self.config.queue_depth,
                "pressure": round(self.pressure(), 4),
                "connections": conns,
                "max_connections": self.config.max_connections,
                "parked": self._parker.parked(),
                "served_total": served,
                "qos": self.qos.payload() if self.qos else None}

    # -- teardown ---------------------------------------------------------

    def server_close(self) -> None:
        with self._lock:
            self._closing = True
        self._parker.stop()
        for _ in self._workers:
            self._dispatch.put(None)
        super().server_close()
        with self._lock:
            conns = list(self._conns)
        for c in conns:  # unblocks workers stuck mid-read
            try:
                c.sock.close()
            except OSError:  # seaweedlint: disable=SW301 — shutdown path; double-close is fine
                pass
        for t in self._workers:
            t.join(timeout=2.0)
        self._parker.join(timeout=2.0)
        with self._lock:
            self._conns.clear()


def debug_payload() -> dict:
    """The ``ingress`` section of ``/debug/vars``."""
    return {"enabled": _ENABLED,
            "servers": [s.stats_payload() for s in list(_SERVERS)
                        if not s._closing],
            "shed": shed_counts()}


# --------------------------------------------------------------------------
# admission control
# --------------------------------------------------------------------------

class AdmissionController:
    """Per-request decisions taken between header parse and the
    application verb (the earliest point a policy answer can still be
    a well-formed HTTP response)."""

    __slots__ = ("server",)

    def __init__(self, server: IngressHTTPServer):
        self.server = server

    def check(self, handler) -> Optional[tuple]:
        """None to admit, else ``(status, reason, retry_after)``."""
        cfg = self.server.config
        val = handler.headers.get(DEADLINE_HEADER)
        if val:
            try:
                remaining = float(val)
            except (TypeError, ValueError):
                remaining = None
            if remaining is not None \
                    and remaining <= cfg.min_deadline_seconds:
                return (504, "deadline", None)
        if self.server.qos is None \
                and not handler.path.startswith(_EXEMPT_PREFIXES):
            if self.server.pressure() >= cfg.shed_watermark:
                return (429, "pressure", cfg.retry_after_seconds)
        return None


def reject(handler, status: int, reason: str,
           retry_after: Optional[float] = None,
           cls_name: str = "anonymous") -> None:
    """Answer a shed decision: counted, span-tagged, keep-alive kept
    (a policy rejection is a healthy connection speaking clearly)."""
    _count_shed(reason, cls_name)
    body = json.dumps({"error": "request shed by admission control",
                       "reason": reason, "class": cls_name}).encode()
    try:
        handler.send_response(status)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        if retry_after:
            handler.send_header(
                "Retry-After", str(max(1, int(math.ceil(retry_after)))))
        handler.end_headers()
        if handler.command != "HEAD":
            handler.wfile.write(body)
    except OSError:
        handler.close_connection = True


def drop_connection(handler) -> None:
    """Mark the connection for a hard close with no response — the
    fault-injection ``drop`` action must look like a connection reset,
    and on a keep-alive connection a half-written exchange would
    poison the next pipelined request (satellite of PR 10)."""
    handler._ingress_drop = True
    handler.close_connection = True


def admission_gate(cls):
    """Wrap every ``do_*`` verb with the admission check. Apply
    *before* ``tracing.instrument_http_handler`` so the trace span is
    outermost and shed decisions land inside it as tags."""
    for name in dir(cls):
        if name.startswith("do_"):
            setattr(cls, name, _gated(getattr(cls, name)))
    return cls


def _gated(fn):
    if getattr(fn, "_ingress_gated", False):
        return fn

    def gated(self):
        srv = getattr(self, "server", None)
        ctrl = getattr(srv, "admission", None)
        if ctrl is None or not _ENABLED:
            return fn(self)
        METRICS.counter("ingress_requests_total",
                        component=srv.component).inc()
        decision = ctrl.check(self)
        if decision is None:
            return fn(self)
        reject(self, *decision)

    gated._ingress_gated = True
    gated.__name__ = fn.__name__
    gated.__qualname__ = getattr(fn, "__qualname__", fn.__name__)
    return gated


# --------------------------------------------------------------------------
# per-tenant QoS (S3 gateway)
# --------------------------------------------------------------------------

class QosShed(Exception):
    """A QoS rejection — mapped to 429 + Retry-After at the gateway."""

    def __init__(self, tenant: str, cls_name: str, reason: str,
                 retry_after: float = 1.0):
        super().__init__(
            f"tenant {tenant!r} (class {cls_name}) shed: {reason}")
        self.tenant = tenant
        self.class_name = cls_name
        self.reason = reason
        self.retry_after = retry_after


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second up to ``burst``."""

    __slots__ = ("rate", "burst", "tokens", "stamp", "clock", "_lock")

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self.clock = clock
        self.stamp = clock()
        self._lock = threading.Lock()

    def take(self) -> float:
        """0.0 when a token was granted, else seconds until one is."""
        with self._lock:
            now = self.clock()
            self.tokens = min(
                self.burst,
                self.tokens + (now - self.stamp) * self.rate)
            self.stamp = now
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return 0.0
            if self.rate <= 0:
                return 1.0
            return (1.0 - self.tokens) / self.rate


class QosClass:
    __slots__ = ("name", "priority", "rate", "burst", "concurrency")

    def __init__(self, name: str, priority: int = 1, rate: float = 0.0,
                 burst: float = 0.0, concurrency: int = 0):
        self.name = name
        self.priority = max(0, int(priority))
        self.rate = float(rate)        # req/s; 0 = unlimited
        self.burst = float(burst) or max(1.0, self.rate)
        self.concurrency = int(concurrency)  # in-flight; 0 = unlimited

    def to_dict(self) -> dict:
        return {"priority": self.priority, "rate_per_second": self.rate,
                "burst": self.burst, "concurrency": self.concurrency}


class QosLease:
    """Releases the tenant's in-flight slot exactly once."""

    __slots__ = ("_engine", "_tenant", "_done")

    def __init__(self, engine: "QosEngine", tenant: str):
        self._engine = engine
        self._tenant = tenant
        self._done = False

    def release(self) -> None:
        if not self._done:
            self._done = True
            self._engine._release(self._tenant)

    def __enter__(self) -> "QosLease":
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


class QosEngine:
    """Priority classes + per-tenant token buckets/concurrency caps.

    Pressure shedding is priority-laddered: class priority ``p`` sheds
    when ingress pressure reaches ``watermark ** p`` — the lowest
    priority gives way earliest, priority 0 ("guaranteed") is never
    pressure-shed and only its own explicit rate/concurrency limits
    (if any) can reject it.
    """

    def __init__(self, classes: Optional[dict] = None,
                 tenants: Optional[dict] = None,
                 default_class: str = "standard",
                 watermark: float = 0.75, clock=time.monotonic):
        self.classes: dict[str, QosClass] = dict(classes or {})
        if default_class not in self.classes:
            self.classes[default_class] = QosClass(default_class)
        self.tenants = {str(k): str(v)
                        for k, v in (tenants or {}).items()}
        self.default_class = default_class
        self.watermark = float(watermark)
        self.clock = clock
        self._buckets: dict[str, TokenBucket] = {}
        self._inflight: dict[str, int] = {}
        self._admitted = 0
        self._shed = 0
        self._lock = threading.Lock()

    def class_of(self, tenant: str) -> QosClass:
        name = self.tenants.get(tenant, self.default_class)
        return self.classes.get(name) or self.classes[self.default_class]

    def shed_threshold(self, qc: QosClass) -> float:
        if qc.priority <= 0:
            return float("inf")
        return self.watermark ** qc.priority

    def admit(self, tenant: str, pressure: float = 0.0) -> QosLease:
        qc = self.class_of(tenant)
        if pressure >= self.shed_threshold(qc):
            self._reject(tenant, qc, "pressure", 1.0)
        if qc.rate > 0:
            with self._lock:
                bucket = self._buckets.get(tenant)
                if bucket is None or bucket.rate != qc.rate:
                    bucket = self._buckets[tenant] = TokenBucket(
                        qc.rate, qc.burst, self.clock)
            wait = bucket.take()
            if wait > 0:
                self._reject(tenant, qc, "rate", wait)
        with self._lock:
            inflight = self._inflight.get(tenant, 0)
            over = 0 < qc.concurrency <= inflight
            if not over:
                self._inflight[tenant] = inflight + 1
                self._admitted += 1
        if over:
            self._reject(tenant, qc, "concurrency", 1.0)
        METRICS.counter("ingress_qos_admitted_total",
                        **{"class": qc.name}).inc()
        return QosLease(self, tenant)

    def _reject(self, tenant: str, qc: QosClass, reason: str,
                retry_after: float):
        with self._lock:
            self._shed += 1
        _count_shed(reason, qc.name)
        raise QosShed(tenant, qc.name, reason,
                      max(1.0, math.ceil(retry_after)))

    def _release(self, tenant: str) -> None:
        with self._lock:
            n = self._inflight.get(tenant, 0) - 1
            if n <= 0:
                self._inflight.pop(tenant, None)
            else:
                self._inflight[tenant] = n

    def payload(self) -> dict:
        with self._lock:
            return {"default_class": self.default_class,
                    "watermark": self.watermark,
                    "classes": {n: c.to_dict()
                                for n, c in self.classes.items()},
                    "tenants": dict(self.tenants),
                    "inflight": dict(self._inflight),
                    "admitted_total": self._admitted,
                    "shed_total": self._shed}


def qos_from_conf(conf: Optional[dict]) -> Optional[QosEngine]:
    """Build a :class:`QosEngine` from a ``[qos]`` TOML block, or None
    when absent/disabled. Schema (subset-parser-safe — scalar values,
    dotted tables only)::

        [qos]
        enabled = true
        default_class = "standard"
        watermark = 0.75

        [qos.class.gold]
        priority = 0          # 0 = guaranteed, never pressure-shed
        rate_per_second = 0.0 # 0 = unlimited
        burst = 0.0
        concurrency = 0       # 0 = unlimited

        [qos.tenant]
        alice = "gold"
    """
    sec = (conf or {}).get("qos")
    if not isinstance(sec, dict) or not sec.get("enabled", False):
        return None
    classes = {}
    for name, c in (sec.get("class") or {}).items():
        if not isinstance(c, dict):
            continue
        classes[name] = QosClass(
            name, priority=int(c.get("priority", 1)),
            rate=float(c.get("rate_per_second", 0.0)),
            burst=float(c.get("burst", 0.0)),
            concurrency=int(c.get("concurrency", 0)))
    tenants = {k: v for k, v in (sec.get("tenant") or {}).items()
               if isinstance(v, str)}
    return QosEngine(
        classes, tenants,
        default_class=str(sec.get("default_class", "standard")),
        watermark=float(sec.get("watermark", _DEFAULT.shed_watermark)))
