"""Write-path auth: HMAC-signed JWTs + cookies.

Mirrors weed/security (SURVEY.md §2 "Security"): when a signing key is
configured, the master attaches a short-lived token to each Assign
response (``GenJwt``) and volume servers verify it on writes/deletes
(``Guard``). Tokens are standard JWS compact HS256 — header.payload.sig
with base64url parts — built on hashlib/hmac so no external jwt
dependency is needed. An empty key disables enforcement, matching the
reference's default.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets
import time


def new_cookie() -> int:
    """Random 32-bit needle cookie (needle/file_id semantics)."""
    return secrets.randbits(32)


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class Guard:
    """Issues and checks HS256 tokens scoped to one file id."""

    def __init__(self, key: str = "", expires_seconds: int = 10):
        self.key = key.encode() if key else b""
        self.expires_seconds = expires_seconds

    @property
    def enabled(self) -> bool:
        return bool(self.key)

    def sign(self, fid: str) -> str:
        if not self.enabled:
            return ""
        header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        payload = _b64(json.dumps({
            "fid": fid,
            "exp": int(time.time()) + self.expires_seconds}).encode())
        signing_input = f"{header}.{payload}".encode()
        sig = _b64(hmac.new(self.key, signing_input, hashlib.sha256)
                   .digest())
        return f"{header}.{payload}.{sig}"

    def verify(self, token: str, fid: str) -> bool:
        """True iff the token is valid for ``fid`` (or auth is off)."""
        if not self.enabled:
            return True
        try:
            header, payload, sig = token.split(".")
            signing_input = f"{header}.{payload}".encode()
            want = hmac.new(self.key, signing_input, hashlib.sha256).digest()
            if not hmac.compare_digest(want, _unb64(sig)):
                return False
            claims = json.loads(_unb64(payload))
            return (claims.get("fid") == fid
                    and claims.get("exp", 0) >= time.time())
        except (ValueError, KeyError, json.JSONDecodeError):
            return False


class _ClientCallDetails:
    """Minimal grpc.ClientCallDetails carrier for the auth interceptor."""

    __slots__ = ("method", "timeout", "metadata", "credentials",
                 "wait_for_ready", "compression")

    def __init__(self, base, metadata):
        self.method = base.method
        self.timeout = base.timeout
        self.metadata = metadata
        self.credentials = getattr(base, "credentials", None)
        self.wait_for_ready = getattr(base, "wait_for_ready", None)
        self.compression = getattr(base, "compression", None)


def grpc_sign(guard: Guard, ttl: int = 60) -> str:
    """Cluster-internal gRPC bearer token: same HS256 JWS as the write
    path, scoped "grpc" instead of a fid (the reference secures this
    plane with gRPC TLS; an env without cert plumbing uses the shared
    signing key — weed/security's Guard role extended to admin/read
    rpcs per SURVEY.md §2 Security)."""
    if not guard.enabled:
        return ""
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64(json.dumps({
        "scope": "grpc", "exp": int(time.time()) + ttl}).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = _b64(hmac.new(guard.key, signing_input, hashlib.sha256)
               .digest())
    return f"{header}.{payload}.{sig}"


def grpc_verify(guard: Guard, token: str) -> bool:
    if not guard.enabled:
        return True
    try:
        header, payload, sig = token.split(".")
        signing_input = f"{header}.{payload}".encode()
        want = hmac.new(guard.key, signing_input, hashlib.sha256).digest()
        if not hmac.compare_digest(want, _unb64(sig)):
            return False
        claims = json.loads(_unb64(payload))
        return (claims.get("scope") == "grpc"
                and claims.get("exp", 0) >= time.time())
    except (ValueError, KeyError, json.JSONDecodeError):
        return False


def grpc_server_interceptor(guard: Guard):
    """Server-side enforcement: every rpc must carry a valid bearer
    token once a key is configured. Returns None when auth is off."""
    import grpc

    if not guard.enabled:
        return None

    def deny(request, context):
        context.abort(grpc.StatusCode.UNAUTHENTICATED,
                      "missing or invalid grpc auth token")

    deny_handler = grpc.unary_unary_rpc_method_handler(deny)

    class _Auth(grpc.ServerInterceptor):
        def intercept_service(self, continuation, details):
            md = dict(details.invocation_metadata or ())
            tok = md.get("authorization", "")
            if tok.startswith("Bearer "):
                tok = tok[len("Bearer "):]
            if grpc_verify(guard, tok):
                return continuation(details)
            return deny_handler

    return _Auth()


def grpc_auth_channel(channel, guard: Guard):
    """Client-side: wrap a channel so every call carries a fresh bearer
    token. No-op when auth is off."""
    import grpc

    if not guard.enabled:
        return channel

    class _Attach(grpc.UnaryUnaryClientInterceptor,
                  grpc.UnaryStreamClientInterceptor,
                  grpc.StreamUnaryClientInterceptor,
                  grpc.StreamStreamClientInterceptor):
        def _details(self, cd):
            md = list(cd.metadata or [])
            md.append(("authorization", f"Bearer {grpc_sign(guard)}"))
            return _ClientCallDetails(cd, md)

        def intercept_unary_unary(self, cont, cd, req):
            return cont(self._details(cd), req)

        def intercept_unary_stream(self, cont, cd, req):
            return cont(self._details(cd), req)

        def intercept_stream_unary(self, cont, cd, it):
            return cont(self._details(cd), it)

        def intercept_stream_stream(self, cont, cd, it):
            return cont(self._details(cd), it)

    return grpc.intercept_channel(channel, _Attach())
