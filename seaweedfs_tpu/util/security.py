"""Write-path auth: HMAC-signed JWTs + cookies.

Mirrors weed/security (SURVEY.md §2 "Security"): when a signing key is
configured, the master attaches a short-lived token to each Assign
response (``GenJwt``) and volume servers verify it on writes/deletes
(``Guard``). Tokens are standard JWS compact HS256 — header.payload.sig
with base64url parts — built on hashlib/hmac so no external jwt
dependency is needed. An empty key disables enforcement, matching the
reference's default.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets
import time


def new_cookie() -> int:
    """Random 32-bit needle cookie (needle/file_id semantics)."""
    return secrets.randbits(32)


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class Guard:
    """Issues and checks HS256 tokens scoped to one file id."""

    def __init__(self, key: str = "", expires_seconds: int = 10):
        self.key = key.encode() if key else b""
        self.expires_seconds = expires_seconds

    @property
    def enabled(self) -> bool:
        return bool(self.key)

    def sign(self, fid: str) -> str:
        if not self.enabled:
            return ""
        header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        payload = _b64(json.dumps({
            "fid": fid,
            "exp": int(time.time()) + self.expires_seconds}).encode())
        signing_input = f"{header}.{payload}".encode()
        sig = _b64(hmac.new(self.key, signing_input, hashlib.sha256)
                   .digest())
        return f"{header}.{payload}.{sig}"

    def verify(self, token: str, fid: str) -> bool:
        """True iff the token is valid for ``fid`` (or auth is off)."""
        if not self.enabled:
            return True
        try:
            header, payload, sig = token.split(".")
            signing_input = f"{header}.{payload}".encode()
            want = hmac.new(self.key, signing_input, hashlib.sha256).digest()
            if not hmac.compare_digest(want, _unb64(sig)):
                return False
            claims = json.loads(_unb64(payload))
            return (claims.get("fid") == fid
                    and claims.get("exp", 0) >= time.time())
        except (ValueError, KeyError, json.JSONDecodeError):
            return False
