"""Subprocess environment scrubbing for the hostile ambient backend.

The driver launches ``bench.py`` and ``__graft_entry__`` under an
environment where a sitecustomize hook (`.axon_site` on PYTHONPATH,
triggered by ``PALLAS_AXON_POOL_IPS``) dials an exclusive TPU tunnel from
EVERY Python process and can hang at first backend init. This module is
the one shared recipe for building a child environment that provably
avoids that: drop the hook from PYTHONPATH, remove its trigger variable,
force the in-process CPU backend, and (optionally) force an exact
virtual CPU device count — overriding any stale ambient value.

Deliberately stdlib-only: the callers import it before any jax import.
"""

from __future__ import annotations

import os
import re

_DEVCOUNT_RE = re.compile(
    r"--xla_force_host_platform_device_count=\d+\s*")


def scrubbed_env(repo_root: str, n_cpu_devices: int = 0) -> dict:
    """A copy of ``os.environ`` safe for a CPU-only JAX child process.

    ``n_cpu_devices > 1`` forces exactly that many virtual CPU devices,
    replacing (not deferring to) any count latched in ambient
    ``XLA_FLAGS`` — a stale count would break a mesh dry run outright.
    """
    env = dict(os.environ)
    pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
          if p and "axon" not in p]
    pp.insert(0, repo_root)
    env["PYTHONPATH"] = os.pathsep.join(pp)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # sitecustomize trigger
    if n_cpu_devices > 1:
        flags = _DEVCOUNT_RE.sub("", env.get("XLA_FLAGS", "")).strip()
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{n_cpu_devices}").strip()
    return env
