"""``/debug/vars`` payload — the Go ``expvar`` analog.

Every HTTP server (master, volume, filer, S3, WebDAV) serves one JSON
document with process vitals (pid, uptime, RSS, CPU, threads, fds, GC)
plus the tracing slow-request ring, so "what is this process doing" is
one curl away without a metrics stack. Callers pass ``extra`` for
role-specific sections (the volume server attaches its telemetry
collector, the master its cluster registry).
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time
from typing import Optional

from . import profiler, tracing
from .stats import Metrics

try:
    import resource
except ImportError:  # non-unix: the /proc vitals still apply
    resource = None  # type: ignore[assignment]

_START_TIME = time.time()


def _pipeline_payload() -> dict:
    # lazy: the EC pipeline (and its jax import chain) must not load
    # just because a gateway served /debug/vars
    mod = sys.modules.get("seaweedfs_tpu.pipeline.pipe")
    if mod is None:
        return {}
    return mod.debug_payload()


def _flight_payload() -> dict:
    # lazy like the pipeline payload: only meaningful once the flight
    # recorder module is loaded (any pipeline import pulls it in)
    mod = sys.modules.get("seaweedfs_tpu.pipeline.flight")
    if mod is None:
        return {}
    return mod.debug_payload()


def _mesh_payload() -> dict:
    # lazy like the pipeline payload: parallel/mesh pulls in jax
    mod = sys.modules.get("seaweedfs_tpu.parallel.mesh")
    if mod is None:
        return {}
    return mod.debug_payload()


def _ingress_payload() -> dict:
    # lazy for the same reason — and httpserver imports stats only,
    # so this stays cheap even when no IngressHTTPServer exists
    mod = sys.modules.get("seaweedfs_tpu.util.httpserver")
    if mod is None:
        return {}
    return mod.debug_payload()


def _rss_bytes() -> Optional[int]:
    # /proc is authoritative on linux; ru_maxrss is a peak, not current
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None


def _open_fds() -> Optional[int]:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def payload(component: str, metrics: Optional[Metrics] = None,
            extra: Optional[dict] = None) -> dict:
    from . import faults, retry  # here, not top: retry imports varz users
    out = {
        "component": component,
        "pid": os.getpid(),
        "start_time": _START_TIME,
        "uptime_seconds": round(time.time() - _START_TIME, 3),
        "python_version": sys.version.split()[0],
        "argv": sys.argv,
        "threads": threading.active_count(),
        "gc_counts": gc.get_count(),
        "slow_requests": tracing.slow_requests(),
        "trace_push": tracing.push_stats(),
        "breakers": retry.breakers_payload(),
        "faults": faults.debug_payload(),
        "profiler": profiler.debug_payload(),
        "pipeline": _pipeline_payload(),
        "flight": _flight_payload(),
        "mesh": _mesh_payload(),
        "ingress": _ingress_payload(),
        "http_pool": retry.pool().payload(),
    }
    rss = _rss_bytes()
    if rss is not None:
        out["rss_bytes"] = rss
    fds = _open_fds()
    if fds is not None:
        out["open_fds"] = fds
    if resource is not None:
        ru = resource.getrusage(resource.RUSAGE_SELF)
        out["user_cpu_seconds"] = ru.ru_utime
        out["system_cpu_seconds"] = ru.ru_stime
    if metrics is not None:
        with metrics._lock:
            out["metric_series"] = len(metrics._metrics)
        out["metrics_namespace"] = metrics.namespace
    if extra:
        out.update(extra)
    return out
