"""Continuous sampling profiler (pure stdlib, flamegraph-ready).

A timer thread walks ``sys._current_frames()`` and folds every thread's
stack into a *collapsed stack* string — ``caller;...;leaf`` with frames
rendered ``file.py:function`` — the exact input format of Brendan
Gregg's ``flamegraph.pl`` / speedscope / pprof's collapsed importer.
Two modes share the sampling core:

* **always-on low rate** (default 1 Hz): a daemon thread aggregates
  into a bounded per-process table. The top-k hot stacks ride the
  heartbeat telemetry snapshot (``TelemetrySnapshot.hot_stacks``), so
  ``volume.heatmap`` on the master can answer *what code* is hot on a
  node without touching it. Cost is one frame walk per second —
  ``bench.py --profile-overhead`` holds it under the 5% bar.
* **on-demand burst**: ``GET /debug/profile?seconds=N`` on any server
  runs a dedicated high-rate (default 97 Hz) capture for N seconds and
  returns the collapsed text, piped straight into
  ``flamegraph.pl > out.svg``.

97 Hz, not 100: a sampling period that is coprime with common 10 ms /
100 ms timer loops avoids lockstep aliasing where every sample lands on
the same sleep (the pprof trick).

Configured by the ``[profiler]`` TOML block (see ``config.SCAFFOLDS``):
``enabled``, ``hz``, ``top_k``, ``max_stacks``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

#: On-demand capture limits: one burst may not exceed this wall time
#: (the handler thread blocks for the duration) or this rate.
MAX_SECONDS = 60.0
MAX_HZ = 250.0
DEFAULT_BURST_HZ = 97.0

_ENABLED = False
_HZ = 1.0
_TOP_K = 5
_MAX_STACKS = 512

_LOCK = threading.Lock()
#: collapsed stack -> sample count (always-on aggregate; bounded by
#: ``max_stacks`` — on overflow the rarest stacks are evicted).
_AGG: dict[str, int] = {}
_SAMPLES = 0          # total samples folded into _AGG
_EVICTED = 0          # stacks dropped by the bound
_STARTED_AT = 0.0
_THREAD: Optional[threading.Thread] = None
_STOP = threading.Event()

#: Thread idents whose stacks are never recorded (the samplers
#: themselves — a profiler that mostly profiles its own wait loop
#: drowns the signal).
_IGNORED_IDENTS: set = set()


def _frame_name(frame) -> str:
    code = frame.f_code
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


def _collapse(frame) -> str:
    """Root-first ``a;b;c`` collapsed form of one thread's stack."""
    parts = []
    while frame is not None:
        parts.append(_frame_name(frame))
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


def _sample_into(agg: dict, ignore: set) -> int:
    """One ``sys._current_frames()`` walk folded into ``agg``;
    returns the number of thread stacks recorded."""
    n = 0
    for ident, frame in sys._current_frames().items():
        if ident in ignore:
            continue
        stack = _collapse(frame)
        if stack:
            agg[stack] = agg.get(stack, 0) + 1
            n += 1
    return n


def _evict_locked() -> None:
    global _EVICTED
    if len(_AGG) <= _MAX_STACKS:
        return
    keep = sorted(_AGG.items(), key=lambda kv: kv[1],
                  reverse=True)[:_MAX_STACKS]
    _EVICTED += len(_AGG) - len(keep)
    _AGG.clear()
    _AGG.update(keep)


def _run() -> None:
    global _SAMPLES
    period = 1.0 / max(0.01, _HZ)
    while not _STOP.wait(period):
        with _LOCK:
            if not _ENABLED:
                return
            _sample_into(_AGG, _IGNORED_IDENTS)
            _SAMPLES += 1
            _evict_locked()


# --------------------------------------------------------------------------
# configuration / lifecycle
# --------------------------------------------------------------------------

def configure(enabled: Optional[bool] = None,
              hz: Optional[float] = None,
              top_k: Optional[int] = None,
              max_stacks: Optional[int] = None) -> None:
    """Apply settings; starts or stops the always-on sampler so a
    runtime toggle (the bench harness, a config reload) takes effect
    immediately."""
    global _ENABLED, _HZ, _TOP_K, _MAX_STACKS
    with _LOCK:
        if hz is not None:
            _HZ = min(float(hz), MAX_HZ)
        if top_k is not None:
            _TOP_K = max(1, int(top_k))
        if max_stacks is not None:
            _MAX_STACKS = max(8, int(max_stacks))
            _evict_locked()
        if enabled is not None:
            _ENABLED = bool(enabled)
    if enabled is not None:
        (ensure_started if _ENABLED else stop)()


def configure_from(conf: dict) -> None:
    """Apply a loaded TOML dict's ``[profiler]`` block (missing keys
    keep their current values)."""
    from . import config as config_mod
    configure(
        enabled=config_mod.lookup(conf, "profiler.enabled"),
        hz=config_mod.lookup(conf, "profiler.hz"),
        top_k=config_mod.lookup(conf, "profiler.top_k"),
        max_stacks=config_mod.lookup(conf, "profiler.max_stacks"))


def enabled() -> bool:
    return _ENABLED


def ensure_started() -> None:
    """Start the always-on sampler thread if enabled and not running
    (idempotent; every server calls this at boot)."""
    global _THREAD, _STARTED_AT
    if not _ENABLED:
        return
    with _LOCK:
        if _THREAD is not None and _THREAD.is_alive():
            return
        _STOP.clear()
        t = threading.Thread(target=_run, daemon=True,
                             name="profiler-sampler")
        _THREAD = t
        if not _STARTED_AT:
            _STARTED_AT = time.time()
    t.start()
    _IGNORED_IDENTS.add(t.ident)


def stop() -> None:
    global _THREAD
    _STOP.set()
    t = _THREAD
    if t is not None:
        t.join(timeout=2)
        _IGNORED_IDENTS.discard(t.ident)
    _THREAD = None


def reset() -> None:
    """Drop the always-on aggregate (tests, bench toggles)."""
    global _SAMPLES, _EVICTED
    with _LOCK:
        _AGG.clear()
        _SAMPLES = 0
        _EVICTED = 0


# --------------------------------------------------------------------------
# queries
# --------------------------------------------------------------------------

def hot_stacks(k: Optional[int] = None) -> list[tuple[str, int]]:
    """Top-k (collapsed_stack, samples) from the always-on aggregate,
    hottest first — what the heartbeat telemetry carries."""
    with _LOCK:
        items = sorted(_AGG.items(), key=lambda kv: kv[1], reverse=True)
    return items[:k if k is not None else _TOP_K]


def collapsed(agg: Optional[dict] = None) -> str:
    """Aggregate -> flamegraph-ready text, one ``stack count`` line per
    distinct stack, hottest first. Defaults to the always-on table."""
    if agg is None:
        with _LOCK:
            agg = dict(_AGG)
    items = sorted(agg.items(), key=lambda kv: kv[1], reverse=True)
    return "".join(f"{stack} {count}\n" for stack, count in items)


def profile(seconds: float, hz: float = DEFAULT_BURST_HZ) -> str:
    """Blocking on-demand capture: sample every thread at ``hz`` for
    ``seconds``, return collapsed-stack text. Runs on the caller's
    thread (the HTTP handler serving ``/debug/profile``), whose own
    stack is excluded — a burst that mostly shows itself waiting in
    ``profile()`` is noise."""
    seconds = min(max(0.05, float(seconds)), MAX_SECONDS)
    hz = min(max(1.0, float(hz)), MAX_HZ)
    period = 1.0 / hz
    ignore = set(_IGNORED_IDENTS)
    ignore.add(threading.get_ident())
    agg: dict[str, int] = {}
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        _sample_into(agg, ignore)
        time.sleep(period)
    return collapsed(agg)


def debug_payload() -> dict:
    """The profiler section of ``/debug/vars``."""
    with _LOCK:
        n_stacks = len(_AGG)
        samples = _SAMPLES
        evicted = _EVICTED
    return {
        "enabled": _ENABLED,
        "hz": _HZ,
        "top_k": _TOP_K,
        "samples": samples,
        "distinct_stacks": n_stacks,
        "evicted_stacks": evicted,
        "running": _THREAD is not None and _THREAD.is_alive(),
        "hot_stacks": [{"stack": s, "samples": c}
                       for s, c in hot_stacks()],
    }
