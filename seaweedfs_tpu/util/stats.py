"""Prometheus-text metrics registry.

Mirrors weed/stats (SURVEY.md §2 "Stats", §5 observability): counters,
gauges, and latency histograms addressable by name+labels, rendered in
Prometheus exposition format at each server's ``/metrics`` endpoint.
Self-contained (no prometheus client dependency).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_right
from typing import Iterable, Optional

DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: Content-Type servers must send on ``/metrics`` responses.
EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4"


def _escape_label_value(v: str) -> str:
    # Exposition-format escaping: backslash first, then quote/newline.
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def add(self, amount: float) -> None:
        with self._lock:
            self.value += float(amount)


class Histogram:
    def __init__(self, buckets: Iterable[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0
        #: bucket index -> (exemplar_id, value, unix_ts): the most
        #: recent exemplar observed per bucket (one slot per bucket
        #: keeps storage O(buckets), never O(observations)).
        self.exemplars: dict[int, tuple[str, float, float]] = {}
        self._lock = threading.Lock()

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        with self._lock:
            i = bisect_right(self.buckets, value)
            self.counts[i] += 1
            self.total += value
            self.n += 1
            if exemplar:
                self.exemplars[i] = (exemplar, value, time.time())


class Digest:
    """Mergeable fixed-size quantile sketch (centroid compaction).

    A simplified t-digest (Dunning & Ertl): samples are buffered, then
    compacted into at most ``max_centroids`` (mean, weight) pairs with
    a uniform per-centroid weight cap (the merging digest's ``k0``
    scale function), so no single centroid can smear more than
    ``~2/max_centroids`` of the rank space — the property that keeps
    body quantiles honest even on bimodal latency data where most of
    the mass piles into one narrow mode. Unlike a histogram the sketch
    is bucket-free, so digests produced on different servers can be
    shipped (proto/JSON) and :meth:`merge`\\ d at the master, and
    ``quantile(0.99)`` still interpolates real sample positions instead
    of bucket edges; 64 centroids bounds the rank error near 1.5% while
    the wire size stays ~1 KiB.

    Thread-safe; all public methods take the internal lock.
    """

    __slots__ = ("max_centroids", "_means", "_weights", "_buf",
                 "min", "max", "count", "sum", "_lock")

    def __init__(self, max_centroids: int = 64):
        if max_centroids < 2:
            raise ValueError("max_centroids must be >= 2")
        self.max_centroids = int(max_centroids)
        self._means: list[float] = []
        self._weights: list[float] = []
        self._buf: list[float] = []
        self.min = float("inf")
        self.max = float("-inf")
        self.count = 0
        self.sum = 0.0
        self._lock = threading.Lock()

    # -- ingest ---------------------------------------------------

    def add(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._buf.append(value)
            self.count += 1
            self.sum += value
            if value < self.min:
                self.min = value
            if value > self.max:
                self.max = value
            if len(self._buf) >= self.max_centroids:
                self._compact_locked()

    def merge(self, other: "Digest") -> None:
        """Fold ``other`` into this digest (other is not modified)."""
        with other._lock:
            means = list(other._means) + list(other._buf)
            weights = list(other._weights) + [1.0] * len(other._buf)
            omin, omax = other.min, other.max
            ocount, osum = other.count, other.sum
        if not ocount:
            return
        with self._lock:
            self._compact_locked()
            self._means += means
            self._weights += weights
            self.count += ocount
            self.sum += osum
            if omin < self.min:
                self.min = omin
            if omax > self.max:
                self.max = omax
            self._compact_locked()

    def _compact_locked(self) -> None:
        """Fold the sample buffer in and merge nearest centroid pairs
        until at most ``max_centroids`` remain."""
        if self._buf:
            self._means += self._buf
            self._weights += [1.0] * len(self._buf)
            self._buf = []
        n = len(self._means)
        if n <= self.max_centroids:
            if n > 1 and any(self._means[i] > self._means[i + 1]
                             for i in range(n - 1)):
                pairs = sorted(zip(self._means, self._weights))
                self._means = [m for m, _ in pairs]
                self._weights = [w for _, w in pairs]
            return
        pairs = sorted(zip(self._means, self._weights))
        # One merge pass with a uniform weight cap: accumulate adjacent
        # centroids while the running group stays under 2*total/k. A
        # closest-gap policy would instead pile dense-mode mass into
        # one mega-centroid and wreck mid-range quantiles on skewed
        # data; the cap bounds every centroid's rank footprint.
        total = sum(w for _, w in pairs)
        cap = 2.0 * total / self.max_centroids
        out: list[tuple[float, float]] = []
        m_acc, w_acc = pairs[0]
        for m, w in pairs[1:]:
            if w_acc + w <= cap:
                m_acc = (m_acc * w_acc + m * w) / (w_acc + w)
                w_acc += w
            else:
                out.append((m_acc, w_acc))
                m_acc, w_acc = m, w
        out.append((m_acc, w_acc))
        pairs = out
        while len(pairs) > self.max_centroids:
            # rare fallback (pathological weight layouts): merge the
            # closest adjacent pair until the budget holds
            best, gap = 0, float("inf")
            for i in range(len(pairs) - 1):
                d = pairs[i + 1][0] - pairs[i][0]
                if d < gap:
                    best, gap = i, d
            (m1, w1), (m2, w2) = pairs[best], pairs[best + 1]
            w = w1 + w2
            pairs[best:best + 2] = [((m1 * w1 + m2 * w2) / w, w)]
        self._means = [m for m, _ in pairs]
        self._weights = [w for _, w in pairs]

    # -- query ----------------------------------------------------

    def quantile(self, q: float) -> float:
        """Estimate the ``q``-quantile (0 <= q <= 1); NaN when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be in [0, 1]")
        with self._lock:
            self._compact_locked()
            if not self._means:
                return float("nan")
            if len(self._means) == 1:
                return self._means[0]
            total = sum(self._weights)
            target = q * total
            # centroid i covers the cumulative-weight interval around
            # its midpoint; interpolate between adjacent midpoints
            cum = 0.0
            mids = []
            for m, w in zip(self._means, self._weights):
                mids.append((cum + w / 2.0, m))
                cum += w
            # anchor the ends at the exact observed extremes
            pts = [(0.0, self.min)] + mids + [(total, self.max)]
            for i in range(len(pts) - 1):
                c0, m0 = pts[i]
                c1, m1 = pts[i + 1]
                if target <= c1:
                    if c1 == c0:
                        return m1
                    return m0 + (m1 - m0) * (target - c0) / (c1 - c0)
            return self.max

    def cdf(self, x: float) -> float:
        """Estimate the fraction of samples <= ``x`` (the inverse of
        :meth:`quantile`, same midpoint interpolation); NaN when empty.
        This is what lets an SLO engine turn a latency digest into a
        good/bad event ratio ("what fraction of reads beat 250 ms")."""
        x = float(x)
        with self._lock:
            self._compact_locked()
            if not self._means:
                return float("nan")
            if x < self.min:
                return 0.0
            if x >= self.max:
                return 1.0
            total = sum(self._weights)
            cum = 0.0
            pts = [(0.0, self.min)]
            for m, w in zip(self._means, self._weights):
                pts.append((cum + w / 2.0, m))
                cum += w
            pts.append((total, self.max))
            for i in range(len(pts) - 1):
                c0, m0 = pts[i]
                c1, m1 = pts[i + 1]
                if x <= m1:
                    if m1 == m0:
                        return c1 / total
                    return (c0 + (c1 - c0) * (x - m0) / (m1 - m0)) / total
            return 1.0

    def percentiles(self, *qs: float) -> dict[str, float]:
        """Convenience: {"p50": ..., "p99": ...} for the given qs."""
        return {"p" + ("%g" % (q * 100)).replace(".", "_"):
                self.quantile(q) for q in qs}

    # -- wire formats ---------------------------------------------

    def to_dict(self) -> dict:
        with self._lock:
            self._compact_locked()
            return {
                "means": list(self._means),
                "weights": list(self._weights),
                "min": self.min if self.count else 0.0,
                "max": self.max if self.count else 0.0,
                "count": self.count,
                "sum": self.sum,
            }

    @classmethod
    def from_dict(cls, d: dict,
                  max_centroids: int = 64) -> "Digest":
        dg = cls(max_centroids=max_centroids)
        dg._means = [float(m) for m in d.get("means", ())]
        dg._weights = [float(w) for w in d.get("weights", ())]
        dg.count = int(d.get("count", 0))
        dg.sum = float(d.get("sum", 0.0))
        if dg.count:
            dg.min = float(d["min"])
            dg.max = float(d["max"])
        return dg

    def to_proto(self):
        """Fill a fresh ``master_pb.DigestMessage`` (lazy import keeps
        stats.py usable without the pb package)."""
        from seaweedfs_tpu.pb import master_pb2

        d = self.to_dict()
        msg = master_pb2.DigestMessage(
            centroid_means=d["means"], centroid_weights=d["weights"],
            min=d["min"], max=d["max"], count=d["count"], sum=d["sum"])
        return msg

    @classmethod
    def from_proto(cls, msg, max_centroids: int = 64) -> "Digest":
        return cls.from_dict(
            {"means": list(msg.centroid_means),
             "weights": list(msg.centroid_weights),
             "min": msg.min, "max": msg.max,
             "count": msg.count, "sum": msg.sum},
            max_centroids=max_centroids)


class Metrics:
    """One registry per server process."""

    def __init__(self, namespace: str = "seaweedfs_tpu"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._metrics: dict[tuple[str, tuple, str], object] = {}
        from . import racecheck
        racecheck.register(self, "stats.Metrics")

    def _get(self, kind: str, name: str, labels: dict[str, str],
             factory):
        key = (name, tuple(sorted((labels or {}).items())), kind)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = factory()
                self._metrics[key] = m
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get("histogram", name, labels, Histogram)

    def render(self) -> str:
        """Prometheus exposition text."""
        lines: list[str] = []
        with self._lock:
            items = sorted(self._metrics.items(),
                           key=lambda kv: (kv[0][0], kv[0][1]))
        for (name, labels, kind), m in items:
            full = f"{self.namespace}_{name}"
            lab = _fmt_labels(dict(labels))
            if kind == "counter":
                lines.append(f"# TYPE {full} counter")
                lines.append(f"{full}{lab} {m.value}")
            elif kind == "gauge":
                lines.append(f"# TYPE {full} gauge")
                lines.append(f"{full}{lab} {m.value}")
            else:
                lines.append(f"# TYPE {full} histogram")
                cum = 0
                for b, c in zip(m.buckets, m.counts):
                    cum += c
                    le = dict(labels); le["le"] = "%g" % b
                    lines.append(f"{full}_bucket{_fmt_labels(le)} {cum}")
                le = dict(labels); le["le"] = "+Inf"
                lines.append(
                    f"{full}_bucket{_fmt_labels(le)} {m.n}")
                lines.append(f"{full}_sum{lab} {m.total}")
                lines.append(f"{full}_count{lab} {m.n}")
                # Exemplars ride as comment lines, NOT OpenMetrics
                # ``... # {trace_id=..}`` suffixes: the 0.0.4 text
                # format (and the strict mini parser the smoke scripts
                # run) treats unknown ``#`` lines as comments, so the
                # trace link is greppable without breaking any scraper.
                # trace_id here is an exemplar annotation, not a metric
                # label — cardinality stays one slot per bucket.
                for i in sorted(m.exemplars):
                    ex_id, ex_val, ex_ts = m.exemplars[i]
                    le = dict(labels)
                    le["le"] = ("%g" % m.buckets[i]
                                if i < len(m.buckets) else "+Inf")
                    lines.append(
                        f"# EXEMPLAR {full}_bucket{_fmt_labels(le)} "
                        f'trace_id="{_escape_label_value(ex_id)}" '
                        f"value={ex_val:g} ts={ex_ts:.3f}")
        return "\n".join(lines) + "\n"


class MetricsPusher:
    """Prometheus push-gateway client (weed/stats push mode): POSTs the
    text exposition to ``http://<addr>/metrics/job/<job>/instance/<i>``
    on an interval. Best-effort — an unreachable gateway is counted,
    never fatal, and the interval keeps ticking."""

    def __init__(self, metrics: "Metrics", address: str, job: str,
                 instance: str, interval_seconds: float = 15.0):
        import threading

        self.metrics = metrics
        self.address = address
        self.job = job
        self.instance = instance
        self.interval = max(1.0, interval_seconds)
        self.pushed = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        # stop()'s final flush runs on the caller's thread and the
        # join above it has a timeout: a hung push means BOTH threads
        # can be inside push_once at once, so the counters need a lock
        self._count_lock = threading.Lock()

    def start(self) -> "MetricsPusher":
        import threading

        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"metrics-push-{self.job}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        # Final best-effort push so metrics from the last interval
        # aren't lost at shutdown.
        self.push_once()

    def push_once(self) -> bool:
        import urllib.request

        url = (f"http://{self.address}/metrics/job/{self.job}"
               f"/instance/{self.instance}")
        body = self.metrics.render().encode()
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "text/plain"})
        try:
            # seaweedlint: disable=SW601 — best-effort fire-and-forget push to an out-of-cluster pushgateway: a breaker/retry would add queueing where dropping a sample is the correct behavior; bounded by the 5s timeout
            with urllib.request.urlopen(req, timeout=5):
                with self._count_lock:
                    self.pushed += 1
                return True
        except Exception:  # noqa: BLE001 — gateway may be down
            with self._count_lock:
                self.errors += 1
            return False

    def _run(self) -> None:
        # immediate first push, then the interval cadence
        while not self._stop.is_set():
            self.push_once()
            if self._stop.wait(self.interval):
                return
