"""Prometheus-text metrics registry.

Mirrors weed/stats (SURVEY.md §2 "Stats", §5 observability): counters,
gauges, and latency histograms addressable by name+labels, rendered in
Prometheus exposition format at each server's ``/metrics`` endpoint.
Self-contained (no prometheus client dependency).
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from typing import Iterable

DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: Content-Type servers must send on ``/metrics`` responses.
EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4"


def _escape_label_value(v: str) -> str:
    # Exposition-format escaping: backslash first, then quote/newline.
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def add(self, amount: float) -> None:
        with self._lock:
            self.value += float(amount)


class Histogram:
    def __init__(self, buckets: Iterable[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self.counts[bisect_right(self.buckets, value)] += 1
            self.total += value
            self.n += 1


class Metrics:
    """One registry per server process."""

    def __init__(self, namespace: str = "seaweedfs_tpu"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._metrics: dict[tuple[str, tuple, str], object] = {}

    def _get(self, kind: str, name: str, labels: dict[str, str],
             factory):
        key = (name, tuple(sorted((labels or {}).items())), kind)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = factory()
                self._metrics[key] = m
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get("histogram", name, labels, Histogram)

    def render(self) -> str:
        """Prometheus exposition text."""
        lines: list[str] = []
        with self._lock:
            items = sorted(self._metrics.items(),
                           key=lambda kv: (kv[0][0], kv[0][1]))
        for (name, labels, kind), m in items:
            full = f"{self.namespace}_{name}"
            lab = _fmt_labels(dict(labels))
            if kind == "counter":
                lines.append(f"# TYPE {full} counter")
                lines.append(f"{full}{lab} {m.value}")
            elif kind == "gauge":
                lines.append(f"# TYPE {full} gauge")
                lines.append(f"{full}{lab} {m.value}")
            else:
                lines.append(f"# TYPE {full} histogram")
                cum = 0
                for b, c in zip(m.buckets, m.counts):
                    cum += c
                    le = dict(labels); le["le"] = "%g" % b
                    lines.append(f"{full}_bucket{_fmt_labels(le)} {cum}")
                le = dict(labels); le["le"] = "+Inf"
                lines.append(
                    f"{full}_bucket{_fmt_labels(le)} {m.n}")
                lines.append(f"{full}_sum{lab} {m.total}")
                lines.append(f"{full}_count{lab} {m.n}")
        return "\n".join(lines) + "\n"


class MetricsPusher:
    """Prometheus push-gateway client (weed/stats push mode): POSTs the
    text exposition to ``http://<addr>/metrics/job/<job>/instance/<i>``
    on an interval. Best-effort — an unreachable gateway is counted,
    never fatal, and the interval keeps ticking."""

    def __init__(self, metrics: "Metrics", address: str, job: str,
                 instance: str, interval_seconds: float = 15.0):
        import threading

        self.metrics = metrics
        self.address = address
        self.job = job
        self.instance = instance
        self.interval = max(1.0, interval_seconds)
        self.pushed = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def start(self) -> "MetricsPusher":
        import threading

        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"metrics-push-{self.job}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        # Final best-effort push so metrics from the last interval
        # aren't lost at shutdown.
        self.push_once()

    def push_once(self) -> bool:
        import urllib.request

        url = (f"http://{self.address}/metrics/job/{self.job}"
               f"/instance/{self.instance}")
        body = self.metrics.render().encode()
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "text/plain"})
        try:
            with urllib.request.urlopen(req, timeout=5):
                self.pushed += 1
                return True
        except Exception:  # noqa: BLE001 — gateway may be down
            self.errors += 1
            return False

    def _run(self) -> None:
        # immediate first push, then the interval cadence
        while not self._stop.is_set():
            self.push_once()
            if self._stop.wait(self.interval):
                return
