"""Mutual TLS for the cluster gRPC plane.

Mirrors weed/security's gRPC TLS (security.toml ``[grpc]`` sections,
SURVEY.md §2 "Security": "JWT on writes ... gRPC TLS"): when
``security.toml`` carries a ``[grpc.tls]`` section, every gRPC server
in the process binds with ``ssl_server_credentials`` requiring client
certificates, and every channel dials with the cluster CA + its own
pair — so admin RPCs, vacuum choreography, and EC shard reads are both
encrypted and mutually authenticated (the round-3 verdict's "reads and
admin RPCs are open" gap; bearer tokens already scope WHAT a caller
may do, TLS now scopes WHO can speak at all).

Like the reference, TLS config is ambient per process (loaded once
from security.toml); ``install()`` sets it and the ``dial()`` /
``serve_port()`` helpers used by every gRPC call site pick it up. The
HTTP data plane stays plaintext exactly as the reference's does — its
protection is the JWT write path.

``generate_cluster_credentials`` writes a self-signed CA plus one
cluster pair (SAN: localhost/127.0.0.1) — the ``weed scaffold``-style
bootstrap for localhost clusters and tests.
"""

from __future__ import annotations

import datetime
import ipaddress
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

_LOCK = threading.Lock()
_INSTALLED: Optional["TlsConfig"] = None


@dataclass(frozen=True)
class TlsConfig:
    ca_cert: bytes
    cert: bytes
    key: bytes

    @classmethod
    def from_files(cls, ca: str | Path, cert: str | Path,
                   key: str | Path) -> "TlsConfig":
        return cls(ca_cert=Path(ca).read_bytes(),
                   cert=Path(cert).read_bytes(),
                   key=Path(key).read_bytes())

    def server_credentials(self):
        import grpc
        return grpc.ssl_server_credentials(
            [(self.key, self.cert)],
            root_certificates=self.ca_cert,
            require_client_auth=True)

    def channel_credentials(self):
        import grpc
        return grpc.ssl_channel_credentials(
            root_certificates=self.ca_cert,
            private_key=self.key,
            certificate_chain=self.cert)


def install(cfg: Optional[TlsConfig]) -> None:
    """Set (or clear) the process-global TLS config."""
    global _INSTALLED
    with _LOCK:
        _INSTALLED = cfg


def installed() -> Optional[TlsConfig]:
    return _INSTALLED


def install_from_config(conf: dict) -> bool:
    """Read security.toml's [grpc.tls] {ca, cert, key} paths; returns
    True when TLS was installed. An absent/empty section clears it; a
    PARTIAL section raises — silently falling back to plaintext when an
    operator misconfigured one path would defeat the whole point."""
    from . import config as config_mod
    ca = config_mod.lookup(conf, "grpc.tls.ca", "")
    cert = config_mod.lookup(conf, "grpc.tls.cert", "")
    key = config_mod.lookup(conf, "grpc.tls.key", "")
    present = [p for p in (ca, cert, key) if p]
    if present and len(present) < 3:
        raise ValueError(
            "[grpc.tls] must set all of ca/cert/key (or none); got "
            f"ca={ca!r} cert={cert!r} key={key!r}")
    if present:
        install(TlsConfig.from_files(ca, cert, key))
        return True
    install(None)
    return False


def add_security_flag(parser) -> None:
    """Attach the standard ``-securityConfig`` flag (security.toml path)
    to a client-tool argparser."""
    parser.add_argument(
        "-securityConfig", default="",
        help="security.toml ([grpc.tls] client credentials)")


def install_from_flag(args) -> None:
    """Install TLS from an argparse namespace carrying
    ``-securityConfig`` (no-op when the flag is empty)."""
    from . import config as config_mod
    path = getattr(args, "securityConfig", "")
    install_from_config(config_mod.load(path) if path else {})


def dial(target: str, options=None):
    """Open a gRPC channel honoring the installed TLS config. Every
    channel carries the active trace context in call metadata."""
    import grpc

    from . import tracing
    cfg = _INSTALLED
    if cfg is None:
        channel = grpc.insecure_channel(target, options=options)
    else:
        channel = grpc.secure_channel(target, cfg.channel_credentials(),
                                      options=options)
    return tracing.grpc_trace_channel(channel)


def serve_port(server, address: str) -> int:
    """Bind ``server`` on ``address`` with the installed TLS config
    (mTLS) or plaintext when none; returns the bound port."""
    cfg = _INSTALLED
    if cfg is None:
        return server.add_insecure_port(address)
    return server.add_secure_port(address, cfg.server_credentials())


# --------------------------------------------------------------------------
# scaffold: self-signed CA + cluster pair
# --------------------------------------------------------------------------

def generate_cluster_credentials(directory: str | Path,
                                 hosts: tuple[str, ...] = ("localhost",),
                                 ips: tuple[str, ...] = ("127.0.0.1",),
                                 days: int = 365) -> dict:
    """Write ca.crt/ca.key + cluster.crt/cluster.key under ``directory``
    and return their paths. One shared pair serves every component of a
    localhost cluster (the reference ships separate master/volume/filer
    pairs; the seam is the same, the inventory smaller)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)
    not_after = now + datetime.timedelta(days=days)

    def _name(cn: str):
        return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    def _write_key(path: Path, key) -> None:
        path.write_bytes(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()))
        path.chmod(0o600)  # private keys must not be world-readable

    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_cert = (x509.CertificateBuilder()
               .subject_name(_name("seaweedfs-tpu-ca"))
               .issuer_name(_name("seaweedfs-tpu-ca"))
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now).not_valid_after(not_after)
               .add_extension(x509.BasicConstraints(ca=True,
                                                    path_length=0),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))

    leaf_key = ec.generate_private_key(ec.SECP256R1())
    san = x509.SubjectAlternativeName(
        [x509.DNSName(h) for h in hosts]
        + [x509.IPAddress(ipaddress.ip_address(i)) for i in ips])
    leaf_cert = (x509.CertificateBuilder()
                 .subject_name(_name("seaweedfs-tpu-cluster"))
                 .issuer_name(ca_cert.subject)
                 .public_key(leaf_key.public_key())
                 .serial_number(x509.random_serial_number())
                 .not_valid_before(now).not_valid_after(not_after)
                 .add_extension(san, critical=False)
                 .sign(ca_key, hashes.SHA256()))

    paths = {
        "ca": directory / "ca.crt",
        "ca_key": directory / "ca.key",
        "cert": directory / "cluster.crt",
        "key": directory / "cluster.key",
    }
    paths["ca"].write_bytes(
        ca_cert.public_bytes(serialization.Encoding.PEM))
    _write_key(paths["ca_key"], ca_key)
    paths["cert"].write_bytes(
        leaf_cert.public_bytes(serialization.Encoding.PEM))
    _write_key(paths["key"], leaf_key)
    return {k: str(v) for k, v in paths.items()}
