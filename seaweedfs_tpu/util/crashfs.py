"""Torn-write crash simulation over the storage fd layer.

ALICE/CrashMonkey-style crash-state exploration without a VM: while a
:class:`CrashRecorder` is active, every file mutation under its root —
``open``-file writes/truncates (volume ``.idx``, vacuum ``.cpd/.cpx``,
disk-tier segments, ``.part`` downloads), ``os.pwrite`` (the
``DiskFile`` backend's positioned appends), ``os.replace``/``rename``,
``os.unlink`` and every ``os.fsync`` — is recorded in order. A fired
``crash`` fault spec (util/faults.py) raises :class:`SimulatedCrash`
through the workload and freezes the log. :meth:`CrashRecorder.replay`
then materializes what a power cut at that instant could legally leave
on disk, into a FRESH directory:

- ops made durable by a subsequent ``fsync`` of their file (renames,
  creates and unlinks: of their parent *directory*) are always applied
  — an fsync is a promise;
- unsynced ("volatile") ops survive only up to a seeded random cut,
  modeling how much of the page cache the disk had drained;
- applied volatile *data* writes may additionally be dropped
  independently (out-of-order persistence: a later write can reach the
  platter while an earlier one does not);
- the last applied volatile write may be **torn** at a 512-byte sector
  boundary (a partially persisted sector run).

The original root keeps the fully-applied state (writes really do hit
disk during recording — only the log is extra); the replay directory
is the crash state, which recovery code (volume load's
``check_volume_data_integrity``, vacuum's ``.cpd/.cpx`` state machine,
the store's orphan sweep) must bring back to a volume that serves
every acknowledged write byte-identically and never serves a torn
needle. tests/test_crashfs.py asserts exactly that across randomized
crashpoints and replay seeds.

Single recorder at a time, single-threaded workloads — this is a test
harness, not a production interposition layer.
"""

from __future__ import annotations

import builtins
import os
import random
import shutil
import threading
from pathlib import Path
from typing import Optional

from . import faults

SECTOR = 512


class SimulatedCrash(BaseException):
    """Raised through the workload when a `crash` fault fires under a
    recording. BaseException: crash must not be swallowed by the
    broad ``except Exception`` resilience handlers on the I/O paths —
    nothing in-process survives a power cut."""

    def __init__(self, point: str):
        super().__init__(f"simulated crash at {point}")
        self.point = point


class _Op:
    __slots__ = ("kind", "path", "a", "b", "durable", "vrank")

    def __init__(self, kind: str, path: str, a=None, b=None):
        self.kind = kind    # write | trunc | create | rename | unlink
        self.path = path    # rename: the SOURCE path (a = dest)
        self.a = a
        self.b = b
        self.durable = False
        self.vrank = -1

    def durability_key(self) -> str:
        """The path whose fsync persists this op: the file itself for
        content ops, the parent directory for namespace ops (rename/
        create/unlink live in the directory, not the file)."""
        if self.kind in ("write", "trunc"):
            return self.path
        p = self.a if self.kind == "rename" else self.path
        return os.path.dirname(p)


_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional["CrashRecorder"] = None


class _TrackedFile:
    """Thin proxy over a real writable file object that logs mutating
    calls to the active recorder. Reads, seeks and everything else
    delegate untouched."""

    def __init__(self, f, rec: "CrashRecorder", path: str):
        self._f = f
        self._rec = rec
        self._path = path
        rec._register_fd(f.fileno(), path)

    def write(self, data):
        pos = self._f.tell()
        n = self._f.write(data)
        self._rec._record(_Op("write", self._path, pos,
                              bytes(data[:n if n is not None
                                         else len(data)])))
        return n

    def writelines(self, lines):
        for line in lines:
            self.write(line)

    def truncate(self, size=None):
        if size is None:
            size = self._f.tell()
        out = self._f.truncate(size)
        self._rec._record(_Op("trunc", self._path, int(size)))
        return out

    def close(self):
        try:
            return self._f.close()
        finally:
            self._rec._unregister_fd(self._path)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __iter__(self):
        return iter(self._f)

    def __getattr__(self, name):
        return getattr(self._f, name)


class CrashRecorder:
    """Record every mutation under ``root``; replay a legal crash
    prefix into a fresh directory. Use as a context manager around the
    workload; arm a ``crash`` fault spec (``faults.inject("crash.
    append.dat", "crash#1")``) to pick the instant."""

    def __init__(self, root: str | Path):
        self.root = os.path.abspath(str(root))
        self.ops: list[_Op] = []
        self.crashed = False
        self.crash_point: Optional[str] = None
        self._recording = False
        self._lock = threading.Lock()
        self._fd_paths: dict[int, str] = {}
        self._snapshot: Optional[str] = None
        self._saved = {}

    # -- recording plumbing ----------------------------------------------

    def _mine(self, path) -> Optional[str]:
        try:
            p = os.path.abspath(os.fspath(path))
        except TypeError:
            return None
        if p == self.root or p.startswith(self.root + os.sep):
            return p
        return None

    def _record(self, op: _Op) -> None:
        with self._lock:
            if self._recording:
                self.ops.append(op)

    def _register_fd(self, fd: int, path: str) -> None:
        with self._lock:
            if self._recording:
                self._fd_paths[fd] = path

    def _unregister_fd(self, path: str) -> None:
        with self._lock:
            for fd, p in list(self._fd_paths.items()):
                if p == path:
                    del self._fd_paths[fd]

    # -- patched entry points --------------------------------------------

    def _open(self, file, mode="r", *args, **kwargs):
        real = self._saved["open"]
        p = self._mine(file)
        if p is None or not any(c in mode for c in "wax+"):
            return real(file, mode, *args, **kwargs)
        existed = os.path.exists(p)
        f = real(file, mode, *args, **kwargs)
        if "w" in mode or not existed:
            self._record(_Op("create", p))
        return _TrackedFile(f, self, p)

    def _os_open(self, path, flags, *args, **kwargs):
        fd = self._saved["os_open"](path, flags, *args, **kwargs)
        p = self._mine(path)
        if p is not None:
            if (flags & os.O_CREAT) and (flags & os.O_TRUNC):
                self._record(_Op("create", p))
            self._register_fd(fd, p)
        return fd

    def _os_close(self, fd):
        with self._lock:
            self._fd_paths.pop(fd, None)
        return self._saved["os_close"](fd)

    def _os_pwrite(self, fd, data, offset):
        n = self._saved["os_pwrite"](fd, data, offset)
        path = self._fd_paths.get(fd)
        if path is not None:
            self._record(_Op("write", path, int(offset),
                             bytes(data[:n])))
        return n

    def _os_fsync(self, fd):
        out = self._saved["os_fsync"](fd)
        path = self._fd_paths.get(fd)
        if path is not None:
            with self._lock:
                if self._recording:
                    for op in self.ops:
                        if op.durability_key() == path:
                            op.durable = True
        return out

    def _os_ftruncate(self, fd, size):
        out = self._saved["os_ftruncate"](fd, size)
        path = self._fd_paths.get(fd)
        if path is not None:
            self._record(_Op("trunc", path, int(size)))
        return out

    def _os_replace(self, src, dst, **kwargs):
        out = self._saved["os_replace"](src, dst, **kwargs)
        p = self._mine(dst)
        if p is not None:
            self._record(_Op("rename", os.path.abspath(os.fspath(src)),
                             p))
        return out

    def _os_unlink(self, path, **kwargs):
        out = self._saved["os_unlink"](path, **kwargs)
        p = self._mine(path)
        if p is not None:
            self._record(_Op("unlink", p))
        return out

    def _on_crash(self, point: str) -> None:
        with self._lock:
            self.crashed = True
            self.crash_point = point
            self._recording = False
        raise SimulatedCrash(point)

    # -- lifecycle --------------------------------------------------------

    def __enter__(self) -> "CrashRecorder":
        global _ACTIVE
        with _ACTIVE_LOCK:
            if _ACTIVE is not None:
                raise RuntimeError("a CrashRecorder is already active")
            _ACTIVE = self
        self._snapshot = self.root + ".crashfs-snapshot"
        shutil.rmtree(self._snapshot, ignore_errors=True)
        shutil.copytree(self.root, self._snapshot)
        self._saved = {
            "open": builtins.open, "os_open": os.open,
            "os_close": os.close, "os_pwrite": os.pwrite,
            "os_fsync": os.fsync, "os_ftruncate": os.ftruncate,
            "os_replace": os.replace, "os_rename": os.rename,
            "os_unlink": os.unlink,
        }
        builtins.open = self._open
        os.open = self._os_open
        os.close = self._os_close
        os.pwrite = self._os_pwrite
        os.fsync = self._os_fsync
        os.ftruncate = self._os_ftruncate
        os.replace = self._os_replace
        os.rename = self._os_replace
        os.unlink = self._os_unlink
        faults.set_crash_handler(self._on_crash)
        self._recording = True
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        with self._lock:
            self._recording = False
        faults.set_crash_handler(None)
        builtins.open = self._saved["open"]
        os.open = self._saved["os_open"]
        os.close = self._saved["os_close"]
        os.pwrite = self._saved["os_pwrite"]
        os.fsync = self._saved["os_fsync"]
        os.ftruncate = self._saved["os_ftruncate"]
        os.replace = self._saved["os_replace"]
        os.rename = self._saved["os_rename"]
        os.unlink = self._saved["os_unlink"]
        with _ACTIVE_LOCK:
            _ACTIVE = None

    # -- replay -----------------------------------------------------------

    def replay(self, dest: str | Path, seed: int = 0,
               tear_probability: float = 0.5,
               drop_probability: float = 0.25) -> Path:
        """Materialize one legal post-crash state into ``dest`` (wiped
        first). Deterministic per ``seed``; different seeds explore
        different legal states for the same recorded run."""
        if self._snapshot is None:
            raise RuntimeError("replay() before recording started")
        rng = random.Random(seed)
        dest = Path(os.path.abspath(str(dest)))
        shutil.rmtree(dest, ignore_errors=True)
        shutil.copytree(self._snapshot, dest)

        volatile = [op for op in self.ops if not op.durable]
        for i, op in enumerate(volatile):
            op.vrank = i
        cut = rng.randint(0, len(volatile))
        tear_last = rng.random() < tear_probability

        def target(p: str) -> str:
            rel = os.path.relpath(p, self.root)
            return str(dest) if rel == "." else str(dest / rel)

        for op in self.ops:
            if not op.durable:
                if op.vrank >= cut:
                    continue
                if (op.kind == "write" and op.vrank < cut - 1
                        and rng.random() < drop_probability):
                    continue  # out-of-order persistence lost this one
            data = op.b
            if (not op.durable and op.kind == "write"
                    and op.vrank == cut - 1 and tear_last):
                keep = rng.randrange(0, len(data) // SECTOR + 1) * SECTOR
                data = data[:keep]
                if not data:
                    continue
            try:
                if op.kind == "write":
                    tp = target(op.path)
                    os.makedirs(os.path.dirname(tp), exist_ok=True)
                    flags = os.O_WRONLY | os.O_CREAT
                    fd = os.open(tp, flags)
                    try:
                        os.pwrite(fd, data, op.a)
                    finally:
                        os.close(fd)
                elif op.kind == "trunc":
                    with open(target(op.path), "r+b") as f:
                        f.truncate(op.a)
                elif op.kind == "create":
                    tp = target(op.path)
                    os.makedirs(os.path.dirname(tp), exist_ok=True)
                    with open(tp, "wb"):
                        pass
                elif op.kind == "rename":
                    src = target(op.path)
                    if os.path.exists(src):
                        # seaweedlint: disable=SW901 — replaying a recorded crash state; durability is the point under test, not a property of the replay
                        os.replace(src, target(op.a))
                elif op.kind == "unlink":
                    Path(target(op.path)).unlink(missing_ok=True)
            except FileNotFoundError:
                # The op's file never materialized in this crash state
                # (its create/rename was itself dropped) — exactly the
                # cross-file reordering a real crash can expose.
                continue
        return dest

    def cleanup(self) -> None:
        if self._snapshot:
            shutil.rmtree(self._snapshot, ignore_errors=True)
            self._snapshot = None
