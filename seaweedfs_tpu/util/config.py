"""Configuration loading: flags > TOML > defaults.

Mirrors weed/util's viper-loaded TOML (SURVEY.md §5 "Config/flag
system"): each command's argparse flags are the primary surface; a TOML
file (``security.toml``-style sections) fills in cross-cutting settings;
hard defaults sit underneath. ``scaffold()`` prints a commented template
like ``weed scaffold``.
"""

from __future__ import annotations

import tomllib
from pathlib import Path

SCAFFOLDS = {
    "security": """\
# security.toml — JWT signing for write requests (weed scaffold analog).
[jwt.signing]
key = ""            # non-empty enables write JWT verification
expires_after_seconds = 10

# Mutual TLS for the gRPC plane (admin RPCs, EC shard reads). Generate a
# localhost CA + cluster pair with:  python -m seaweedfs_tpu tls.gen -dir certs
# All three paths set -> every gRPC server requires client certs and
# every channel dials with this CA + pair.
[grpc.tls]
ca = ""             # e.g. certs/ca.crt
cert = ""           # e.g. certs/cluster.crt
key = ""            # e.g. certs/cluster.key
""",
    "master": """\
# master.toml
[master.volume_growth]
copy_1 = 7
copy_2 = 6
copy_3 = 3
copy_other = 1
""",
}


def load(path: str | Path) -> dict:
    """Parse one TOML file into nested dicts; missing file -> {}."""
    p = Path(path)
    if not p.exists():
        return {}
    with open(p, "rb") as f:
        return tomllib.load(f)


def lookup(conf: dict, dotted: str, default=None):
    """conf['a']['b']['c'] via 'a.b.c', with default."""
    cur = conf
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


def scaffold(name: str) -> str:
    if name not in SCAFFOLDS:
        raise KeyError(f"no scaffold named {name!r}; "
                       f"have {sorted(SCAFFOLDS)}")
    return SCAFFOLDS[name]
