"""Configuration loading: flags > TOML > defaults.

Mirrors weed/util's viper-loaded TOML (SURVEY.md §5 "Config/flag
system"): each command's argparse flags are the primary surface; a TOML
file (``security.toml``-style sections) fills in cross-cutting settings;
hard defaults sit underneath. ``scaffold()`` prints a commented template
like ``weed scaffold``.
"""

from __future__ import annotations

from pathlib import Path

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:
        tomllib = None  # fall back to the subset parser below

SCAFFOLDS = {
    "security": """\
# security.toml — JWT signing for write requests (weed scaffold analog).
[jwt.signing]
key = ""            # non-empty enables write JWT verification
expires_after_seconds = 10

# Mutual TLS for the gRPC plane (admin RPCs, EC shard reads). Generate a
# localhost CA + cluster pair with:  python -m seaweedfs_tpu tls.gen -dir certs
# All three paths set -> every gRPC server requires client certs and
# every channel dials with this CA + pair.
[grpc.tls]
ca = ""             # e.g. certs/ca.crt
cert = ""           # e.g. certs/cluster.crt
key = ""            # e.g. certs/cluster.key
""",
    "master": """\
# master.toml
[master.volume_growth]
copy_1 = 7
copy_2 = 6
copy_3 = 3
copy_other = 1
""",
    "cache": """\
# cache.toml — tiered chunk cache for read paths (docs/cache.md).
[cache]
memory_bytes = 67108864          # in-memory tier capacity (64 MiB)
admission_max_fraction = 0.125   # reject blobs larger than this share
ttl_seconds = 0                  # 0 disables time-based expiry
protected_fraction = 0.8         # SLRU protected-segment share

[cache.disk]
dir = ""                         # empty disables the on-disk tier
capacity_bytes = 268435456       # 256 MiB across all segment files
segments = 4
""",
    "tracing": """\
# tracing.toml — end-to-end request tracing (docs/observability.md).
[tracing]
enabled = true                   # false strips all span bookkeeping
ring_size = 256                  # completed traces kept per process
slow_threshold_seconds = 1.0     # slower roots log a span-tree line
push_threshold_seconds = 1.0     # slower/errored roots push to master
collector_url = ""               # master host:port override (servers
                                 # that know their master set it)
collector_ring_size = 256        # stitched traces kept on the master
""",
    "telemetry": """\
# telemetry.toml — heartbeat-carried per-volume hot stats
# (docs/observability.md). Applies to volume servers; the master's
# registry always accepts whatever snapshots arrive.
[telemetry]
enabled = true                   # false makes the collector a no-op
""",
    "retry": """\
# retry.toml — unified resilience policy (docs/robustness.md).
[retry]
max_attempts = 4                 # per request, first try included
base_delay_seconds = 0.05        # full-jitter exponential backoff base
max_delay_seconds = 2.0          # backoff cap
request_timeout_seconds = 60.0   # default per-request deadline budget
failover_budget_seconds = 5.0    # cap on waiting out a master election

[retry.breaker]
failure_threshold = 5            # consecutive failures -> open
cooldown_seconds = 5.0           # open -> half-open probe delay

[retry.pool]
max_idle_per_host = 4            # parked keep-alive sockets per host
idle_seconds = 30.0              # parked longer than this -> redial
""",
    "ingress": """\
# ingress.toml — overload-resilient server core (docs/ingress.md).
# Applies to every HTTP listener (master, volume, filer, s3, webdav).
[ingress]
enabled = true                   # false = admit everything (bench A/B)
workers = 16                     # request-servicing threads per server
queue_depth = 64                 # dispatch backlog driving `pressure`
max_connections = 512            # accept cap; beyond it -> raw 429
keepalive_idle_seconds = 15.0    # parked idle conns reaped after this
keepalive_max_requests = 1000    # requests per connection before close
request_read_timeout_seconds = 30.0
shed_watermark = 0.75            # pressure >= this -> 429 Retry-After
retry_after_seconds = 1.0        # Retry-After hint on pressure sheds
min_deadline_seconds = 0.0       # X-Seaweed-Deadline <= this -> 504
""",
    "qos": """\
# qos.toml — per-tenant QoS at the S3 gateway (docs/ingress.md).
# Tenants are authenticated SigV4 identity names; unauthenticated
# traffic is the "anonymous" tenant. Priority 0 = guaranteed (never
# pressure-shed); higher priorities shed earlier as queue pressure
# rises (class threshold = watermark ** priority).
[qos]
enabled = true
default_class = "standard"       # class for unmapped tenants
watermark = 0.75                 # base of the priority shed ladder

[qos.class.gold]
priority = 0                     # guaranteed: only its own caps apply
rate_per_second = 0              # token-bucket refill; 0 = unlimited
burst = 0                        # bucket size; 0 = max(1, rate)
concurrency = 0                  # in-flight cap; 0 = unlimited

[qos.class.standard]
priority = 1
rate_per_second = 0
burst = 0
concurrency = 0

[qos.class.bronze]
priority = 2
rate_per_second = 50
burst = 100
concurrency = 16

[qos.tenant]
# alice = "gold"                 # identity name -> class name
# mallory = "bronze"
""",
    "pipeline": """\
# pipeline.toml — overlapped EC ingest plane (docs/pipeline.md).
[pipeline]
depth = 2                        # stage-queue depth (double buffering)
batch_bytes = 268435456          # max input bytes per device batch
grouped_batch_bytes = 67108864   # per-batch clamp while grouping
group_cap = 0                    # max batches/dispatch; 0 = env default
writer_threads = 4               # positioned shard-write pool width
writer_queue_depth = 4           # pending writes per writer thread
pool_buffers = 0                 # reusable host buffers; 0 = derive
feedback = true                  # latency-fed group-size controller
overlapped = true                # false = synchronous reference path
preallocate = true               # size shard files up front
double_buffer = false            # two-deep H2D lookahead (mesh path)
""",
    "flight": """\
# flight.toml — pipeline flight recorder (docs/pipeline.md).
# Per-batch lifecycle events (read/H2D/dispatch/D2H/write/recycle)
# into a bounded preallocated ring; export with `pipeline.dump -trace`
# and read the verdict with `pipeline.analyze`. SEAWEED_FLIGHT=1 arms
# it from the environment without a config file.
[flight]
enabled = false                  # arm the per-batch event recorder
capacity = 65536                 # ring slots (oldest events evicted)
""",
    "mesh": """\
# mesh.toml — explicit (dp, sp) device mesh for EC compute (docs/mesh.md).
# Disabled: multi-chip accelerators auto-shard, everything else takes
# the single-device host path. Enabled: encode/rebuild/batch shard over
# ALL local devices; dp*sp must equal the device count (0 = derive the
# most-square factorization). The -mesh shell flag overrides per command.
[mesh]
enabled = false
dp = 0                           # volume/batch axis; 0 = derive
sp = 0                           # stripe (byte-range) axis; 0 = derive
""",
    "profiler": """\
# profiler.toml — continuous sampling profiler (docs/observability.md).
[profiler]
enabled = true                   # always-on low-rate sampler thread
hz = 1.0                         # background sampling rate
top_k = 5                        # hot stacks carried on heartbeats
max_stacks = 512                 # distinct collapsed stacks retained
""",
    "slo": """\
# slo.toml — master-side SLO burn-rate engine (docs/observability.md).
# Latency objectives are "no more than 1% of ops slower than the
# target"; availability is the fraction of ops that must succeed.
# Burn rate = observed bad-event rate / budgeted bad-event rate,
# evaluated over fast (5m + 1h) and slow (6h) windows (SRE multiwindow
# multi-burn-rate alerting): fast windows page, the slow window warns.
[slo]
enabled = true
read_p99_ms = 250.0              # volume read latency target; 0 = off
write_p99_ms = 500.0             # volume write latency target; 0 = off
availability = 0.999             # min ok fraction; 0 = off
evaluation_interval_seconds = 5.0
fast_burn_threshold = 14.4       # burns 2% of a 30d budget in 1h
slow_burn_threshold = 6.0        # burns 5% of a 30d budget in 6h
fast_window_seconds = 300.0      # paired with fast_long_window
fast_long_window_seconds = 3600.0
slow_window_seconds = 21600.0
""",
    "storage": """\
# storage.toml — durability + scrub policy (docs/robustness.md).
# fsync: "commit" = every acknowledged write is fsynced (an ack means
# the bytes survive power loss); "batch" = group commits by bytes/age
# (bounded loss window); "off" = flush to the OS only (process-crash
# safe, not power-loss safe — the pre-durability-sweep behavior).
[storage]
fsync = "commit"
fsync_batch_bytes = 8388608      # batch mode: fsync every 8 MiB
fsync_batch_seconds = 1.0        # ... or every second, whichever first

# Background scrub (docs/robustness.md "Scrub & repair"): re-read data
# at rest, verify CRC/parity, quarantine + repair silent corruption.
[storage.scrub]
rate_bytes_per_second = 8388608  # token-bucket pacing (0 = unpaced)
""",
    "faults": """\
# faults.toml — deterministic fault injection (docs/robustness.md).
# Spec syntax: action[@probability][:param][#count], e.g.
#   "volume.read=error@0.5#10"   first 10 coin-flip wins raise
#   "filer.data=delay:0.2"       200 ms latency on every call
#   "ec.shard_read=truncate:0.5" shard reads return half the bytes
[faults]
enabled = false                  # master switch (SEAWEED_FAULTS too)
seed = 0                         # deterministic replay seed
inject = ""                      # "point=spec;point=spec;..."
""",
}


def load(path: str | Path) -> dict:
    """Parse one TOML file into nested dicts; missing file -> {}."""
    p = Path(path)
    if not p.exists():
        return {}
    if tomllib is not None:
        with open(p, "rb") as f:
            return tomllib.load(f)
    return _parse_toml_subset(p.read_text())


def _parse_toml_subset(text: str) -> dict:
    """Parser for the TOML subset the scaffolds use — ``[a.b]`` tables
    and string/int/float/bool scalars with ``#`` comments. Interpreters
    without tomllib (and without a tomli wheel) land here; anything
    fancier than the subset raises rather than mis-parsing."""
    root: dict = {}
    table = root
    for lineno, line in enumerate(text.splitlines(), 1):
        s = line.strip()
        if not s or s.startswith("#"):
            continue
        if s.startswith("[") and s.endswith("]"):
            table = root
            for part in s[1:-1].split("."):
                table = table.setdefault(part.strip(), {})
            continue
        key, eq, raw = s.partition("=")
        if not eq:
            raise ValueError(
                f"toml line {lineno}: expected key = value: {line!r}")
        table[key.strip()] = _parse_scalar(raw.strip(), lineno)
    return root


def _parse_scalar(raw: str, lineno: int):
    if raw.startswith('"'):
        end = raw.find('"', 1)
        while end != -1 and raw[end - 1] == "\\":
            end = raw.find('"', end + 1)
        if end == -1:
            raise ValueError(f"toml line {lineno}: unterminated string")
        return raw[1:end].replace('\\"', '"').replace("\\\\", "\\")
    raw = raw.split("#", 1)[0].strip()
    low = raw.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(raw, 0)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"toml line {lineno}: unsupported value {raw!r}") from None


def lookup(conf: dict, dotted: str, default=None):
    """conf['a']['b']['c'] via 'a.b.c', with default."""
    cur = conf
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


def scaffold(name: str) -> str:
    if name not in SCAFFOLDS:
        raise KeyError(f"no scaffold named {name!r}; "
                       f"have {sorted(SCAFFOLDS)}")
    return SCAFFOLDS[name]
