"""Eraser-style lockset race detector — the dynamic half of SW801.

Under ``SEAWEED_RACECHECK=1`` selected long-lived shared objects
(pipeline buffer pools, the writeback pool, stage stats, the metrics
registry, cache tiers, the ingress server) instrument themselves at
construction: their class is swapped for a subclass whose
``__setattr__`` reports every attribute write to a per-(object, attr)
state machine before storing the value. Held locks come from
lockcheck's per-thread ledger (``lockcheck.held_locks()``), so arming
racecheck implies arming lockcheck — only locks created under the
patched factories are visible.

The state machine is classic Eraser (Savage et al. 1997), per
(object, attribute):

  virgin ──first write (thread T)──> exclusive(T)
  exclusive(T) ──write by T──> exclusive(T)           (no cost)
  exclusive(T) ──read  by U──> shared, C := held(U)
  exclusive(T) ──write by U──> shared-modified, C := held(U)
  shared       ──write──>      shared-modified, C := C ∩ held
  shared/shared-modified ──access──> C := C ∩ held

C empty in shared-modified = no lock consistently protected the
attribute: a race report carrying BOTH stacks (the access that
installed the current state and the offending one). ``raise`` mode
(``SEAWEED_RACECHECK=raise``, used by tests) raises ``RaceViolation``
at the offending write; record mode logs through glog and keeps
going — ``races()`` returns everything observed, and the tier-1
conftest fails the session when it is non-empty.

Reads cannot be intercepted by ``__setattr__``; hot read paths may
call ``note_read(obj, attr)`` explicitly, and the exclusive→shared
edge is otherwise exercised by the tests. Happens-before edges a pure
lockset checker cannot see (thread join, pool handoff) are declared
with ``quiesce(obj)``: every attribute of the object returns to
virgin, exactly the "single writer per stage, read after join"
contract PipeStats documents.

Static counterpart: ``python -m seaweedfs_tpu.analysis`` (SW801-804).
"""

from __future__ import annotations

import _thread
import os
import sys
import threading
import weakref
from dataclasses import dataclass, field

from . import lockcheck

__all__ = ["install_from_env", "install", "uninstall", "enabled",
           "register", "note_read", "quiesce", "races", "reset",
           "RaceViolation", "RaceReport", "TRACKER"]

#: Attribute-name tokens that mark synchronization primitives; writing
#: a Lock/Event into a slot is how objects BECOME safe, not a race.
_SYNC_TOKENS = ("lock", "cond", "event", "sem")

_VIRGIN = "virgin"
_EXCLUSIVE = "exclusive"
_SHARED = "shared"
_SHARED_MOD = "shared-modified"


class RaceViolation(AssertionError):
    """An attribute's candidate lockset became empty."""


_sync_memo: dict[str, bool] = {}


def _sync_attr(name: str) -> bool:
    # memoized: runs on every instrumented attribute write, and the
    # attr-name population is the registered classes' fields (bounded)
    v = _sync_memo.get(name)
    if v is None:
        low = name.lower()
        # "_Class__attr" is a name-mangled private: those writes come
        # from class-internal protocols we do not control — e.g.
        # socketserver's _BaseServer__shutdown_request handshake,
        # which serve_forever and shutdown() flip from different
        # threads by design (GIL-atomic flag + Event). This repo's own
        # classes use single-underscore attrs, so nothing real hides
        # behind the exemption.
        v = name.startswith("__") or \
            (name.startswith("_") and "__" in name[1:]) or \
            any(t in low for t in _SYNC_TOKENS)
        _sync_memo[name] = v
    return v


def _capture_stack(limit: int = 6) -> tuple:
    """Raw (file, line, func) frames of the caller, cheapest possible:
    ``traceback.format_stack`` costs tens of microseconds and EVERY
    off-fast-path access must capture its stack (a lock-protected
    cross-thread counter stays off the fast path forever — the 5%
    encode-overhead budget dies by formatting). Formatting happens in
    :func:`_render_stack`, only when a report actually fires."""
    try:
        f = sys._getframe(2)
    except ValueError:
        return ()
    # skip the tracker's own frames (__setattr__/note_read -> on_* ->
    # _transition) whichever entry path was taken
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    out = []
    while f is not None and len(out) < limit:
        out.append((f.f_code.co_filename, f.f_lineno,
                    f.f_code.co_name))
        f = f.f_back
    return tuple(out)


def _render_stack(frames: tuple) -> str:
    return "".join(
        f'  File "{fn}", line {ln}, in {name}\n'
        for fn, ln, name in reversed(frames))


@dataclass
class _AttrState:
    state: str = _VIRGIN
    owner: int = 0                      # thread ident while exclusive
    lockset: frozenset = frozenset()    # candidate lockset C
    stack: tuple = ()                   # raw frames of last access
    thread: str = ""
    reported: bool = False


@dataclass
class RaceReport:
    obj: str
    attr: str
    thread: str
    stack: str
    prior_thread: str
    prior_stack: str

    def describe(self) -> str:
        return (f"unsynchronized access: attribute '{self.attr}' of "
                f"{self.obj} has an empty candidate lockset.\n"
                f"--- this write ({self.thread}):\n{self.stack}"
                f"--- earlier access ({self.prior_thread}):\n"
                f"{self.prior_stack}")


@dataclass
class _RaceTracker:
    states: dict = field(default_factory=dict)
    names: dict = field(default_factory=dict)
    reports: list = field(default_factory=list)
    raise_on_race: bool = False

    def __post_init__(self):
        # raw C lock: instrumented writes happen on every thread and
        # the tracker must never recurse through a TrackedLock
        self._mu = _thread.allocate_lock()

    # -- state machine -----------------------------------------------

    def _describe(self, obj) -> str:
        return self.names.get(id(obj)) or \
            f"{type(obj).__module__}.{type(obj).__name__}"

    def _transition(self, obj, attr: str, write: bool):
        key = (id(obj), attr)
        tid = threading.get_ident()
        # Lock-free fast paths for the two steady states that dominate
        # armed hot loops (GIL-atomic dict/attr reads; a stale read at
        # worst falls through to the locked slow path). Without these,
        # a lock-protected cross-thread counter — permanently
        # shared-modified — would pay _mu contention plus a stack
        # capture on EVERY write, and the <5% encode-overhead budget
        # (bench.py --racecheck-overhead) is unmeetable.
        st = self.states.get(key)
        if st is not None:
            state = st.state
            if state == _EXCLUSIVE:
                if tid == st.owner:
                    return None         # same owner: nothing changes
            elif state == _SHARED_MOD or (state == _SHARED
                                          and not write):
                if st.reported:
                    return None         # one report per attr
                cl = st.lockset
                if cl:
                    # this thread's own held list, read in place (only
                    # the owning thread ever mutates it); plain loops,
                    # no generator allocation, C is typically one lock
                    held = lockcheck.TRACKER._held()
                    for lid in cl:
                        for h in held:
                            if id(h) == lid:
                                break
                        else:
                            break       # a C lock is not held: slow path
                    else:
                        # C ∩ held == C: no state, lockset, or report
                        # change. The stack snapshot goes stale — a
                        # later report shows the access that last
                        # CHANGED the state, which is the useful one.
                        return None
        hit = None
        with self._mu:
            st = self.states.get(key)
            if st is not None and st.state == _EXCLUSIVE \
                    and tid == st.owner:
                return None
            # off the fast path only: snapshot this thread's locks
            held = frozenset(id(l) for l in lockcheck.held_locks())
            if st is None:
                self.states[key] = _AttrState(
                    _EXCLUSIVE, tid, held, _capture_stack(),
                    threading.current_thread().name)
                return None
            if st.state == _EXCLUSIVE:
                st.state = _SHARED_MOD if write else _SHARED
                st.lockset = held
            else:
                if write:
                    st.state = _SHARED_MOD
                st.lockset = st.lockset & held
            if st.state == _SHARED_MOD and not st.lockset \
                    and not st.reported:
                st.reported = True
                hit = RaceReport(
                    obj=self._describe(obj), attr=attr,
                    thread=threading.current_thread().name,
                    stack=_render_stack(_capture_stack()),
                    prior_thread=st.thread,
                    prior_stack=_render_stack(st.stack))
                self.reports.append(hit)
            st.stack = _capture_stack()
            st.thread = threading.current_thread().name
        if hit is not None:
            if self.raise_on_race:
                raise RaceViolation(hit.describe())
            from . import glog
            glog.warning("racecheck: %s", hit.describe())
        return hit

    def on_write(self, obj, attr: str):
        if _sync_attr(attr):
            return None
        return self._transition(obj, attr, write=True)

    def on_read(self, obj, attr: str):
        if _sync_attr(attr):
            return None
        return self._transition(obj, attr, write=False)

    def purge(self, oid: int) -> None:
        with self._mu:
            for key in [k for k in self.states if k[0] == oid]:
                del self.states[key]
            self.names.pop(oid, None)


TRACKER = _RaceTracker()

#: original class -> instrumented subclass
_instrumented: dict[type, type] = {}

_installed = False


def _instrument_class(cls: type) -> type:
    icls = _instrumented.get(cls)
    if icls is None:
        def __setattr__(self, name, value, _base=cls):
            # store first: a detected race HAS happened either way,
            # and record mode must not alter program behavior
            _base.__setattr__(self, name, value)
            TRACKER.on_write(self, name)

        icls = type(cls.__name__, (cls,), {
            "__setattr__": __setattr__,
            "_racecheck_base": cls,
        })
        icls.__module__ = cls.__module__
        icls.__qualname__ = cls.__qualname__
        _instrumented[cls] = icls
    return icls


def enabled() -> bool:
    return _installed


def install(raise_on_race: bool = False) -> None:
    """Arm the checker (idempotent). Implies lockcheck, which supplies
    the per-thread held-locks ledger."""
    global _installed
    TRACKER.raise_on_race = raise_on_race
    if not lockcheck.enabled():
        lockcheck.install()
    _installed = True


def uninstall() -> None:
    """Stop registering new objects. Already-instrumented objects keep
    their subclass and keep reporting (mirrors lockcheck)."""
    global _installed
    _installed = False


def install_from_env() -> bool:
    """Honor SEAWEED_RACECHECK: "1"/"record" records, "raise" also
    raises RaceViolation at the offending write."""
    mode = os.environ.get("SEAWEED_RACECHECK", "").strip().lower()
    if mode in ("1", "true", "record", "on"):
        install(raise_on_race=False)
    elif mode == "raise":
        install(raise_on_race=True)
    return _installed


def register(obj, name: str | None = None) -> bool:
    """Instrument one object's attribute writes. No-op (False) when
    the checker is disarmed — THE fast path: construction sites call
    this unconditionally and pay one module-global flag test.

    Objects whose layout forbids ``__class__`` assignment (slots-only
    classes, C extensions) are skipped, not errors."""
    if not _installed:
        return False
    cls = type(obj)
    if getattr(cls, "_racecheck_base", None) is not None:
        return True                     # already instrumented
    try:
        obj.__class__ = _instrument_class(cls)
    except TypeError:
        return False
    TRACKER.names[id(obj)] = name or \
        f"{cls.__module__}.{cls.__qualname__}"
    # not weakref-able: per-attr state outlives the object (bounded
    # by the handful of registered singletons, so acceptable)
    try:
        weakref.finalize(obj, TRACKER.purge, id(obj))
    except TypeError:  # seaweedlint: disable=SW301 — tracking stays correct, only cleanup is lost
        pass
    return True


def note_read(obj, attr: str):
    """Record a read-side access (``__setattr__`` cannot see reads).
    Drives exclusive -> shared and refines the candidate lockset."""
    if not _installed and not TRACKER.states:
        return None
    return TRACKER.on_read(obj, attr)


def quiesce(obj) -> None:
    """Declare a happens-before point for every attribute of ``obj``
    (thread join, pool handoff): states return to virgin so the next
    writer starts a fresh exclusive epoch instead of racing history."""
    TRACKER.purge(id(obj))
    # keep the display name: the object stays registered
    cls = type(obj)
    base = getattr(cls, "_racecheck_base", None)
    if base is not None:
        TRACKER.names[id(obj)] = f"{base.__module__}.{base.__qualname__}"


def races() -> list[RaceReport]:
    return list(TRACKER.reports)


def reset() -> None:
    """Clear all state machines and reports (tests)."""
    with TRACKER._mu:
        TRACKER.states.clear()
        TRACKER.reports.clear()
