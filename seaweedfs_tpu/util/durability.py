"""Durability policy: one barrier for every storage commit point.

Before this module, each commit path chose its own discipline —
``storage/volume.py`` flushed the .dat without fsync while fsyncing the
.idx, ``cache/disk_tier.py`` only flushed, and the rename-into-place
sites (vacuum's two-phase swap, tier sidecars/downloads, replica file
copies) never fsynced the parent directory, so a power cut could lose
an acknowledged write or leave a rename un-persisted. Now every commit
point calls one of three helpers and the policy lives in a single
``[storage]`` TOML block:

- :func:`barrier` — "this write is a commit point": flush + fsync under
  the ``commit`` policy, accumulate-and-batch under ``batch``, flush
  only under ``off``.
- :func:`fsync_dir` — persist a directory entry (required after any
  rename/create/unlink that must survive power loss; fsyncing the file
  alone does NOT persist its name on most filesystems).
- :func:`durable_replace` — the full rename-commit idiom: fsync the
  source file, ``os.replace`` it into place, fsync the destination's
  parent directory. seaweedlint's SW901 rule flags rename commit
  points that skip either fsync.

Policy (``[storage] fsync``):

- ``commit`` (default): every barrier fsyncs. An acknowledged write is
  durable — the invariant the crash-recovery tests
  (tests/test_crashfs.py) assert.
- ``batch``: barriers accumulate per-fd byte counts and fsync when
  ``fsync_batch_bytes`` accumulate or ``fsync_batch_seconds`` elapse.
  Bounded-loss mode for ingest-heavy deployments.
- ``off``: flush to the OS only (the pre-PR 20 behavior): crash-safe
  against process death, not against power loss.

``durable_replace``/``fsync_dir`` always run regardless of policy —
rename commit points are rare and cheap relative to what they protect
(a vacuum's whole compacted volume, a tier download).
"""

from __future__ import annotations

import os
import threading
import time
from pathlib import Path
from typing import Optional

MODES = ("commit", "batch", "off")

_LOCK = threading.Lock()
_MODE = "commit"
_BATCH_BYTES = 8 * 1024 * 1024
_BATCH_SECONDS = 1.0
#: fd -> [accumulated bytes, last fsync monotonic time] (batch mode).
_PENDING: dict[int, list] = {}


def configure(mode: Optional[str] = None,
              batch_bytes: Optional[int] = None,
              batch_seconds: Optional[float] = None) -> None:
    global _MODE, _BATCH_BYTES, _BATCH_SECONDS
    with _LOCK:
        if mode is not None:
            if mode not in MODES:
                raise ValueError(
                    f"unknown fsync mode {mode!r}; have "
                    f"{', '.join(MODES)}")
            _MODE = mode
        if batch_bytes is not None:
            _BATCH_BYTES = int(batch_bytes)
        if batch_seconds is not None:
            _BATCH_SECONDS = float(batch_seconds)
        if mode is not None:
            _PENDING.clear()


def configure_from(conf: dict) -> None:
    """Apply a loaded TOML dict's ``[storage]`` block."""
    from . import config as config_mod
    configure(
        mode=config_mod.lookup(conf, "storage.fsync"),
        batch_bytes=config_mod.lookup(conf, "storage.fsync_batch_bytes"),
        batch_seconds=config_mod.lookup(
            conf, "storage.fsync_batch_seconds"))


def mode() -> str:
    return _MODE


def barrier(f, nbytes: int = 0) -> None:
    """Commit barrier on an open file. ``f`` is either a file object
    (flushed first) or a raw fd. Under ``commit`` this fsyncs; under
    ``batch`` it fsyncs once the per-fd byte/age budget is spent;
    under ``off`` it only flushes."""
    if hasattr(f, "flush"):
        f.flush()
        fd = f.fileno()
    else:
        fd = f
    if _MODE == "off":
        return
    if _MODE == "commit":
        os.fsync(fd)
        return
    now = time.monotonic()
    with _LOCK:
        acc = _PENDING.setdefault(fd, [0, now])
        acc[0] += max(0, int(nbytes))
        due = (acc[0] >= _BATCH_BYTES
               or now - acc[1] >= _BATCH_SECONDS)
        if due:
            _PENDING.pop(fd, None)
    if due:
        os.fsync(fd)


def drain(f) -> None:
    """Force out any batched-but-unsynced bytes on ``f`` (close paths,
    seals). A no-op under ``commit``/``off`` beyond a plain fsync."""
    if hasattr(f, "flush"):
        f.flush()
        fd = f.fileno()
    else:
        fd = f
    with _LOCK:
        _PENDING.pop(fd, None)
    if _MODE != "off":
        os.fsync(fd)


def fsync_dir(path: str | Path) -> None:
    """Persist a directory's entries after a rename/create/unlink in
    it. Directories cannot be opened for writing; O_RDONLY is the
    portable fsync handle. Platforms whose directory handles refuse
    fsync (some network filesystems) degrade to a no-op."""
    fd = os.open(str(path), os.O_RDONLY)
    try:
        try:
            os.fsync(fd)
        except OSError:  # seaweedlint: disable=SW301 — documented degrade: some network filesystems refuse directory fsync; the rename itself still happened
            pass
    finally:
        os.close(fd)


def durable_replace(src: str | Path, dst: str | Path,
                    fsync_src: bool = True) -> None:
    """Atomically rename ``src`` over ``dst`` such that the rename —
    and the bytes it publishes — survive power loss: fsync the source
    file's contents, rename, then fsync the destination's parent
    directory (the rename itself lives in the directory, not the
    file)."""
    src, dst = str(src), str(dst)
    if fsync_src:
        fd = os.open(src, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    os.replace(src, dst)
    fsync_dir(os.path.dirname(os.path.abspath(dst)) or ".")


def debug_payload() -> dict:
    with _LOCK:
        return {"mode": _MODE, "batch_bytes": _BATCH_BYTES,
                "batch_seconds": _BATCH_SECONDS,
                "pending_fds": len(_PENDING)}
