"""Runtime lock-order recorder — the dynamic half of seaweedlint.

ThreadSanitizer-style happens-before-order checking for locks, scoped
to this project: under ``SEAWEED_LOCKCHECK=1`` the ``threading.Lock`` /
``threading.RLock`` factories are wrapped so that every lock *created
by seaweedfs_tpu code* (decided by the creator's module at allocation
time — third-party and stdlib locks are never touched) is tracked.

Each acquisition records edges "lock at site A was held while lock at
site B was acquired" into one process-global order graph, keyed by the
locks' CREATION SITES (file:line), not object ids — so two ChunkCache
instances locked in opposite orders by two threads are reported as a
potential deadlock even if no actual deadlock happened on this run,
which is exactly the ordering discipline a single execution can check
that a static analyzer cannot prove.

An observed inversion (edge B→A recorded when A→B already exists) is a
violation: always recorded (``violations()``), raised immediately as
``LockOrderViolation`` under ``SEAWEED_LOCKCHECK=raise``. The tier-1
suite enables record mode in tests/conftest.py and fails the session
if any violation was observed (see ``pytest_sessionfinish`` there).

Static counterpart: ``python -m seaweedfs_tpu.analysis`` (SW101/SW102).
"""

from __future__ import annotations

import _thread
import os
import sys
import threading
import traceback
from dataclasses import dataclass, field

__all__ = ["install_from_env", "install", "uninstall", "enabled",
           "violations", "reset", "held_locks", "LockOrderViolation",
           "TrackedLock", "TRACKER"]

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

#: Wrap only locks allocated from these module prefixes.
_SCOPE_PREFIXES = ("seaweedfs_tpu",)


class LockOrderViolation(AssertionError):
    """Two locks were observed acquired in both orders."""


@dataclass
class Violation:
    first: str          # creation site of the lock acquired second
    second: str         # creation site of the lock being acquired
    thread: str
    stack: str
    prior_stack: str    # where the opposite order was recorded

    def describe(self) -> str:
        return (f"lock-order inversion: {self.second} acquired while "
                f"holding {self.first}, but the opposite order was "
                f"seen before.\n--- this acquisition "
                f"({self.thread}):\n{self.stack}"
                f"--- prior opposite-order site:\n{self.prior_stack}")


def _short_stack(skip: int = 3, limit: int = 6) -> str:
    frames = traceback.format_stack()[:-skip]
    return "".join(frames[-limit:])


@dataclass
class _Tracker:
    #: (site_held, site_acquired) -> stack where first recorded
    edges: dict = field(default_factory=dict)
    violations_list: list = field(default_factory=list)
    raise_on_violation: bool = False

    def __post_init__(self):
        # raw C lock: the tracker must never recurse into itself
        self._mu = _thread.allocate_lock()
        self._tls = threading.local()

    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = []
            self._tls.held = h
        return h

    def on_acquired(self, lock: "TrackedLock") -> None:
        held = self._held()
        if any(entry is lock for entry in held):
            held.append(lock)   # reentrant re-acquire: no new edges
            return
        site = lock._site
        hit: Violation | None = None
        with self._mu:
            for h in held:
                hs = h._site
                if hs == site:
                    continue    # sibling from the same allocation site
                fwd, rev = (hs, site), (site, hs)
                if fwd in self.edges:
                    continue    # steady state: no stack capture, no cost
                if rev in self.edges:
                    hit = Violation(
                        first=hs, second=site,
                        thread=threading.current_thread().name,
                        stack=_short_stack(),
                        prior_stack=self.edges[rev])
                    self.violations_list.append(hit)
                self.edges[fwd] = _short_stack()
        held.append(lock)
        if hit is not None and self.raise_on_violation:
            raise LockOrderViolation(hit.describe())

    def on_released(self, lock: "TrackedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def on_released_all(self, lock: "TrackedLock") -> None:
        held = self._held()
        held[:] = [entry for entry in held if entry is not lock]


TRACKER = _Tracker()


class TrackedLock:
    """Delegating wrapper around a real Lock/RLock.

    Implements the full lock protocol plus the private
    ``_release_save`` / ``_acquire_restore`` / ``_is_owned`` trio so a
    ``threading.Condition`` built on a tracked RLock still releases
    every recursion level across ``wait()``.
    """

    __slots__ = ("_inner", "_site", "_kind")

    def __init__(self, inner, site: str, kind: str):
        self._inner = inner
        self._site = site
        self._kind = kind

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            TRACKER.on_acquired(self)
        return ok

    def release(self) -> None:
        TRACKER.on_released(self)
        self._inner.release()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # --- Condition integration (RLock protocol) ---

    def _release_save(self):
        TRACKER.on_released_all(self)
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        TRACKER.on_acquired(self)

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain Lock fallback mirroring threading.Condition's own trick
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self) -> str:
        return f"<TrackedLock {self._kind} from {self._site}>"


def _make_factory(orig, kind: str):
    def factory(*args, **kwargs):
        inner = orig(*args, **kwargs)
        frame = sys._getframe(1)
        mod = frame.f_globals.get("__name__", "")
        if not mod.startswith(_SCOPE_PREFIXES):
            return inner
        site = f"{mod}:{frame.f_lineno}"
        return TrackedLock(inner, site, kind)
    factory._seaweed_lockcheck = True  # idempotence marker
    return factory


_installed = False


def enabled() -> bool:
    return _installed


def install(raise_on_violation: bool = False) -> None:
    """Patch the threading.Lock/RLock factories (idempotent)."""
    global _installed
    if _installed:
        TRACKER.raise_on_violation = raise_on_violation
        return
    TRACKER.raise_on_violation = raise_on_violation
    threading.Lock = _make_factory(_ORIG_LOCK, "Lock")
    threading.RLock = _make_factory(_ORIG_RLOCK, "RLock")
    _installed = True


def uninstall() -> None:
    """Restore the original factories. Locks already created stay
    tracked (they keep working; they just keep reporting)."""
    global _installed
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    _installed = False


def install_from_env() -> bool:
    """Honor SEAWEED_LOCKCHECK: "1"/"record" records, "raise" also
    raises LockOrderViolation at the offending acquire."""
    mode = os.environ.get("SEAWEED_LOCKCHECK", "").strip().lower()
    if mode in ("1", "true", "record", "on"):
        install(raise_on_violation=False)
    elif mode == "raise":
        install(raise_on_violation=True)
    return _installed


def held_locks() -> tuple:
    """TrackedLocks the CALLING thread currently holds, in acquisition
    order (reentrant acquires appear once per level). The racecheck
    lockset checker reads this to compute candidate locksets; empty
    whenever lockcheck was never installed, since only locks created
    under the patched factories are tracked."""
    return tuple(TRACKER._held())


def violations() -> list[Violation]:
    return list(TRACKER.violations_list)


def reset() -> None:
    """Clear the recorded graph and violations (tests)."""
    with TRACKER._mu:
        TRACKER.edges.clear()
        TRACKER.violations_list.clear()
