"""Offline volume tools: ``weed fix`` and ``weed export``.

Mirrors weed/command/fix.go (rebuild a lost/corrupt .idx by walking the
.dat's needle records) and weed/command/export.go (dump a volume's live
needles to a tar archive, or list them). Both operate on files
directly — no servers involved.
"""

from __future__ import annotations

import io
import os
import tarfile
import time
from pathlib import Path

from .storage import needle as needle_mod
from .storage.idx import CompactMap, IndexEntry
from .storage.superblock import SuperBlock
from .storage.types import NEEDLE_HEADER_SIZE, NEEDLE_PADDING_SIZE, \
    to_offset_units
from .storage.volume import dat_path, idx_path
from .util import tls as tls_mod


def walk_dat_records(base: str | Path):
    """Yield (offset, body_size, Needle) for every decodable record in
    a .dat, in file order, via incremental preads (volumes are
    multi-GB; loading the whole file would OOM exactly when this
    offline tool matters). Stops at the first undecodable position
    (torn tail)."""
    dp = dat_path(base)
    total = dp.stat().st_size
    if total < 8:
        return
    with open(dp, "rb") as f:
        fd = f.fileno()
        sb = SuperBlock.parse(os.pread(fd, 64, 0))
        pos = sb.block_size
        version = sb.version
        while pos + NEEDLE_HEADER_SIZE <= total:
            if pos % NEEDLE_PADDING_SIZE:
                pos += (-pos) % NEEDLE_PADDING_SIZE
                continue
            try:
                _, _nid, body = needle_mod.parse_header(
                    os.pread(fd, NEEDLE_HEADER_SIZE, pos))
                size = needle_mod.record_size(body, version)
                if pos + size > total:
                    return
                n = needle_mod.Needle.parse(
                    os.pread(fd, size, pos), version)
            except needle_mod.NeedleError:
                return
            yield pos, body, n
            pos += size


def rebuild_idx(base: str | Path) -> int:
    """fix.go: reconstruct <base>.idx from the .dat records. Later
    records for the same id win (overwrite semantics); deletes cannot
    be recovered (tombstones live only in the lost journal). Returns
    the number of live entries written."""
    entries: dict[int, IndexEntry] = {}
    for pos, body_size, n in walk_dat_records(base):
        entries[n.id] = IndexEntry(n.id, to_offset_units(pos),
                                   body_size)
    with open(idx_path(base), "wb") as f:
        for key in sorted(entries):
            f.write(entries[key].to_bytes())
    return len(entries)


def _safe_tar_name(raw: bytes, key: int, used: set[str]) -> str:
    """Archive member name from a client-controlled needle name:
    traversal components and absolute paths are stripped (an extracted
    archive must never write outside its directory), and collisions
    get the needle id appended (silent last-wins extraction would lose
    exported data)."""
    name = raw.decode("utf-8", "replace") if raw else ""
    parts = [p for p in name.split("/")
             if p not in ("", ".", "..")]
    name = "/".join(parts) or str(key)
    # suffix until actually unique — one fixed suffix could itself
    # collide with a stored name like "dup.<key>"
    candidate, n = name, 0
    while candidate in used:
        candidate = f"{name}.{key}" if n == 0 else f"{name}.{key}.{n}"
        n += 1
    used.add(candidate)
    return candidate


def export_volume(base: str | Path, out_tar: str | Path) -> int:
    """export.go: write every LIVE needle (per the .idx if present,
    else the .dat walk) into a tar as ``<id>`` files. Streams one
    record at a time — only the needle map, never the payloads, is
    held in memory. Returns count."""
    base = Path(base)
    #: key -> (offset, body_size); payloads are read per-needle.
    live: dict[int, tuple[int, int]] = {}
    ip = idx_path(base)
    if ip.exists():
        nm = CompactMap.load_from_idx(ip)
        for e in nm.live_entries():
            live[e.key] = (e.byte_offset, e.size)
    else:
        for pos, body, n in walk_dat_records(base):
            live[n.id] = (pos, body)
    count = 0
    used_names: set[str] = set()
    with open(dat_path(base), "rb") as df, \
            tarfile.open(out_tar, "w") as tf:
        fd = df.fileno()
        sb = SuperBlock.parse(os.pread(fd, 64, 0))
        for key in sorted(live):
            off, body = live[key]
            size = needle_mod.record_size(body, sb.version)
            n = needle_mod.Needle.parse(os.pread(fd, size, off),
                                        sb.version)
            name = _safe_tar_name(n.name, key, used_names)
            info = tarfile.TarInfo(name=name)
            info.size = len(n.data)
            info.mtime = int(n.append_at_ns / 1e9) if n.append_at_ns \
                else int(time.time())
            tf.addfile(info, io.BytesIO(n.data))
            count += 1
    return count


def run_fix(argv: list[str] | None = None) -> int:
    """``weed fix -dir <d> -volumeId N [-collection c]``."""
    import argparse

    p = argparse.ArgumentParser(prog="fix")
    p.add_argument("-dir", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    args = p.parse_args(argv)
    from .storage.store import volume_base_name
    base = Path(args.dir) / volume_base_name(args.volumeId,
                                             args.collection)
    if not dat_path(base).exists():
        print(f"fix: {dat_path(base)} not found")
        return 1
    n = rebuild_idx(base)
    print(f"fix: rebuilt {idx_path(base)} with {n} entries")
    return 0


def run_export(argv: list[str] | None = None) -> int:
    """``weed export -dir <d> -volumeId N -o out.tar``."""
    import argparse

    p = argparse.ArgumentParser(prog="export")
    p.add_argument("-dir", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-o", dest="out", required=True)
    args = p.parse_args(argv)
    from .storage.store import volume_base_name
    base = Path(args.dir) / volume_base_name(args.volumeId,
                                             args.collection)
    if not dat_path(base).exists():
        print(f"export: {dat_path(base)} not found")
        return 1
    n = export_volume(base, args.out)
    print(f"export: wrote {n} needles to {args.out}")
    return 0


def run_watch(argv: list[str] | None = None) -> int:
    """``weed watch -filer <host:port> [-pathPrefix /p]`` — tail the
    filer's metadata stream to stdout (weed/command/watch.go)."""
    import argparse
    import json as json_mod

    import grpc

    from . import pb
    from .cluster.master import _grpc_port
    from .pb import filer_pb2

    p = argparse.ArgumentParser(prog="watch")
    p.add_argument("-filer", required=True)
    p.add_argument("-pathPrefix", default="/")
    p.add_argument("-config", default="",
                   help="security.toml ([grpc.tls] client credentials)")
    args = p.parse_args(argv)
    from .util import config as config_mod
    tls_mod.install_from_config(
        config_mod.load(args.config) if args.config else {})
    ip, http_port = args.filer.rsplit(":", 1)
    ch = tls_mod.dial(f"{ip}:{_grpc_port(int(http_port))}")
    stub = pb.filer_stub(ch)
    stream = stub.SubscribeMetadata(filer_pb2.SubscribeMetadataRequest(
        client_name="weed-watch", path_prefix=args.pathPrefix))
    try:
        for resp in stream:
            note = resp.event_notification
            if not note.new_entry.name and not note.old_entry.name:
                continue  # hello/attach marker, not a mutation
            kind = ("delete" if not note.new_entry.name else
                    "create" if not note.old_entry.name else "update")
            name = (note.new_entry.name or note.old_entry.name)
            print(json_mod.dumps({
                "tsNs": resp.ts_ns, "event": kind,
                "path": f"{resp.directory.rstrip('/')}/{name}",
                "size": max(note.new_entry.attributes.file_size,
                            sum(c.size for c in note.new_entry.chunks)),
            }), flush=True)
    except KeyboardInterrupt:
        pass
    except grpc.RpcError as e:
        # filer gone, or the stream lagged past the filer's queue
        # bound — one clean line, not a traceback
        print(f"watch: stream ended: "
              f"{e.details() if hasattr(e, 'details') else e}")
        return 1
    finally:
        ch.close()
    return 0


def backup_volume(master_url: str, volume_id: int, directory: str | Path,
                  collection: str = "", secret: str = "") -> dict:
    """Incremental local backup of one volume (weed/command/backup.go):
    pull the append-only .dat/.idx tails from whichever server holds
    the volume, resuming from the local copy's sizes. A changed
    superblock compact revision (vacuum ran upstream) or a shrunken
    remote invalidates the increments — then re-copy from scratch.
    Returns {"bytes": transferred, "full": was_full_copy}."""
    from . import pb
    from .cluster.wdclient import MasterClient
    from .pb import volume_server_pb2
    from .storage.store import volume_base_name
    from .storage.superblock import SUPER_BLOCK_SIZE
    from .util import security

    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    base = directory / volume_base_name(volume_id, collection)
    mc = MasterClient(master_url)
    try:
        locs = mc.lookup(volume_id, collection)
    finally:
        mc.close()
    if not locs:
        raise RuntimeError(f"volume {volume_id} not found via "
                           f"{master_url}")
    from .cluster.master import _grpc_port

    url = locs[0]["url"]
    ip, http_port = url.rsplit(":", 1)
    channel = tls_mod.dial(f"{ip}:{_grpc_port(int(http_port))}")
    if secret:
        channel = security.grpc_auth_channel(
            channel, security.Guard(secret))
    try:
        stub = pb.volume_stub(channel)
        st = stub.VolumeStatus(volume_server_pb2.VolumeStatusRequest(
            volume_id=volume_id, collection=collection))
        if not st.has_volume:
            raise RuntimeError(f"{url} no longer has volume "
                               f"{volume_id}")

        def pull(ext: str, dest: Path, start: int) -> int:
            n = 0
            mode = "r+b" if start and dest.exists() else "wb"
            with open(dest, mode) as f:
                if start:
                    f.seek(start)
                for resp in stub.CopyFile(
                        volume_server_pb2.CopyFileRequest(
                            volume_id=volume_id, collection=collection,
                            ext=ext, start_offset=start)):
                    f.write(resp.file_content)
                    n += len(resp.file_content)
                f.truncate()
            return n

        def remote_superblock() -> bytes:
            return b"".join(r.file_content for r in stub.CopyFile(
                volume_server_pb2.CopyFileRequest(
                    volume_id=volume_id, collection=collection,
                    ext=".dat", stop_offset=SUPER_BLOCK_SIZE)))

        dat, idx = dat_path(base), idx_path(base)
        local_dat = dat.stat().st_size if dat.exists() else 0
        sb_before = remote_superblock()
        full = True
        if local_dat >= SUPER_BLOCK_SIZE and \
                local_dat <= st.dat_size:
            # same superblock (compact revision) = increments are valid
            with open(dat, "rb") as f:
                full = f.read(SUPER_BLOCK_SIZE) != sb_before

        def pull_pair(dat_start: int, idx_start: int) -> int:
            # .idx BEFORE .dat (the VolumeCopy ordering invariant): a
            # write racing the pulls then only leaves unindexed tail
            # bytes in the replica's .dat — never an index entry
            # pointing past its end
            n = pull(".idx", idx, idx_start)
            return n + pull(".dat", dat, dat_start)

        moved = 0
        if full:
            moved += pull_pair(0, 0)
        else:
            local_idx = idx.stat().st_size if idx.exists() else 0
            moved += pull_pair(local_dat, local_idx)
        # A compaction landing MID-backup mixes revisions in the pulled
        # idx/dat pair; redo full copies until one completes with the
        # superblock unchanged across it — check-then-pull, so every
        # copy performed is validated and the final iteration never
        # wastes a full pull it cannot check (bounded: a vacuum per
        # pull forever would mean the cluster is melting anyway).
        for attempt in range(5):
            sb_after = remote_superblock()
            if sb_after == sb_before:
                return {"bytes": moved, "full": full}
            if attempt == 4:
                break  # a pull we could not validate would be wasted
            sb_before = sb_after
            moved += pull_pair(0, 0)
            full = True
        raise RuntimeError(
            f"volume {volume_id} compacted on every copy attempt; "
            f"backup inconsistent — retry later")
    finally:
        channel.close()


def run_backup(argv: list[str] | None = None) -> int:
    """``weed backup -server <master> -volumeId N -dir <d>`` —
    incremental read-only replica of a live volume on local disk,
    loadable by `weed export` / `weed fix`."""
    import argparse

    p = argparse.ArgumentParser(prog="backup")
    p.add_argument("-server", default="127.0.0.1:9333",
                   help="master host:port")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-dir", default=".")
    p.add_argument("-config", default="",
                   help="security.toml ([grpc.tls] client credentials)")
    args = p.parse_args(argv)
    from .util import config as config_mod
    cfg = config_mod.load(args.config) if args.config else {}
    tls_mod.install_from_config(cfg)
    secret = config_mod.lookup(cfg, "jwt.signing.key", "") if cfg \
        else ""
    try:
        r = backup_volume(args.server, args.volumeId, args.dir,
                          collection=args.collection, secret=secret)
    except Exception as e:  # noqa: BLE001 — CLI surface
        print(f"backup: {e}")
        return 1
    print(f"backup: volume {args.volumeId} -> {args.dir} "
          f"({r['bytes']} bytes, {'full' if r['full'] else 'incremental'})")
    return 0
