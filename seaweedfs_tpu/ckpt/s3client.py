"""Minimal S3-gateway client for the checkpoint/dataloader plane.

Everything the checkpoint store needs from the gateway — bucket
ensure, object put/get/head/list/delete and RANGED get — over
``retry.http_request`` (breaker + deadline + jittered retries; raw
``urllib`` outside util/retry.py is an SW601 finding). The client is
deliberately unauthenticated: training jobs talk to an open or
VPC-internal gateway; SigV4 signing belongs to external tooling.

Every ranged read is recorded in :attr:`GatewayClient.ranges` —
tests and ``ckpt_smoke.sh`` assert from it that a restoring process
touched ONLY its own shards' byte ranges (the acceptance criterion is
asserted, not assumed).
"""

from __future__ import annotations

import urllib.error
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Optional

from ..util import retry

_XMLNS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


class GatewayError(Exception):
    """The gateway answered, but not with what the caller needed."""


class GatewayClient:
    """One S3 gateway endpoint (``host:port``)."""

    def __init__(self, gateway_url: str, timeout: float = 30.0):
        self.base = gateway_url if "://" in gateway_url \
            else f"http://{gateway_url}"
        self.timeout = float(timeout)
        #: every ranged GET issued: (bucket, key, offset, length)
        self.ranges: list[tuple[str, str, int, int]] = []
        self.stats = {"puts": 0, "gets": 0, "ranged_gets": 0,
                      "heads": 0, "lists": 0, "deletes": 0,
                      "bytes_out": 0, "bytes_in": 0}

    def _url(self, bucket: str, key: str = "") -> str:
        path = f"/{bucket}"
        if key:
            path += "/" + urllib.parse.quote(key)
        return self.base + path

    # ---- buckets ----

    def ensure_bucket(self, bucket: str) -> None:
        try:
            retry.http_request(self._url(bucket), b"", "PUT",
                               point="ckpt.bucket",
                               timeout=self.timeout)
        except urllib.error.HTTPError as e:
            if e.code != 409:  # BucketAlreadyExists is fine
                raise

    # ---- objects ----

    def put(self, bucket: str, key: str, data: bytes,
            mime: str = "application/octet-stream") -> None:
        retry.http_request(self._url(bucket, key), data, "PUT",
                           {"Content-Type": mime}, point="ckpt.put",
                           timeout=self.timeout)
        self.stats["puts"] += 1
        self.stats["bytes_out"] += len(data)

    def get(self, bucket: str, key: str) -> bytes:
        resp = retry.http_request(self._url(bucket, key),
                                  point="ckpt.get",
                                  timeout=self.timeout)
        self.stats["gets"] += 1
        self.stats["bytes_in"] += len(resp.data)
        return resp.data

    def get_range(self, bucket: str, key: str, offset: int,
                  length: int) -> bytes:
        """Read ``length`` bytes at ``offset`` — REQUIRES a 206 with a
        matching ``Content-Range``; a gateway quietly answering 200
        with the whole object would hide a broken range path, so that
        fails loudly instead."""
        if length <= 0:
            return b""
        stop = offset + length - 1
        resp = retry.http_request(
            self._url(bucket, key),
            headers={"Range": f"bytes={offset}-{stop}"},
            point="ckpt.get_range", timeout=self.timeout)
        if resp.status != 206:
            raise GatewayError(
                f"ranged GET of {bucket}/{key} answered "
                f"{resp.status}, want 206")
        got = resp.headers.get("Content-Range", "")
        want = f"bytes {offset}-{stop}/"
        if not got.startswith(want):
            raise GatewayError(
                f"ranged GET of {bucket}/{key}: Content-Range "
                f"{got!r} does not match requested {want!r}*")
        if len(resp.data) != length:
            raise GatewayError(
                f"ranged GET of {bucket}/{key}: {len(resp.data)} "
                f"bytes for a {length}-byte range")
        self.stats["ranged_gets"] += 1
        self.stats["bytes_in"] += length
        self.ranges.append((bucket, key, offset, length))
        return resp.data

    def head(self, bucket: str, key: str) -> Optional[int]:
        """Object size, or None when absent."""
        try:
            resp = retry.http_request(self._url(bucket, key),
                                      method="HEAD",
                                      point="ckpt.head",
                                      timeout=self.timeout)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        self.stats["heads"] += 1
        return int(resp.headers.get("Content-Length", 0) or 0)

    def delete(self, bucket: str, key: str) -> None:
        try:
            retry.http_request(self._url(bucket, key), method="DELETE",
                               point="ckpt.delete",
                               timeout=self.timeout)
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
        self.stats["deletes"] += 1

    def list(self, bucket: str, prefix: str = "") -> list[str]:
        """All keys under ``prefix``, following continuation tokens."""
        keys: list[str] = []
        token = ""
        while True:
            q = {"list-type": "2", "prefix": prefix,
                 "max-keys": "1000"}
            if token:
                q["continuation-token"] = token
            resp = retry.http_request(
                self._url(bucket) + "?" + urllib.parse.urlencode(q),
                point="ckpt.list", timeout=self.timeout)
            self.stats["lists"] += 1
            root = ET.fromstring(resp.data)
            for c in root.findall(f"{_XMLNS}Contents"):
                k = c.find(f"{_XMLNS}Key")
                if k is not None and k.text:
                    keys.append(k.text)
            trunc = root.find(f"{_XMLNS}IsTruncated")
            nxt = root.find(f"{_XMLNS}NextContinuationToken")
            if trunc is None or trunc.text != "true" or nxt is None \
                    or not nxt.text:
                return keys
            token = nxt.text
