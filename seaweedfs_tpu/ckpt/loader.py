"""Dataloader scans over gateway objects (docs/workloads.md).

The second workload the checkpoint plane serves: a training job
streaming many data objects per epoch. :class:`ObjectLoader` scans a
key list in a SEEDED shuffle (every epoch is reproducible, and every
data-parallel worker derives its own disjoint order from the same
seed), fetching up to ``prefetch_depth`` objects ahead of the consumer
on a small thread pool — the same bounded-lookahead shape as the mount
layer's readahead, but at object granularity. ``depth=0`` degrades to
synchronous GETs, which is exactly the no-readahead baseline
``bench.py --child-ckpt`` compares against.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Iterator, Optional

from .s3client import GatewayClient


class ObjectLoader:
    """Seeded shuffled scans over one bucket's objects."""

    def __init__(self, client: GatewayClient, bucket: str,
                 keys: Optional[list[str]] = None, prefix: str = "",
                 seed: int = 0, prefetch_depth: int = 4):
        self.client = client
        self.bucket = bucket
        self._keys = list(keys) if keys is not None \
            else client.list(bucket, prefix)
        self.seed = int(seed)
        self.depth = max(0, int(prefetch_depth))
        self.stats = {"objects": 0, "bytes": 0, "wait_seconds": 0.0,
                      "epochs": 0}

    @property
    def keys(self) -> list[str]:
        return list(self._keys)

    def epoch_order(self, epoch: int) -> list[str]:
        """The (deterministic) key order for one epoch."""
        order = list(self._keys)
        random.Random(f"{self.seed}:{epoch}").shuffle(order)
        return order

    def scan(self, epoch: int = 0) -> Iterator[tuple[str, bytes]]:
        """Yield ``(key, data)`` over one epoch's shuffled order,
        keeping at most ``prefetch_depth`` fetches in flight."""
        order = self.epoch_order(epoch)
        self.stats["epochs"] += 1
        if self.depth == 0:
            for key in order:
                t0 = time.perf_counter()
                data = self.client.get(self.bucket, key)
                self.stats["wait_seconds"] += time.perf_counter() - t0
                self.stats["objects"] += 1
                self.stats["bytes"] += len(data)
                yield key, data
            return
        # bounded lookahead: a deque of in-flight fetch slots, each
        # filled by its own short-lived worker; the consumer pops the
        # head (preserving order) and tops the tail back up
        window: deque[tuple[str, threading.Thread, list]] = deque()
        it = iter(order)

        def _start(key: str):
            slot: list = [None, None]  # [data, exception]

            def _fetch():
                try:
                    slot[0] = self.client.get(self.bucket, key)
                except Exception as e:  # noqa: BLE001 — re-raised
                    slot[1] = e

            t = threading.Thread(target=_fetch, daemon=True,
                                 name="ckpt-loader")
            t.start()
            window.append((key, t, slot))

        for key in it:
            _start(key)
            if len(window) >= self.depth:
                break
        while window:
            key, t, slot = window.popleft()
            t0 = time.perf_counter()
            t.join()
            self.stats["wait_seconds"] += time.perf_counter() - t0
            nxt = next(it, None)
            if nxt is not None:
                _start(nxt)
            if slot[1] is not None:
                raise slot[1]
            self.stats["objects"] += 1
            self.stats["bytes"] += len(slot[0])
            yield key, slot[0]
