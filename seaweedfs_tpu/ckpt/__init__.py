"""Checkpoint & dataloader workload plane (docs/workloads.md).

The end-to-end ML consumer of the store: save a sharded ``jax.Array``
pytree through the S3 gateway as one object per (param, shard) plus a
committed manifest, restore it onto a mesh with each process
range-reading only its own shards' bytes, and stream data objects in
seeded shuffled scans with bounded prefetch.
"""

from .loader import ObjectLoader
from .manifest import (FORMAT, Manifest, ManifestError, ParamSpec,
                       ShardEntry, spec_from_json, spec_to_json)
from .s3client import GatewayClient, GatewayError
from .store import (CheckpointError, CheckpointStore, CorruptShardError)

__all__ = ["FORMAT", "CheckpointError", "CheckpointStore",
           "CorruptShardError", "GatewayClient", "GatewayError",
           "Manifest", "ManifestError", "ObjectLoader", "ParamSpec",
           "ShardEntry", "spec_from_json", "spec_to_json"]
