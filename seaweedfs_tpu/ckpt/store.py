"""Sharded jax.Array checkpoint store over the S3 gateway.

Save (multi-process safe, docs/workloads.md "Checkpoint layout"):

1. every process writes one object per (param, local shard) for the
   shards it OWNS (``replica_id == 0`` — exactly one writer per global
   shard no matter how the array is replicated), named by the shard's
   global start indices so no coordination is needed to agree on keys;
2. every process writes its part-manifest to
   ``{root}/_parts/{process_index}.json``;
3. process 0 waits for all parts, merges them, orders each param's
   shard table canonically and assigns packed byte ranges
   (``Manifest.finalize``), and writes ``{root}/manifest.json`` — the
   COMMIT POINT; the other processes poll for it as the save barrier.

Restore: read the manifest, build each param's ``NamedSharding`` from
the stored ``PartitionSpec`` and the live mesh, and let
``jax.make_array_from_callback`` pull exactly the blocks this
process's addressable devices need — each block is a RANGED read of
the covering shard object(s) (an axis-0 slice of a saved shard is
contiguous in its C-order bytes, so restoring onto more processes
than saved sub-range-reads instead of over-reading). Block bytes stage
through a :class:`~seaweedfs_tpu.pipeline.pipe.HostBufferPool` slab
(bounding peak host memory and running under bufcheck), are sha256-
verified against the manifest whenever a whole shard object is read,
and a mismatch fails closed with :class:`CorruptShardError` — a
checkpoint never half-loads.

The per-shard ``device_put`` loop the naive restore would write is
exactly what seaweedlint SW704 flags; ``make_array_from_callback``
keeps placement inside jax (tests/test_dataflow_rules.py pins the
fixture from this file's history).
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Optional

import numpy as np

from ..util import faults, glog, tracing
from .manifest import (Manifest, ManifestError, ParamSpec,
                       ShardEntry, spec_from_json, spec_to_json)
from .s3client import GatewayClient


class CheckpointError(Exception):
    """Save/restore failed in a way retrying won't fix."""


class CorruptShardError(CheckpointError):
    """A shard object's bytes do not hash to the manifest's sha256."""


def _path_name(path) -> str:
    """jax tree path -> stable object-key-safe param name."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:  # pragma: no cover — future jax key types
            parts.append(str(p))
    return "/".join(parts) or "_root"


def _norm_index(index, shape: tuple) -> tuple[tuple, tuple]:
    """A device's index (tuple of slices) -> (start, stop) int tuples."""
    start, stop = [], []
    for sl, dim in zip(index, shape):
        start.append(0 if sl.start is None else int(sl.start))
        stop.append(dim if sl.stop is None else int(sl.stop))
    return tuple(start), tuple(stop)


class CheckpointStore:
    """Checkpoints under ``{bucket}/{prefix}/{name}/`` on one gateway."""

    MANIFEST = "manifest.json"

    def __init__(self, gateway_url: str, bucket: str = "ckpt",
                 prefix: str = "checkpoints",
                 client: Optional[GatewayClient] = None,
                 barrier_timeout: float = 120.0):
        self.client = client or GatewayClient(gateway_url)
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.barrier_timeout = float(barrier_timeout)

    def _root(self, name: str) -> str:
        return f"{self.prefix}/{name}" if self.prefix else name

    # ------------- save -------------

    def save(self, name: str, tree: Any) -> Manifest:
        """Write one checkpoint; every participating jax process must
        call this with its own (process-local view of the) ``tree``.
        Returns the merged manifest (process 0 builds it; the others
        re-read the committed one)."""
        import jax

        with tracing.span("ckpt.save"):
            pid = jax.process_index()
            nproc = jax.process_count()
            root = self._root(name)
            self.client.ensure_bucket(self.bucket)
            if pid == 0:
                # Overwriting a committed checkpoint under the same
                # name: clear stale parts FIRST, then the manifest —
                # its absence is the "cleanup done" signal the other
                # processes wait on, so no process writes a fresh part
                # that cleanup could swallow, and the old manifest can
                # never double as OUR commit point.
                for i in range(nproc):
                    self.client.delete(self.bucket,
                                       f"{root}/_parts/{i}.json")
                self.client.delete(self.bucket,
                                   f"{root}/{self.MANIFEST}")
            else:
                self._await_absent(f"{root}/{self.MANIFEST}")
            leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
            if not leaves:
                raise CheckpointError("empty pytree")
            part = Manifest({})
            for path, leaf in leaves:
                part.params.append(self._save_leaf(root,
                                                   _path_name(path),
                                                   leaf, part))
            self.client.put(self.bucket, f"{root}/_parts/{pid}.json",
                            part.to_json(), "application/json")
            if pid == 0:
                man = self._merge_parts(root, nproc)
                man.finalize()
                man.validate()
                # the manifest PUT is the checkpoint's rename-style
                # commit point: a crash on either side leaves a fully
                # readable prior state (no manifest = no checkpoint)
                faults.check("crash.ckpt.save")
                self.client.put(self.bucket, f"{root}/{self.MANIFEST}",
                                man.to_json(), "application/json")
                glog.info("ckpt: committed %s (%d params, %d procs)",
                          root, len(man.params), nproc)
                return man
            return self._await_manifest(root)

    def _save_leaf(self, root: str, pname: str, leaf,
                   part: Manifest) -> ParamSpec:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        if not isinstance(leaf, jax.Array):
            leaf = np.asarray(leaf)
            # host arrays are "replicated": only process 0 writes them
            spec_json = spec_to_json(PartitionSpec(*([None] *
                                                     leaf.ndim)))
            p = ParamSpec(pname, str(leaf.dtype), leaf.shape,
                          spec_json)
            if jax.process_index() == 0:
                p.shards.append(self._put_block(
                    root, pname, np.ascontiguousarray(leaf),
                    tuple([0] * leaf.ndim), leaf.shape))
            return p
        sharding = leaf.sharding
        if isinstance(sharding, NamedSharding):
            spec_json = spec_to_json(sharding.spec)
            if not part.mesh_axes:
                part.mesh_axes.update(
                    {str(k): int(v) for k, v in
                     sharding.mesh.shape.items()})
        else:
            spec_json = spec_to_json(PartitionSpec(*([None] *
                                                     leaf.ndim)))
        p = ParamSpec(pname, str(leaf.dtype), leaf.shape, spec_json)
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue  # exactly one writer per global shard
            start, stop = _norm_index(shard.index, leaf.shape)
            block = np.ascontiguousarray(np.asarray(shard.data))
            p.shards.append(self._put_block(root, pname, block,
                                            start, stop))
        return p

    def _put_block(self, root: str, pname: str, block: np.ndarray,
                   start: tuple, stop: tuple) -> ShardEntry:
        data = block.tobytes()
        key = f"{root}/{pname}/shard-" + \
            "_".join(str(i) for i in start)
        self.client.put(self.bucket, key, data)
        return ShardEntry(key, start, stop, len(data),
                          hashlib.sha256(data).hexdigest())

    def _merge_parts(self, root: str, nproc: int) -> Manifest:
        parts: dict[int, Manifest] = {}
        deadline = time.monotonic() + self.barrier_timeout
        while len(parts) < nproc:
            for i in range(nproc):
                if i in parts:
                    continue
                raw = self._get_if_exists(f"{root}/_parts/{i}.json")
                if raw is not None:
                    parts[i] = Manifest.from_json(raw)
            if len(parts) < nproc:
                if time.monotonic() > deadline:
                    raise CheckpointError(
                        f"save barrier: {len(parts)}/{nproc} part "
                        f"manifests after {self.barrier_timeout}s")
                time.sleep(0.05)
        merged = Manifest({})
        for i in sorted(parts):
            for p in parts[i].params:
                merged.mesh_axes.update(parts[i].mesh_axes)
                try:
                    mine = merged.param(p.name)
                except ManifestError:
                    merged.params.append(p)
                    continue
                seen = {s.start for s in mine.shards}
                mine.shards.extend(s for s in p.shards
                                   if s.start not in seen)
        return merged

    def _get_if_exists(self, key: str) -> Optional[bytes]:
        import urllib.error
        try:
            return self.client.get(self.bucket, key)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def _await_absent(self, key: str) -> None:
        deadline = time.monotonic() + self.barrier_timeout
        while self.client.head(self.bucket, key) is not None:
            if time.monotonic() > deadline:
                raise CheckpointError(
                    f"save barrier: stale {key} never cleared "
                    f"(process 0 missing?)")
            time.sleep(0.05)

    def _await_manifest(self, root: str) -> Manifest:
        deadline = time.monotonic() + self.barrier_timeout
        while True:
            raw = self._get_if_exists(f"{root}/{self.MANIFEST}")
            if raw is not None:
                return Manifest.from_json(raw)
            if time.monotonic() > deadline:
                raise CheckpointError(
                    f"save barrier: no {self.MANIFEST} under {root} "
                    f"after {self.barrier_timeout}s")
            time.sleep(0.05)

    # ------------- restore -------------

    def read_manifest(self, name: str) -> Manifest:
        raw = self._get_if_exists(
            f"{self._root(name)}/{self.MANIFEST}")
        if raw is None:
            raise ManifestError(
                f"no {self.MANIFEST} under {self._root(name)} — "
                f"checkpoint absent or its save never committed")
        man = Manifest.from_json(raw)
        man.validate()
        return man

    def restore(self, name: str, mesh=None, template: Any = None,
                pool=None) -> Any:
        """Load one checkpoint onto ``mesh`` (default: the configured
        process mesh). Returns a pytree shaped like ``template`` when
        given (leaves matched by tree-path name), else a flat
        ``{param_name: jax.Array}`` dict."""
        import jax
        from jax.sharding import NamedSharding

        man = self.read_manifest(name)
        if mesh is None:
            from ..parallel import mesh as mesh_mod
            mesh = mesh_mod.configured_mesh() or mesh_mod.make_mesh()
        own_pool = pool is None
        if own_pool:
            pool = self._make_pool(man)
        arrays: dict[str, Any] = {}
        try:
            with tracing.span("ckpt.restore"):
                for p in man.params:
                    sharding = NamedSharding(mesh,
                                             spec_from_json(p.spec))
                    arrays[p.name] = self._restore_param(p, sharding,
                                                         pool)
                for arr in arrays.values():
                    # pooled staging slabs recycle below; every block
                    # must be on-device before then (bufcheck contract)
                    arr.block_until_ready()
        finally:
            if own_pool:
                pool = None  # slabs die with the pool
        if template is None:
            return arrays
        paths, treedef = jax.tree_util.tree_flatten_with_path(template)
        leaves = []
        for path, _ in paths:
            pname = _path_name(path)
            if pname not in arrays:
                raise ManifestError(
                    f"template leaf {pname!r} not in checkpoint")
            leaves.append(arrays[pname])
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _make_pool(self, man: Manifest):
        from ..pipeline.pipe import HostBufferPool

        biggest = max((s.nbytes for p in man.params
                       for s in p.shards), default=1)
        return HostBufferPool(max(4096, biggest), 4)

    def _restore_param(self, p: ParamSpec, sharding, pool):
        import jax

        shape = tuple(p.shape)
        dtype = np.dtype(p.dtype)
        blocks: dict[tuple, np.ndarray] = {}

        def fetch(index) -> np.ndarray:
            start, stop = _norm_index(index, shape)
            cached = blocks.get((start, stop))
            if cached is None:
                cached = self._read_block(p, start, stop, dtype, pool)
                blocks[(start, stop)] = cached
            return cached

        return jax.make_array_from_callback(shape, sharding, fetch)

    def _read_block(self, p: ParamSpec, start: tuple, stop: tuple,
                    dtype: np.dtype, pool) -> np.ndarray:
        """One device's block, assembled from the covering saved
        shard(s) with ranged reads of exactly the bytes needed."""
        shape = tuple(hi - lo for lo, hi in zip(start, stop))
        out = np.empty(shape, dtype)
        flat = out.reshape(shape[0] if shape else 1, -1) \
            if shape else out.reshape(1, 1)
        filled = 0
        for s in sorted(p.shards, key=lambda s: s.start):
            if s.start[1:] != start[1:] or s.stop[1:] != stop[1:]:
                if self._intersects(s, start, stop):
                    raise ManifestError(
                        f"{p.name!r}: restore block {start}..{stop} "
                        f"cuts shard {s.key} on a non-leading axis — "
                        f"only axis-0 resharding is supported")
                continue
            lo = max(start[0] if start else 0, s.start[0] if s.start
                     else 0)
            hi = min(stop[0] if stop else 1, s.stop[0] if s.stop
                     else 1)
            if lo >= hi:
                continue
            row = int(np.prod(shape[1:], dtype=np.int64)) * \
                dtype.itemsize if len(shape) > 1 else dtype.itemsize
            off = (lo - (s.start[0] if s.start else 0)) * row
            nbytes = (hi - lo) * row
            raw = self._fetch_verified(p, s, off, nbytes, pool)
            dst = flat[lo - (start[0] if start else 0):
                       hi - (start[0] if start else 0)]
            dst.reshape(-1).view(np.uint8)[:] = raw
            filled += nbytes
        if filled != out.nbytes:
            raise ManifestError(
                f"{p.name!r}: shards cover {filled} of {out.nbytes} "
                f"bytes for block {start}..{stop}")
        return out

    @staticmethod
    def _intersects(s: ShardEntry, start: tuple, stop: tuple) -> bool:
        return all(lo < shi and slo < hi for lo, hi, slo, shi in
                   zip(start, stop, s.start, s.stop))

    def _fetch_verified(self, p: ParamSpec, s: ShardEntry, off: int,
                        nbytes: int, pool) -> np.ndarray:
        """Ranged read of ``[off, off+nbytes)`` from one shard object,
        staged through a pooled slab; whole-shard reads verify the
        manifest sha256 and fail closed on mismatch."""
        data = self.client.get_range(self.bucket, s.key, off, nbytes)
        if len(data) != nbytes:
            raise CorruptShardError(
                f"{p.name!r}: shard {s.key} range [{off}, "
                f"{off + nbytes}) returned {len(data)} bytes")
        buf = pool.acquire(timeout=30.0)
        try:
            view = buf[:nbytes]
            view[:] = np.frombuffer(data, np.uint8)
            if off == 0 and nbytes == s.nbytes:
                digest = hashlib.sha256(view).hexdigest()
                if digest != s.sha256:
                    raise CorruptShardError(
                        f"{p.name!r}: shard {s.key} sha256 {digest} "
                        f"!= manifest {s.sha256} — refusing to load")
            return view.copy()
        finally:
            pool.release(buf)

    # ------------- listing -------------

    def list_checkpoints(self) -> list[dict]:
        """[{name, params, shards, bytes}] for every COMMITTED
        checkpoint under the prefix (uncommitted saves are invisible,
        matching restore's view)."""
        out = []
        pfx = f"{self.prefix}/" if self.prefix else ""
        for key in self.client.list(self.bucket, pfx):
            if not key.endswith(f"/{self.MANIFEST}"):
                continue
            name = key[len(pfx):-len(self.MANIFEST) - 1]
            try:
                man = Manifest.from_json(
                    self.client.get(self.bucket, key))
            except ManifestError as e:
                glog.v(1, f"ckpt.list: skipping malformed manifest "
                          f"{key}: {e}")
                continue
            out.append({
                "name": name,
                "params": len(man.params),
                "shards": sum(len(p.shards) for p in man.params),
                "bytes": sum(s.nbytes for p in man.params
                             for s in p.shards)})
        return sorted(out, key=lambda d: d["name"])
