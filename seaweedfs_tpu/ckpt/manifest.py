"""Checkpoint manifest: the metadata that makes shard objects a model.

A checkpoint is laid out as one object per (param, shard) plus ONE
``manifest.json`` (docs/workloads.md "Checkpoint layout"):

``{root}/{param}/shard-{i0}_{i1}...``
    the C-order bytes of that shard's block of the global array
    (``i0``, ``i1``, ... are the block's global start indices — a
    deterministic name every writing process computes independently)
``{root}/manifest.json``
    format tag, the mesh axis sizes it was saved under, and one
    :class:`ParamSpec` per leaf: dtype, global shape, the
    ``PartitionSpec`` as JSON, and per-shard entries (global start/stop
    indices, nbytes, sha256, and the byte range the shard occupies in
    the param's packed C-order stream).

The manifest is the COMMIT POINT: a save that dies before writing it
leaves garbage shard objects but no restorable checkpoint, and restore
never has to guess whether a save finished. sha256 is per shard object
so restore verifies exactly what it reads (full-shard reads; sub-range
reads are covered by the surrounding object's hash only when the whole
object is eventually consumed — see store.py).
"""

from __future__ import annotations

import json
from typing import Optional

FORMAT = "seaweed-ckpt/1"


class ManifestError(Exception):
    """Manifest missing, malformed, or incompatible with the request."""


def spec_to_json(spec) -> list:
    """``PartitionSpec`` -> JSON: one entry per dim, each None, an axis
    name, or a list of axis names (a tuple-sharded dim)."""
    out: list = []
    for part in tuple(spec):
        if part is None:
            out.append(None)
        elif isinstance(part, (tuple, list)):
            out.append([str(a) for a in part])
        else:
            out.append(str(part))
    return out


def spec_from_json(obj) -> "jax.sharding.PartitionSpec":  # noqa: F821
    from jax.sharding import PartitionSpec

    parts = []
    for part in obj:
        if part is None:
            parts.append(None)
        elif isinstance(part, list):
            parts.append(tuple(part))
        else:
            parts.append(str(part))
    return PartitionSpec(*parts)


class ShardEntry:
    """One saved shard object of one param."""

    __slots__ = ("key", "start", "stop", "nbytes", "sha256",
                 "byte_start", "byte_stop")

    def __init__(self, key: str, start: tuple, stop: tuple,
                 nbytes: int, sha256: str,
                 byte_start: int = 0, byte_stop: int = 0):
        self.key = key
        self.start = tuple(int(x) for x in start)
        self.stop = tuple(int(x) for x in stop)
        self.nbytes = int(nbytes)
        self.sha256 = sha256
        self.byte_start = int(byte_start)
        self.byte_stop = int(byte_stop)

    def to_json(self) -> dict:
        return {"key": self.key, "start": list(self.start),
                "stop": list(self.stop), "nbytes": self.nbytes,
                "sha256": self.sha256, "byte_start": self.byte_start,
                "byte_stop": self.byte_stop}

    @classmethod
    def from_json(cls, d: dict) -> "ShardEntry":
        try:
            return cls(d["key"], d["start"], d["stop"], d["nbytes"],
                       d["sha256"], d.get("byte_start", 0),
                       d.get("byte_stop", 0))
        except (KeyError, TypeError) as e:
            raise ManifestError(f"bad shard entry: {e}") from e


class ParamSpec:
    """One pytree leaf: global geometry + its shard table."""

    __slots__ = ("name", "dtype", "shape", "spec", "shards")

    def __init__(self, name: str, dtype: str, shape: tuple,
                 spec: list, shards: Optional[list] = None):
        self.name = name
        self.dtype = str(dtype)
        self.shape = tuple(int(x) for x in shape)
        self.spec = list(spec)
        self.shards: list[ShardEntry] = list(shards or [])

    def to_json(self) -> dict:
        return {"name": self.name, "dtype": self.dtype,
                "shape": list(self.shape), "spec": self.spec,
                "shards": [s.to_json() for s in self.shards]}

    @classmethod
    def from_json(cls, d: dict) -> "ParamSpec":
        try:
            return cls(d["name"], d["dtype"], d["shape"], d["spec"],
                       [ShardEntry.from_json(s) for s in d["shards"]])
        except (KeyError, TypeError) as e:
            raise ManifestError(f"bad param spec: {e}") from e


class Manifest:
    """The whole checkpoint's metadata (what ``manifest.json`` holds)."""

    __slots__ = ("mesh_axes", "params")

    def __init__(self, mesh_axes: dict,
                 params: Optional[list] = None):
        self.mesh_axes = {str(k): int(v)
                          for k, v in (mesh_axes or {}).items()}
        self.params: list[ParamSpec] = list(params or [])

    def param(self, name: str) -> ParamSpec:
        for p in self.params:
            if p.name == name:
                return p
        raise ManifestError(f"param {name!r} not in manifest")

    def finalize(self) -> None:
        """Order each param's shards canonically (by global start
        index) and assign packed-stream byte ranges — the merge step
        process 0 runs before committing the manifest."""
        for p in self.params:
            p.shards.sort(key=lambda s: s.start)
            pos = 0
            for s in p.shards:
                s.byte_start = pos
                s.byte_stop = pos + s.nbytes
                pos = s.byte_stop

    def validate(self) -> None:
        import numpy as np

        for p in self.params:
            if not p.shards:
                raise ManifestError(f"param {p.name!r} has no shards")
            itemsize = np.dtype(p.dtype).itemsize
            for s in p.shards:
                if len(s.start) != len(p.shape) or \
                        len(s.stop) != len(p.shape):
                    raise ManifestError(
                        f"{p.name!r}: shard rank mismatch")
                n = itemsize
                for lo, hi, dim in zip(s.start, s.stop, p.shape):
                    if not 0 <= lo < hi <= dim:
                        raise ManifestError(
                            f"{p.name!r}: shard {s.key} out of bounds")
                    n *= hi - lo
                if n != s.nbytes:
                    raise ManifestError(
                        f"{p.name!r}: shard {s.key} nbytes {s.nbytes} "
                        f"!= block size {n}")

    def to_json(self) -> bytes:
        return json.dumps(
            {"format": FORMAT, "mesh_axes": self.mesh_axes,
             "params": [p.to_json() for p in self.params]},
            indent=1, sort_keys=True).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Manifest":
        try:
            d = json.loads(raw)
        except ValueError as e:
            raise ManifestError(f"manifest is not JSON: {e}") from e
        if d.get("format") != FORMAT:
            raise ManifestError(
                f"unsupported manifest format {d.get('format')!r} "
                f"(want {FORMAT})")
        try:
            return cls(d.get("mesh_axes", {}),
                       [ParamSpec.from_json(p) for p in d["params"]])
        except (KeyError, TypeError) as e:
            raise ManifestError(f"bad manifest: {e}") from e
