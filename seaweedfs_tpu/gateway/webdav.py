"""WebDAV server over the filer (weed/server/webdav_server.go analog).

Class-1 WebDAV on the filer namespace: OPTIONS, PROPFIND (Depth 0/1),
GET/HEAD, PUT, DELETE, MKCOL, MOVE and COPY. Enough for davfs2 /
cadaver / OS file-manager mounts, matching the subset the reference's
golang.org/x/net/webdav handler exposes over its filer FS adapter.
"""

from __future__ import annotations

import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler
from typing import Optional

from ..cache import global_chunk_cache
from ..cache import invalidation as invalidation_mod
from ..cluster import usage as usage_mod
from ..cluster.filer_client import FilerClient, FilerClientError
from ..util import glog
from ..util import httpserver
from ..util import tracing

DAV_NS = "DAV:"


def _entry_sig(entry) -> str:
    """Content identity of an entry: its chunk fids + write stamps.
    Part of the cache key, so a rewrite can never serve stale bytes."""
    import hashlib

    h = hashlib.blake2s(digest_size=8)
    for c in entry.chunks:
        h.update(f"{c.file_id}@{c.mtime_ns}".encode())
    return h.hexdigest()


def _rfc1123(ts: float) -> str:
    import time

    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))


class WebDavServer:
    def __init__(self, filer_url: str, ip: str = "127.0.0.1",
                 port: int = 7333, root: str = "/",
                 master_url: str = ""):
        self.filer = FilerClient(filer_url)
        self.ip = ip
        self.port = port
        self.url = f"{ip}:{port}"
        self.root = root.rstrip("/")
        self.master_url = master_url
        # DAV has no auth layer, so all traffic is the anonymous
        # tenant; the hot-key sketch still attributes paths.
        self.usage = usage_mod.UsageCollector("webdav")
        self._usage_pusher: Optional[usage_mod.UsagePusher] = None
        self._http_server: Optional[httpserver.IngressHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> "WebDavServer":
        self._http_server = httpserver.IngressHTTPServer(
            (self.ip, self.port), _make_handler(self), component="dav")
        self._thread = threading.Thread(
            target=self._http_server.serve_forever, daemon=True,
            name=f"webdav-{self.port}")
        self._thread.start()
        if self.master_url:
            self._usage_pusher = usage_mod.UsagePusher(
                self.usage, self.master_url,
                f"webdav@{self.url}").start()
            # Job-commit cache invalidation: register this gateway's
            # chunk cache for the master's fan-out (docs/jobs.md).
            invalidation_mod.start_subscriber(self.master_url,
                                              self.url, self._stop)
        glog.info("webdav at %s -> filer %s", self.url,
                  self.filer.filer_url)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._usage_pusher:
            self._usage_pusher.stop()
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        self.filer.close()

    def __enter__(self) -> "WebDavServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def fpath(self, dav_path: str) -> str:
        p = self.root + dav_path
        return p if p.startswith("/") else "/" + p


def _prop_response(href: str, is_dir: bool, size: int, mtime: float
                   ) -> ET.Element:
    resp = ET.Element(f"{{{DAV_NS}}}response")
    ET.SubElement(resp, f"{{{DAV_NS}}}href").text = urllib.parse.quote(
        href + ("/" if is_dir and not href.endswith("/") else ""))
    stat = ET.SubElement(resp, f"{{{DAV_NS}}}propstat")
    prop = ET.SubElement(stat, f"{{{DAV_NS}}}prop")
    rtype = ET.SubElement(prop, f"{{{DAV_NS}}}resourcetype")
    if is_dir:
        ET.SubElement(rtype, f"{{{DAV_NS}}}collection")
    else:
        ET.SubElement(prop,
                      f"{{{DAV_NS}}}getcontentlength").text = str(size)
    ET.SubElement(prop, f"{{{DAV_NS}}}getlastmodified").text = \
        _rfc1123(mtime)
    ET.SubElement(stat, f"{{{DAV_NS}}}status").text = \
        "HTTP/1.1 200 OK"
    return resp


def _make_handler(dav: WebDavServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "seaweedfs-tpu-webdav"

        def log_message(self, fmt, *args):
            glog.v(2, "webdav: " + fmt, *args)

        def _send(self, code: int, body: bytes = b"",
                  ctype: str = "application/xml; charset=utf-8",
                  extra: Optional[dict] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            if not extra or "Content-Length" not in extra:
                self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            if body and self.command != "HEAD":
                self.wfile.write(body)

        def _dav_path(self) -> str:
            p = urllib.parse.unquote(
                urllib.parse.urlsplit(self.path).path)
            return p if p == "/" else p.rstrip("/")

        def _account(self, path: str, *, n_in: int = 0,
                     n_out: int = 0, seconds: float = 0.0,
                     error: bool = False) -> None:
            parts = path.strip("/").split("/")
            dav.usage.record(
                "anonymous", parts[0] if parts else "",
                n_in=n_in, n_out=n_out, seconds=seconds,
                error=error, key=dav.fpath(path))

        def _lookup(self, path: str):
            fp = dav.fpath(path)
            if fp == "/":
                import time

                from ..pb import filer_pb2
                e = filer_pb2.Entry(name="", is_directory=True)
                e.attributes.mtime = int(time.time())
                return e
            d, _, name = fp.rpartition("/")
            return dav.filer.lookup(d or "/", name)

        def do_OPTIONS(self):
            self._send(200, extra={
                "DAV": "1",
                "Allow": "OPTIONS, PROPFIND, GET, HEAD, PUT, DELETE, "
                         "MKCOL, MOVE, COPY"})

        def do_POST(self):
            # DAV itself has no POST; the one accepted path is the
            # maintenance-job cache-invalidation fan-out (docs/jobs.md).
            import json

            if urllib.parse.urlsplit(self.path).path != \
                    "/cache/invalidate":
                self._send(405)
                return
            n = int(self.headers.get("Content-Length", "0"))
            try:
                self._send(200, json.dumps(
                    invalidation_mod.handle_event(json.loads(
                        self.rfile.read(n) if n else b"{}"))
                ).encode(), ctype="application/json")
            except (ValueError, KeyError) as e:
                self._send(400, json.dumps(
                    {"error": str(e)}).encode(),
                    ctype="application/json")

        def do_PROPFIND(self):
            n = int(self.headers.get("Content-Length", "0"))
            if n:
                self.rfile.read(n)
            path = self._dav_path()
            depth = self.headers.get("Depth", "1")
            entry = self._lookup(path)
            if entry is None:
                self._send(404)
                return
            ms = ET.Element(f"{{{DAV_NS}}}multistatus")
            ms.append(_prop_response(
                path, entry.is_directory, entry.attributes.file_size,
                entry.attributes.mtime))
            if entry.is_directory and depth != "0":
                base = path if path != "/" else ""
                for child in dav.filer.list(dav.fpath(path)):
                    ms.append(_prop_response(
                        f"{base}/{child.name}", child.is_directory,
                        child.attributes.file_size,
                        child.attributes.mtime))
            self._send(207, ET.tostring(ms))

        def do_GET(self):
            path = self._dav_path()
            if path == "/debug/vars":
                import json

                from ..util import varz
                self._send(200, json.dumps(varz.payload(
                    "webdav",
                    extra={"usage": dav.usage.to_payload()},
                )).encode(), "application/json")
                return
            if path == "/debug/profile":
                from ..util import profiler
                q = dict(urllib.parse.parse_qsl(
                    urllib.parse.urlsplit(self.path).query))
                self._send(200, profiler.profile(
                    float(q.get("seconds", 2.0)),
                    hz=float(q.get("hz", profiler.DEFAULT_BURST_HZ))
                ).encode(), "text/plain; charset=utf-8")
                return
            t0 = time.perf_counter()
            entry = self._lookup(path)
            if entry is None:
                self._account(path, error=True)
                self._send(404)
                return
            if entry.is_directory:
                self._send(403)
                return
            # Hot-read cache keyed on the entry's chunk identity — an
            # overwrite mints new fids, so stale keys simply rot out.
            cache = global_chunk_cache()
            ckey = f"dav:{dav.fpath(path)}:{_entry_sig(entry)}"
            data = cache.get(ckey)
            if data is None:
                try:
                    data = dav.filer.get_data(dav.fpath(path))
                except FilerClientError:
                    self._account(path, error=True)
                    self._send(404)
                    return
                cache.put(ckey, data)
            self._account(path, n_out=len(data),
                          seconds=time.perf_counter() - t0)
            self._send(200, data, entry.attributes.mime
                       or "application/octet-stream")

        def do_HEAD(self):
            path = self._dav_path()
            entry = self._lookup(path)
            if entry is None:
                self._send(404)
                return
            self._send(200, b"", "application/octet-stream", {
                "Content-Length": "0" if entry.is_directory
                else str(entry.attributes.file_size)})

        def do_PUT(self):
            n = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(n) if n else b""
            path = self._dav_path()
            t0 = time.perf_counter()
            try:
                dav.filer.put_data(
                    dav.fpath(path), body,
                    mime=self.headers.get("Content-Type", ""))
            except FilerClientError as e:
                self._account(path, n_in=len(body), error=True)
                self._send(409, str(e).encode(), "text/plain")
                return
            self._account(path, n_in=len(body),
                          seconds=time.perf_counter() - t0)
            self._send(201)

        def do_MKCOL(self):
            path = self._dav_path()
            fp = dav.fpath(path)
            d, _, name = fp.rpartition("/")
            try:
                dav.filer.mkdir(d or "/", name)
            except FilerClientError as e:
                self._send(409, str(e).encode(), "text/plain")
                return
            self._send(201)

        def do_DELETE(self):
            path = self._dav_path()
            if self._lookup(path) is None:
                self._account(path, error=True)
                self._send(404)
                return
            try:
                dav.filer.delete_data(dav.fpath(path), recursive=True)
            except FilerClientError as e:
                self._account(path, error=True)
                self._send(409, str(e).encode(), "text/plain")
                return
            self._account(path)
            self._send(204)

        def _destination(self) -> Optional[str]:
            dest = self.headers.get("Destination", "")
            if not dest:
                return None
            p = urllib.parse.unquote(urllib.parse.urlsplit(dest).path)
            return p if p == "/" else p.rstrip("/")

        def do_MOVE(self):
            src = self._dav_path()
            dst = self._destination()
            if dst is None or self._lookup(src) is None:
                self._send(404 if dst else 400)
                return
            sf, df = dav.fpath(src), dav.fpath(dst)
            sd, _, sn = sf.rpartition("/")
            dd, _, dn = df.rpartition("/")
            dav.filer.rename(sd or "/", sn, dd or "/", dn)
            self._send(201)

        def do_COPY(self):
            src = self._dav_path()
            dst = self._destination()
            entry = self._lookup(src)
            if dst is None or entry is None:
                self._send(404 if dst else 400)
                return
            if entry.is_directory:
                self._send(501)  # collection COPY not supported
                return
            df = dav.fpath(dst)
            sf = dav.fpath(src)
            try:
                dav.filer.copy_data(
                    sf, df, entry.attributes.file_size,
                    mime=entry.attributes.mime,
                    extended=dict(entry.extended),
                    file_mode=entry.attributes.file_mode)
            except FilerClientError as e:
                self._send(409, str(e).encode(), "text/plain")
                return
            self._send(201)

    return tracing.instrument_http_handler(
        httpserver.admission_gate(Handler), "dav")


def main(argv: list[str]) -> int:
    import argparse
    import signal

    p = argparse.ArgumentParser(prog="webdav")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=7333)
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.add_argument("-root", default="/",
                   help="filer directory served as the DAV root")
    p.add_argument("-master", default="",
                   help="master url to push usage snapshots to")
    p.add_argument("-toml", default="",
                   help="server TOML ([ingress], [retry])")
    from ..util import tls as tls_mod
    tls_mod.add_security_flag(p)
    args = p.parse_args(argv)
    tls_mod.install_from_flag(args)
    if args.toml:
        from ..util import config as config_mod
        from ..util import retry as retry_mod
        conf = config_mod.load(args.toml)
        httpserver.configure_from(conf)
        retry_mod.configure_from(conf)
        tracing.configure_from(conf)
    srv = WebDavServer(args.filer, ip=args.ip, port=args.port,
                       root=args.root,
                       master_url=args.master).start()
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    stop.wait()
    srv.stop()
    return 0
