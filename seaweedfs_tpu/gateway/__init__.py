"""Gateways (L5): S3 REST and WebDAV over the filer (weed/s3api analog)."""
