"""AWS Signature V4 verification for the S3 gateway.

Mirrors weed/s3api/auth_signature_v4.go behavior from the algorithm's
public spec: reconstruct the canonical request from the incoming
headers, derive the signing key from the configured secret, and compare
signatures. Supports header auth (``Authorization: AWS4-HMAC-SHA256``)
and presigned URLs (``X-Amz-Signature`` query). When no identities are
configured the gateway runs open (the reference's default without
-s3.config), so anonymous requests pass.
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass
from typing import Optional


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    #: Granted actions, weed ``s3.configure`` shape: "Admin" | "Read" |
    #: "Write", each optionally bucket-scoped as "Action:bucket".
    actions: tuple[str, ...] = ("Admin",)

    def can(self, action: str, bucket: str = "") -> bool:
        """Authorize ``action`` ("Read"/"Write"/"Admin") on ``bucket``.

        Mirrors weed/s3api identity actions: "Admin" covers everything;
        a bare action grants it on every bucket; "Action:bucket" scopes
        the grant to one bucket (and never satisfies bucket-less ops)."""
        for a in self.actions:
            name, _, scope = a.partition(":")
            if scope and (not bucket or scope != bucket):
                continue
            if name == "Admin" or name == action:
                return True
        return False


class AuthError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _signing_key(secret: str, date: str, region: str,
                 service: str) -> bytes:
    k = _hmac(f"AWS4{secret}".encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def _canonical_query(query: str, drop_signature: bool = False) -> str:
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    if drop_signature:
        pairs = [(k, v) for k, v in pairs if k != "X-Amz-Signature"]
    pairs.sort()
    return "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}" for k, v in pairs)


#: Maximum tolerated |server clock - x-amz-date|, matching the
#: reference's (and AWS's) ~15-minute skew window — without it a
#: captured signed request replays successfully forever.
MAX_CLOCK_SKEW_S = 15 * 60


def _check_date_freshness(amz_date: str, cred_date: str) -> None:
    import calendar
    import time as _time

    try:
        t0 = calendar.timegm(_time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
    except ValueError as e:
        raise AuthError("AccessDenied",
                        f"malformed x-amz-date {amz_date!r}") from e
    if not amz_date.startswith(cred_date):
        raise AuthError("AccessDenied",
                        "credential scope date does not match x-amz-date")
    if abs(_time.time() - t0) > MAX_CLOCK_SKEW_S:
        raise AuthError("RequestTimeTooSkewed",
                        "x-amz-date outside the accepted clock-skew "
                        "window")


class SigV4Verifier:
    def __init__(self, identities: Optional[list[Identity]] = None):
        #: Deny-all gate for "config exists but is unreadable" — auth
        #: must fail CLOSED until a definitive load (or confirmed
        #: absence) happens, never open because the filer was down.
        self.deny_all = False
        self.set_identities(identities)

    def set_identities(self,
                       identities: Optional[list[Identity]]) -> None:
        """Atomically swap the identity set (live reload from the
        filer-stored config; a dict rebind is atomic under the GIL so
        in-flight verifies see either the old or the new set)."""
        # whole-dict rebind per the docstring; never mutated in place
        # seaweedlint: disable=SW801 — atomic reference swap
        self.by_access_key = {i.access_key: i
                              for i in (identities or [])}
        # bool rebind paired with the swap above
        # seaweedlint: disable=SW801 — atomic reference swap
        self.deny_all = False

    def set_unavailable(self) -> None:
        self.deny_all = True

    @property
    def open_access(self) -> bool:
        return not self.by_access_key

    def verify(self, method: str, raw_path: str, query: str,
               headers, body_sha256: str) -> Optional[Identity]:
        """Returns the authenticated Identity (None if gateway is open).
        Raises AuthError on bad/missing credentials."""
        if self.deny_all:
            raise AuthError("AccessDenied",
                            "identity configuration unavailable")
        if self.open_access:
            return None
        auth = headers.get("Authorization", "")
        if auth.startswith("AWS4-HMAC-SHA256 "):
            return self._verify_header(method, raw_path, query, headers,
                                       body_sha256, auth)
        q = dict(urllib.parse.parse_qsl(query, keep_blank_values=True))
        if "X-Amz-Signature" in q:
            return self._verify_presigned(method, raw_path, query,
                                          headers, q)
        raise AuthError("AccessDenied", "no credentials provided")

    def _identity(self, access_key: str) -> Identity:
        ident = self.by_access_key.get(access_key)
        if ident is None:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key}")
        return ident

    def _verify_header(self, method, raw_path, query, headers,
                       body_sha256, auth) -> Identity:
        parts = {}
        for piece in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = piece.strip().partition("=")
            parts[k] = v
        try:
            cred = parts["Credential"]
            signed_headers = parts["SignedHeaders"]
            signature = parts["Signature"]
        except KeyError as e:
            raise AuthError("AuthorizationHeaderMalformed",
                            f"missing {e}") from e
        access_key, date, region, service, _ = cred.split("/", 4)
        ident = self._identity(access_key)
        amz_date = headers.get("x-amz-date") or headers.get("X-Amz-Date")
        if not amz_date:
            raise AuthError("AccessDenied", "missing x-amz-date")
        _check_date_freshness(amz_date, date)
        canonical_headers = "".join(
            f"{h}:{' '.join((headers.get(h) or '').split())}\n"
            for h in signed_headers.split(";"))
        payload = headers.get("x-amz-content-sha256") or body_sha256
        # The signature must cover the bytes actually received, not just
        # the client-claimed hash header (tamper protection).
        if payload not in ("UNSIGNED-PAYLOAD",
                           "STREAMING-AWS4-HMAC-SHA256-PAYLOAD") \
                and payload != body_sha256:
            raise AuthError("SignatureDoesNotMatch",
                            "x-amz-content-sha256 does not match body")
        creq = "\n".join([method, raw_path or "/",
                          _canonical_query(query), canonical_headers,
                          signed_headers, payload])
        sts = "\n".join(["AWS4-HMAC-SHA256", amz_date,
                         f"{date}/{region}/{service}/aws4_request",
                         hashlib.sha256(creq.encode()).hexdigest()])
        want = hmac.new(
            _signing_key(ident.secret_key, date, region, service),
            sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, signature):
            raise AuthError("SignatureDoesNotMatch",
                            "signature mismatch")
        return ident

    def _verify_presigned(self, method, raw_path, query, headers,
                          q) -> Identity:
        try:
            cred = q["X-Amz-Credential"]
            amz_date = q["X-Amz-Date"]
            signed_headers = q["X-Amz-SignedHeaders"]
            signature = q["X-Amz-Signature"]
        except KeyError as e:
            raise AuthError("AuthorizationQueryParametersError",
                            f"missing {e}") from e
        access_key, date, region, service, _ = cred.split("/", 4)
        ident = self._identity(access_key)
        import calendar
        import time as _time

        try:
            t0 = calendar.timegm(
                _time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
            expires = int(q.get("X-Amz-Expires", "604800"))
        except ValueError as e:
            raise AuthError("AuthorizationQueryParametersError",
                            str(e)) from e
        if _time.time() > t0 + min(expires, 604800):
            raise AuthError("AccessDenied", "request has expired")
        canonical_headers = "".join(
            f"{h}:{' '.join((headers.get(h) or '').split())}\n"
            for h in signed_headers.split(";"))
        creq = "\n".join([method, raw_path or "/",
                          _canonical_query(query, drop_signature=True),
                          canonical_headers, signed_headers,
                          "UNSIGNED-PAYLOAD"])
        sts = "\n".join(["AWS4-HMAC-SHA256", amz_date,
                         f"{date}/{region}/{service}/aws4_request",
                         hashlib.sha256(creq.encode()).hexdigest()])
        want = hmac.new(
            _signing_key(ident.secret_key, date, region, service),
            sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, signature):
            raise AuthError("SignatureDoesNotMatch",
                            "signature mismatch")
        return ident


def sign_request_headers(method: str, url: str, headers: dict,
                         body: bytes, access_key: str,
                         secret_key: str, region: str = "us-east-1",
                         service: str = "s3") -> dict:
    """Client-side SigV4 signer (tests + interop tooling)."""
    import datetime

    u = urllib.parse.urlsplit(url)
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    payload = hashlib.sha256(body).hexdigest()
    out = dict(headers)
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload
    out["host"] = u.netloc
    signed = ";".join(sorted(h.lower() for h in
                             ("host", "x-amz-date",
                              "x-amz-content-sha256")))
    canonical_headers = "".join(
        f"{h}:{' '.join(out[h].split())}\n" for h in signed.split(";"))
    creq = "\n".join([method, u.path or "/",
                      _canonical_query(u.query), canonical_headers,
                      signed, payload])
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date,
                     f"{date}/{region}/{service}/aws4_request",
                     hashlib.sha256(creq.encode()).hexdigest()])
    sig = hmac.new(_signing_key(secret_key, date, region, service),
                   sts.encode(), hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{date}/{region}/"
        f"{service}/aws4_request, SignedHeaders={signed}, "
        f"Signature={sig}")
    del out["host"]  # urllib sets it; keep for canonicalization only
    return out
