"""S3 REST gateway over the filer (weed/s3api analog, SURVEY.md §2).

Buckets are directories under ``/buckets`` on the filer, objects are
filer entries beneath them — the reference's layout. Supported surface:
bucket CRUD + listing, object PUT/GET/HEAD/DELETE with ranges,
CopyObject, ListObjectsV1/V2 (prefix, delimiter, continuation, max-keys)
and multipart uploads. Multipart "complete" is metadata-only: each
part's chunk list is re-offset and concatenated into the final entry, so
terabyte objects assemble without moving a byte — the chunked-entry
design makes the reference's part-merge copy unnecessary.

Auth: AWS SigV4 (header or presigned) against identities from an
s3-config JSON; with no identities the gateway is open (reference
default).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler
from typing import Optional

from ..cache import invalidation as invalidation_mod
from ..cache import readahead as readahead_mod
from ..cluster import usage as usage_mod
from ..cluster.filer_client import FilerClient, FilerClientError
from ..pb import filer_pb2
from ..util import glog
from ..util import httpserver
from ..util import profiler
from ..util import tracing
from ..util import varz
from ..util.stats import Metrics
from .s3_auth import AuthError, Identity, SigV4Verifier

BUCKETS_DIR = "/buckets"
#: Filer-stored gateway config (the reference keeps its s3 identities
#: in the filer and the gateway subscribes for live reloads; shell
#: `s3.configure` edits this file).
S3_CONF_PATH = "/etc/s3/identities.json"
UPLOADS_DIR = ".uploads"
XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _xml(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>' + \
        ET.tostring(root)


def _error_xml(code: str, message: str, resource: str) -> bytes:
    e = ET.Element("Error")
    ET.SubElement(e, "Code").text = code
    ET.SubElement(e, "Message").text = message
    ET.SubElement(e, "Resource").text = resource
    return _xml(e)


_STATUS = {"NoSuchBucket": 404, "NoSuchKey": 404, "NoSuchUpload": 404,
           "BucketAlreadyExists": 409, "BucketNotEmpty": 409,
           "AccessDenied": 403, "InvalidAccessKeyId": 403,
           "SignatureDoesNotMatch": 403, "InvalidArgument": 400,
           "AuthorizationHeaderMalformed": 400,
           "AuthorizationQueryParametersError": 400,
           "InvalidPart": 400, "MalformedXML": 400,
           "InvalidRange": 416, "RequestTimeTooSkewed": 403,
           "InternalError": 500}


class S3Error(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(message or code)
        self.code = code
        self.message = message or code


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


class S3Gateway:
    def __init__(self, filer_url: str, ip: str = "127.0.0.1",
                 port: int = 8333,
                 identities: Optional[list[Identity]] = None,
                 master_url: str = "",
                 qos: Optional[httpserver.QosEngine] = None):
        self.filer = FilerClient(filer_url)
        self.ip = ip
        self.port = port
        self.url = f"{ip}:{port}"
        #: Per-tenant traffic accounting (tenant = the SigV4 identity
        #: name; "anonymous" on an open gateway). Pushed to the master
        #: when one is configured — the gateway does not heartbeat.
        self.master_url = master_url
        self.usage = usage_mod.UsageCollector("s3")
        self._usage_pusher: Optional[usage_mod.UsagePusher] = None
        #: identities passed explicitly (-config file) are static; with
        #: none, the gateway follows the filer-stored config and
        #: reloads it live (the reference's s3.configure flow)
        self.static_identities = identities is not None
        self.auth = SigV4Verifier(identities)
        self.metrics = Metrics(namespace="s3")
        #: per-tenant QoS ladder ([qos] in the server TOML); None =
        #: no classes configured, gateway sheds on raw pressure like
        #: the other components
        self.qos = qos
        self._http_server: Optional[httpserver.IngressHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._conf_stop = threading.Event()
        self._conf_thread: Optional[threading.Thread] = None
        #: becomes True after the first DEFINITIVE config read (loaded
        #: or confirmed absent); before that, transient filer errors
        #: leave the gateway deny-all instead of open
        self._conf_loaded = False
        #: Ranged-read readahead (docs/workloads.md): one window per
        #: (path, etag) byte stream, LRU-bounded so churning keys can't
        #: grow state; block cache keys inserted by prefetch and not
        #: yet read live in _ra_prefetched for hit/wasted accounting.
        self._ra_lock = threading.Lock()
        self._ra_windows: OrderedDict[str, object] = OrderedDict()
        self._ra_prefetched: set[str] = set()
        #: block key -> Event set when its in-flight prefetch lands;
        #: a foreground miss WAITS on this instead of re-fetching the
        #: same block the prefetcher already has on the wire
        self._ra_inflight: dict[str, threading.Event] = {}

    def _load_filer_identities(self) -> None:
        try:
            raw = self.filer.get_data(S3_CONF_PATH)
        except Exception as e:  # noqa: BLE001
            if getattr(e, "code", None) == 404:
                # confirmed absent: the operator removed the config,
                # gateway runs open (reference default without config)
                self.auth.set_identities(None)
                # one-way bool latch; both writers only ever set True
                # seaweedlint: disable=SW801 — idempotent latch
                self._conf_loaded = True
            elif self._conf_loaded:
                # transient (filer restart, network): auth must NOT
                # fail open — keep the previous identity set
                glog.warning("s3: cannot read %s (%s); keeping "
                             "previous identities", S3_CONF_PATH, e)
            else:
                # never read a definitive state: deny everything
                # rather than starting open with a config possibly
                # present but unreadable
                glog.warning("s3: cannot read %s (%s); denying all "
                             "requests until the filer answers",
                             S3_CONF_PATH, e)
                self.auth.set_unavailable()
            return
        try:
            import json as json_mod
            idents = parse_identities(json_mod.loads(raw))
        except Exception as e:  # noqa: BLE001 — keep the old set
            glog.warning("s3: bad %s: %s (keeping previous identities)",
                         S3_CONF_PATH, e)
            return
        self.auth.set_identities(idents)
        self._conf_loaded = True
        glog.info("s3: loaded %d identities from filer %s",
                  len(idents), S3_CONF_PATH)

    def _follow_conf(self) -> None:
        """Reload identities whenever the filer-stored config changes
        (SubscribeMetadata on its directory; reconnect with backoff)."""
        conf_dir = S3_CONF_PATH.rsplit("/", 1)[0]
        while not self._conf_stop.is_set():
            try:
                attached = False
                for resp in self.filer.subscribe(
                        path_prefix=conf_dir,
                        client_name=f"s3-{self.port}"):
                    if self._conf_stop.is_set():
                        return
                    if not attached:
                        # the stream's hello marker: re-read the config
                        # once per (re)attach, covering changes made
                        # while we were detached (live-only streams
                        # replay nothing)
                        attached = True
                        self._load_filer_identities()
                        continue
                    note = resp.event_notification
                    if note.new_entry.name or note.old_entry.name:
                        self._load_filer_identities()
            except Exception as e:  # noqa: BLE001 — filer restart etc.
                glog.v(1, "s3 identity watch stream broke: %s", e)
            # stream ended (error OR clean server-side return): pause
            # before re-attaching so a lagging/shutting-down filer is
            # not hammered in a tight loop
            if self._conf_stop.wait(1.0):
                return

    def start(self) -> "S3Gateway":
        if not self.static_identities:
            self._load_filer_identities()
            self._conf_thread = threading.Thread(
                target=self._follow_conf, daemon=True,
                name=f"s3-conf-{self.port}")
            self._conf_thread.start()
        handler = _make_handler(self)
        self._http_server = httpserver.IngressHTTPServer(
            (self.ip, self.port), handler, component="s3")
        # class-aware shedding replaces the generic pressure 429 (the
        # admission gate skips it when .qos is set, so guaranteed
        # tenants are never blind-shed before authentication)
        self._http_server.qos = self.qos
        self._thread = threading.Thread(
            target=self._http_server.serve_forever, daemon=True,
            name=f"s3-{self.port}")
        self._thread.start()
        if self.master_url:
            self._usage_pusher = usage_mod.UsagePusher(
                self.usage, self.master_url, f"s3@{self.url}").start()
            # Job-commit cache invalidation: register this gateway's
            # chunk cache for the master's fan-out (docs/jobs.md).
            invalidation_mod.start_subscriber(self.master_url,
                                              self.url,
                                              self._conf_stop)
        glog.info("s3 gateway at %s -> filer %s", self.url,
                  self.filer.filer_url)
        return self

    def stop(self) -> None:
        self._conf_stop.set()
        if self._usage_pusher is not None:
            self._usage_pusher.stop()
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        self.filer.close()

    def __enter__(self) -> "S3Gateway":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def account(self, ident, bucket: str, key: str, *,
                n_in: int = 0, n_out: int = 0, seconds: float = 0.0,
                error: bool = False) -> None:
        """One usage row per request; object keys feed the hot-key
        sketch as ``bucket/key`` so /cluster/topk can attribute them."""
        self.usage.record(
            ident.name if ident is not None else "anonymous", bucket,
            n_in=n_in, n_out=n_out, seconds=seconds, error=error,
            key=f"{bucket}/{key}" if bucket and key else "")

    # ---- bucket ops ----

    def list_buckets(self, ident=None) -> bytes:
        root = ET.Element("ListAllMyBucketsResult", xmlns=XMLNS)
        owner = ET.SubElement(root, "Owner")
        ET.SubElement(owner, "ID").text = "seaweedfs-tpu"
        buckets = ET.SubElement(root, "Buckets")
        for e in self.filer.list(BUCKETS_DIR):
            if not e.is_directory or e.name == UPLOADS_DIR:
                continue
            if ident is not None and not (
                    ident.can("Read", e.name) or
                    ident.can("Write", e.name)):
                # scoped identities see only buckets they can touch
                # (weed s3api filters the listing the same way)
                continue
            b = ET.SubElement(buckets, "Bucket")
            ET.SubElement(b, "Name").text = e.name
            ET.SubElement(b, "CreationDate").text = _iso(
                e.attributes.crtime or e.attributes.mtime)
        return _xml(root)

    def create_bucket(self, bucket: str) -> None:
        if self.filer.lookup(BUCKETS_DIR, bucket) is not None:
            raise S3Error("BucketAlreadyExists", bucket)
        self.filer.mkdir(BUCKETS_DIR, bucket)

    def delete_bucket(self, bucket: str) -> None:
        self._require_bucket(bucket)
        if next(iter(self.filer.list(f"{BUCKETS_DIR}/{bucket}",
                                     limit=1)), None) is not None:
            raise S3Error("BucketNotEmpty", bucket)
        self.filer.delete(BUCKETS_DIR, bucket, recursive=True)

    def _require_bucket(self, bucket: str) -> None:
        if self.filer.lookup(BUCKETS_DIR, bucket) is None:
            raise S3Error("NoSuchBucket", bucket)

    # ---- object listing ----

    def list_objects(self, bucket: str, q: dict, v2: bool) -> bytes:
        self._require_bucket(bucket)
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", "1000"))
        if v2:
            after = q.get("continuation-token") or q.get("start-after",
                                                         "")
        else:
            after = q.get("marker", "")
        base = f"{BUCKETS_DIR}/{bucket}"
        # items: ("key", key, entry) | ("prefix", prefix, None), in key
        # order — one list so the continuation token is always the last
        # EMITTED name, whether that was an object or a common prefix.
        items: list[tuple[str, str, Optional[filer_pb2.Entry]]] = []
        # max-keys=0 is legal: answer IsTruncated=false with no items
        # (matching AWS) instead of a token-less truncated response.
        truncated = max_keys > 0 and self._walk(
            base, "", prefix, delimiter, after, max_keys, items)
        root = ET.Element(
            "ListBucketResult", xmlns=XMLNS)
        ET.SubElement(root, "Name").text = bucket
        ET.SubElement(root, "Prefix").text = prefix
        ET.SubElement(root, "MaxKeys").text = str(max_keys)
        ET.SubElement(root, "IsTruncated").text = \
            "true" if truncated else "false"
        if delimiter:
            ET.SubElement(root, "Delimiter").text = delimiter
        if v2:
            ET.SubElement(root, "KeyCount").text = str(len(items))
            if truncated and items:
                ET.SubElement(root, "NextContinuationToken").text = \
                    items[-1][1]
        elif truncated and items:
            ET.SubElement(root, "NextMarker").text = items[-1][1]
        for kind, key, e in items:
            if kind == "prefix":
                cp = ET.SubElement(root, "CommonPrefixes")
                ET.SubElement(cp, "Prefix").text = key
                continue
            c = ET.SubElement(root, "Contents")
            ET.SubElement(c, "Key").text = key
            ET.SubElement(c, "LastModified").text = _iso(
                e.attributes.mtime)
            ET.SubElement(c, "ETag").text = f'"{_etag(e)}"'
            ET.SubElement(c, "Size").text = str(e.attributes.file_size)
            ET.SubElement(c, "StorageClass").text = "STANDARD"
        return _xml(root)

    def _walk(self, base: str, rel: str, prefix: str, delimiter: str,
              after: str, max_keys: int, items: list) -> bool:
        """DFS in key order; returns True when truncated. Common
        prefixes count against max-keys at append time, same as keys
        (the S3 contract: MaxKeys bounds keys + CommonPrefixes)."""
        directory = f"{base}/{rel}" if rel else base
        for e in self.filer.list(directory):
            key = f"{rel}{e.name}" if not e.is_directory else \
                f"{rel}{e.name}/"
            if e.is_directory and e.name == UPLOADS_DIR and not rel:
                continue
            probe = key if not e.is_directory else key[:-1]
            if prefix and not probe.startswith(prefix) \
                    and not prefix.startswith(key):
                continue
            if e.is_directory:
                if delimiter == "/" and key.startswith(prefix):
                    if key > after:
                        if len(items) >= max_keys:
                            return True
                        items.append(("prefix", key, None))
                    continue
                if self._walk(base, key, prefix, delimiter, after,
                              max_keys, items):
                    return True
                continue
            if not key.startswith(prefix) or key <= after:
                continue
            if len(items) >= max_keys:
                return True
            items.append(("key", key, e))
        return False

    # ---- object ops ----

    def put_object(self, bucket: str, key: str, data: bytes,
                   mime: str) -> str:
        self._require_bucket(bucket)
        self.filer.put_data(f"{BUCKETS_DIR}/{bucket}/{key}", data,
                            mime=mime)
        return hashlib.md5(data).hexdigest()

    def get_object_entry(self, bucket: str, key: str) -> filer_pb2.Entry:
        self._require_bucket(bucket)
        d, _, name = f"{BUCKETS_DIR}/{bucket}/{key}".rpartition("/")
        e = self.filer.lookup(d, name)
        if e is None or e.is_directory:
            raise S3Error("NoSuchKey", key)
        return e

    #: Ranged reads cache in fixed blocks, so an arbitrary
    #: (offset, length) stream mints at most size/RANGE_BLOCK distinct
    #: keys per object version — never one key per request shape (the
    #: whole-object-poisoning bug this replaced).
    RANGE_BLOCK = 1 * 1024 * 1024
    #: Streams with live readahead windows (LRU cap).
    RANGE_STREAMS = 64
    #: Readahead window ceiling, in RANGE_BLOCK units (8 MiB): deep
    #: enough to hide filer latency, shallow enough that a seek does
    #: not strand tens of MiB of wasted prefetch.
    RANGE_WINDOW_UNITS = 8
    #: Max blocks one prefetch filer read may claim: a foreground read
    #: waiting on a claimed block waits for at most this much data.
    PREFETCH_RUN_BLOCKS = 4

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        entry = self.get_object_entry(bucket, key)
        path = f"{BUCKETS_DIR}/{bucket}/{key}"
        # Hot-read cache, keyed on content identity (etag covers the
        # chunk list): an overwrite changes the etag, so stale entries
        # can never serve — they just age out of the LRU.
        from ..cache import global_chunk_cache

        etag = _etag(entry)
        size = entry.attributes.file_size
        cache = global_chunk_cache()
        full_key = f"s3:{path}:{etag}:full"
        if offset == 0 and length is None:
            data = cache.get(full_key)
            if data is None:
                data = self.filer.get_data(path)
                cache.put(full_key, data)
            return data
        end = min(offset + (size - offset if length is None
                            else length), size)
        if end <= offset:
            return b""
        # A resident full object serves any range by slicing.
        full = cache.get(full_key)
        if full is not None:
            return full[offset:end]
        return self._ranged_read(cache, path, etag, size, offset,
                                 end - offset)

    def _block_key(self, path: str, etag: str, idx: int) -> str:
        return f"s3:{path}:{etag}:blk:{idx}"

    def _ranged_read(self, cache, path: str, etag: str, size: int,
                     offset: int, length: int) -> bytes:
        """Block-aligned read-through for ranged GETs, with sequential
        read-ahead: a confirmed-sequential stream of ranges prefetches
        upcoming blocks into the chunk cache off-thread."""
        bs = self.RANGE_BLOCK
        end = offset + length
        first, last = offset // bs, (end - 1) // bs
        out = bytearray(length)
        b = first
        while b <= last:
            bkey = self._block_key(path, etag, b)
            blk = cache.get(bkey)
            if blk is None:
                # a prefetch already has this block on the wire: wait
                # for it instead of issuing a duplicate fetch
                blk = self._await_inflight(cache, bkey)
            if blk is not None:
                with self._ra_lock:
                    if bkey in self._ra_prefetched:
                        self._ra_prefetched.discard(bkey)
                        readahead_mod.note_hit()
                lo = max(offset, b * bs)
                hi = min(end, b * bs + len(blk))
                if lo < hi:
                    out[lo - offset:hi - offset] = \
                        blk[lo - b * bs:hi - b * bs]
                b += 1
                continue
            # contiguous run of blocks neither cached nor in flight,
            # fetched in ONE filer read
            run_end = b + 1
            while run_end <= last:
                k = self._block_key(path, etag, run_end)
                if cache.get(k) is not None:
                    break
                with self._ra_lock:
                    if k in self._ra_inflight:
                        break
                run_end += 1
            blob = self.filer.get_data(
                path, b * bs, min(run_end * bs, size) - b * bs)
            for i in range(b, run_end):
                cache.put(self._block_key(path, etag, i),
                          blob[(i - b) * bs:(i - b + 1) * bs])
            lo = max(offset, b * bs)
            hi = min(end, b * bs + len(blob))
            if lo < hi:
                out[lo - offset:hi - offset] = \
                    blob[lo - b * bs:hi - b * bs]
            b = run_end
        self._observe_stream(cache, path, etag, size, offset, length)
        return bytes(out)

    #: A foreground read waits at most this long on an in-flight
    #: prefetch of the block it needs before fetching it itself (the
    #: duplicate fetch is the fallback, not the norm).
    PREFETCH_WAIT_SECONDS = 30.0

    def _await_inflight(self, cache, bkey: str):
        with self._ra_lock:
            ev = self._ra_inflight.get(bkey)
        if ev is None:
            return None
        if not ev.wait(self.PREFETCH_WAIT_SECONDS):
            return None
        return cache.get(bkey)

    def _observe_stream(self, cache, path: str, etag: str, size: int,
                        offset: int, length: int) -> None:
        stream = f"{path}:{etag}"
        with self._ra_lock:
            win = self._ra_windows.get(stream)
            if win is None:
                win = readahead_mod.ReadaheadWindow(
                    unit=self.RANGE_BLOCK,
                    max_units=self.RANGE_WINDOW_UNITS)
                self._ra_windows[stream] = win
                while len(self._ra_windows) > self.RANGE_STREAMS:
                    _, old = self._ra_windows.popitem(last=False)
                    old.close()
            self._ra_windows.move_to_end(stream)
            plan = win.observe(offset, length, size)
        if plan is None:
            return
        start, nbytes = plan
        bs = self.RANGE_BLOCK

        def _prefetch() -> None:
            fetched = 0
            lo_blk = start // bs
            hi_blk = (start + nbytes + bs - 1) // bs
            i = lo_blk
            while i < hi_blk:
                if cache.get(self._block_key(path, etag, i)) \
                        is not None:
                    i += 1
                    continue
                # claim a contiguous run of uncached, unclaimed
                # blocks, then fetch the whole run in ONE filer read
                claimed: list[tuple[str, threading.Event]] = []
                with self._ra_lock:
                    j = i
                    while (j < hi_blk
                           and len(claimed) < self.PREFETCH_RUN_BLOCKS):
                        k = self._block_key(path, etag, j)
                        if k in self._ra_inflight:
                            break
                        ev = threading.Event()
                        self._ra_inflight[k] = ev
                        claimed.append((k, ev))
                        j += 1
                if not claimed:
                    i += 1
                    continue
                try:
                    blob = self.filer.get_data(
                        path, i * bs, min(j * bs, size) - i * bs)
                    # publish each block the moment its bytes land so a
                    # foreground reader waiting on it unblocks without
                    # waiting for the rest of the run
                    for n, (k, ev) in enumerate(claimed):
                        cache.put(k, blob[n * bs:(n + 1) * bs])
                        with self._ra_lock:
                            self._ra_prefetched.add(k)
                            self._ra_inflight.pop(k, None)
                            while len(self._ra_prefetched) > 4096:
                                self._ra_prefetched.pop()
                                readahead_mod.note_wasted()
                        ev.set()
                    fetched += len(blob)
                finally:
                    with self._ra_lock:
                        for k, ev in claimed:
                            self._ra_inflight.pop(k, None)
                            ev.set()
                i = j
            if fetched:
                readahead_mod.record_prefetch(fetched)

        readahead_mod.shared_prefetcher().submit(
            ("s3", path, etag, start), _prefetch)

    def delete_object(self, bucket: str, key: str) -> None:
        self._require_bucket(bucket)
        d, _, name = f"{BUCKETS_DIR}/{bucket}/{key}".rpartition("/")
        try:
            self.filer.delete(d, name, recursive=True)
        except FilerClientError:
            pass  # S3 deletes are idempotent

    #: Copy window: bounds gateway memory and keeps each filer PUT well
    #: inside the HTTP client timeout for arbitrarily large objects.
    COPY_WINDOW = 32 * 1024 * 1024

    def copy_object(self, bucket: str, key: str, src_bucket: str,
                    src_key: str) -> bytes:
        src = self.get_object_entry(src_bucket, src_key)
        self._require_bucket(bucket)
        src_path = f"{BUCKETS_DIR}/{src_bucket}/{src_key}"
        dst_path = f"{BUCKETS_DIR}/{bucket}/{key}"
        # Self-copy (the S3 metadata-refresh idiom) must not touch the
        # data path: copy_data no-ops, and the entry stays as-is.
        self.filer.copy_data(src_path, dst_path,
                             src.attributes.file_size,
                             mime=src.attributes.mime,
                             window=self.COPY_WINDOW,
                             extended=dict(src.extended))
        # Report the DESTINATION's ETag: the copy has its own chunk ids,
        # so echoing the source's would mismatch a later GET/HEAD.
        dst = self.get_object_entry(bucket, key)
        root = ET.Element("CopyObjectResult", xmlns=XMLNS)
        ET.SubElement(root, "LastModified").text = _iso(time.time())
        ET.SubElement(root, "ETag").text = f'"{_etag(dst)}"'
        return _xml(root)

    # ---- multipart ----

    def initiate_multipart(self, bucket: str, key: str) -> bytes:
        self._require_bucket(bucket)
        upload_id = uuid.uuid4().hex
        self.filer.mkdir(f"{BUCKETS_DIR}/{UPLOADS_DIR}", upload_id)
        marker = filer_pb2.Entry(name="key", is_directory=False)
        marker.extended["key"] = key.encode()
        marker.extended["bucket"] = bucket.encode()
        self.filer.create(f"{BUCKETS_DIR}/{UPLOADS_DIR}/{upload_id}",
                          marker)
        root = ET.Element("InitiateMultipartUploadResult", xmlns=XMLNS)
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "UploadId").text = upload_id
        return _xml(root)

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_number: int, data: bytes) -> str:
        self._upload_dir(upload_id, bucket)
        self.filer.put_data(
            f"{BUCKETS_DIR}/{UPLOADS_DIR}/{upload_id}/"
            f"{part_number:05d}.part", data)
        return hashlib.md5(data).hexdigest()

    def _upload_dir(self, upload_id: str,
                    bucket: Optional[str] = None) -> str:
        d = f"{BUCKETS_DIR}/{UPLOADS_DIR}/{upload_id}"
        if self.filer.lookup(f"{BUCKETS_DIR}/{UPLOADS_DIR}",
                             upload_id) is None:
            raise S3Error("NoSuchUpload", upload_id)
        if bucket is not None:
            # the URL bucket was what the caller was AUTHORIZED against;
            # it must be the bucket the upload was initiated in, or a
            # scoped identity could drive another bucket's upload
            marker = self.filer.lookup(d, "key")
            owner = (marker.extended.get("bucket", b"").decode()
                     if marker is not None else "")
            # Markers written before the bucket attribute existed have
            # owner == "" and skip the check (back-compat: such legacy
            # in-flight uploads remain drivable from any bucket the
            # caller can Write). New markers always carry the attribute,
            # so the window closes as old uploads complete or expire.
            if owner and owner != bucket:
                raise S3Error("NoSuchUpload", upload_id)
        return d

    def complete_multipart(self, bucket: str, key: str,
                           upload_id: str) -> bytes:
        d = self._upload_dir(upload_id, bucket)
        parts = sorted(
            (e for e in self.filer.list(d)
             if e.name.endswith(".part")), key=lambda e: e.name)
        if not parts:
            raise S3Error("InvalidPart", "no parts uploaded")
        # Metadata-only assembly: concatenate every part's chunks with
        # re-based offsets into one entry.
        final = filer_pb2.Entry(name=key.rsplit("/", 1)[-1],
                                is_directory=False)
        offset = 0
        for p in parts:
            for c in p.chunks:
                nc = final.chunks.add()
                nc.CopyFrom(c)
                nc.offset = offset + c.offset
            offset += p.attributes.file_size
        final.attributes.CopyFrom(parts[0].attributes)
        final.attributes.file_size = offset
        final.attributes.mtime = int(time.time())
        dst_dir = f"{BUCKETS_DIR}/{bucket}/{key}".rpartition("/")[0]
        self.filer.create(dst_dir, final)
        # Drop the upload scaffolding WITHOUT deleting chunk data — the
        # final entry owns those chunks now.
        self.filer.delete(f"{BUCKETS_DIR}/{UPLOADS_DIR}", upload_id,
                          recursive=True, delete_data=False)
        root = ET.Element("CompleteMultipartUploadResult", xmlns=XMLNS)
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "ETag").text = \
            f'"{hashlib.md5(str(offset).encode()).hexdigest()}-' \
            f'{len(parts)}"'
        return _xml(root)

    def abort_multipart(self, upload_id: str,
                        bucket: Optional[str] = None) -> None:
        self._upload_dir(upload_id, bucket)
        self.filer.delete(f"{BUCKETS_DIR}/{UPLOADS_DIR}", upload_id,
                          recursive=True, delete_data=True)


def _parse_s3_range(header, size: int):
    """S3 single-range semantics: returns (offset, length), or None to
    serve the full body with 200 (absent/malformed headers are ignored,
    per RFC 7233). Raises InvalidRange (416) when the range is
    syntactically valid but unsatisfiable, e.g. ``bytes=500-`` on a
    100-byte object."""
    if not header or not header.startswith("bytes=") or not size:
        return None
    spec = header[6:].split(",")[0].strip()
    lo, sep, hi = spec.partition("-")
    if not sep:
        return None
    try:
        if not lo:  # suffix: last N bytes
            n = int(hi)
            if n <= 0:
                return None
            offset = max(0, size - n)
            return offset, size - offset
        offset = int(lo)
        stop = int(hi) + 1 if hi else size
    except ValueError:
        return None
    if offset < 0 or (hi and stop <= offset):
        return None  # malformed (last-byte-pos < first-byte-pos)
    if offset >= size:
        raise S3Error("InvalidRange",
                      f"range start {offset} beyond object size {size}")
    return offset, min(stop, size) - offset


def _etag(e: filer_pb2.Entry) -> str:
    if e.extended.get("etag"):
        return e.extended["etag"].decode()
    h = hashlib.md5()
    for c in e.chunks:
        h.update(c.file_id.encode())
    return h.hexdigest()


def _make_handler(gw: S3Gateway):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "seaweedfs-tpu-s3"

        def log_message(self, fmt, *args):
            glog.v(2, "s3 http: " + fmt, *args)

        # -- plumbing --

        def _split(self) -> tuple[str, str, dict, str]:
            u = urllib.parse.urlsplit(self.path)
            q = {k: v[0] for k, v in urllib.parse.parse_qs(
                u.query, keep_blank_values=True).items()}
            parts = urllib.parse.unquote(u.path).lstrip("/").split(
                "/", 1)
            bucket = parts[0]
            key = parts[1] if len(parts) > 1 else ""
            return bucket, key, q, u.query

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length", "0"))
            return self.rfile.read(n) if n else b""

        def _send(self, code: int, body: bytes = b"",
                  ctype: str = "application/xml",
                  extra: Optional[dict] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            if not extra or "Content-Length" not in extra:
                self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            if body and self.command != "HEAD":
                self.wfile.write(body)

        def _fail(self, exc) -> None:
            if isinstance(exc, httpserver.QosShed):
                # tenant over its class budget (or pressure-shed by
                # the priority ladder): S3's throttling surface
                self._send(429,
                           _error_xml("SlowDown", str(exc), self.path),
                           extra={"Retry-After":
                                  str(max(1, int(exc.retry_after)))})
                return
            if isinstance(exc, AuthError):
                code, msg = exc.code, str(exc)
            elif isinstance(exc, S3Error):
                code, msg = exc.code, exc.message
            elif isinstance(exc, FilerClientError):
                code, msg = "InternalError", str(exc)
            else:
                code, msg = "InternalError", str(exc)
            self._send(_STATUS.get(code, 500),
                       _error_xml(code, msg, self.path))

        def _auth(self, body: bytes, action: str = "",
                  bucket: str = ""):
            u = urllib.parse.urlsplit(self.path)
            ident = gw.auth.verify(self.command, u.path or "/", u.query,
                                   self.headers,
                                   hashlib.sha256(body).hexdigest())
            # authorization (weed s3.configure identity actions): None
            # identity = open gateway, all actions permitted
            if ident is not None and action and \
                    not ident.can(action, bucket):
                raise AuthError(
                    "AccessDenied",
                    f"{action} on {bucket or 'service'} not permitted "
                    f"for {ident.name}")
            return ident

        def _qos(self, ident) -> Optional[httpserver.QosLease]:
            """Class-aware admission, AFTER SigV4 so the tenant is the
            authenticated identity. Raises QosShed (-> 429 SlowDown)
            when the tenant's class is over budget or sheds under the
            current queue pressure."""
            srv = self.server
            qos = getattr(srv, "qos", None)
            if qos is None:
                return None
            pressure = srv.pressure() if hasattr(srv, "pressure") \
                else 0.0
            return qos.admit(
                ident.name if ident is not None else "anonymous",
                pressure)

        # -- verbs --

        def do_GET(self):
            u = urllib.parse.urlsplit(self.path)
            if u.path == "/debug/vars":
                import json

                self._send(200, json.dumps(varz.payload(
                    "s3", gw.metrics,
                    extra={"usage": gw.usage.to_payload()})).encode(),
                    "application/json")
                return
            if u.path == "/debug/profile":
                q = dict(urllib.parse.parse_qsl(u.query))
                self._send(200, profiler.profile(
                    float(q.get("seconds", 2.0)),
                    hz=float(q.get("hz", profiler.DEFAULT_BURST_HZ))
                ).encode(), "text/plain; charset=utf-8")
                return
            bucket, key, q, _ = self._split()
            gw.metrics.counter("request_total", method="GET").inc()
            t0 = time.perf_counter()
            ident = None
            n_out = 0
            err = False
            lease = None
            try:
                ident = self._auth(b"", "Read" if bucket else "", bucket)
                lease = self._qos(ident)
                if not bucket:
                    self._send(200, gw.list_buckets(ident))
                elif not key:
                    v2 = q.get("list-type") == "2"
                    self._send(200, gw.list_objects(bucket, q, v2))
                else:
                    entry = gw.get_object_entry(bucket, key)
                    size = entry.attributes.file_size
                    offset, length = 0, None
                    status, extra = 200, {"Accept-Ranges": "bytes"}
                    parsed = _parse_s3_range(
                        self.headers.get("Range"), size)
                    if parsed is not None:
                        offset, length = parsed
                        status = 206
                        extra["Content-Range"] = \
                            f"bytes {offset}-{offset + length - 1}" \
                            f"/{size}"
                    data = gw.get_object(bucket, key, offset, length)
                    n_out = len(data)
                    extra["ETag"] = f'"{_etag(entry)}"'
                    extra["Last-Modified"] = time.strftime(
                        "%a, %d %b %Y %H:%M:%S GMT",
                        time.gmtime(entry.attributes.mtime))
                    self._send(status, data,
                               entry.attributes.mime
                               or "application/octet-stream", extra)
            except Exception as e:
                err = True
                self._fail(e)
            finally:
                if lease is not None:
                    lease.release()
                gw.account(ident, bucket, key, n_out=n_out,
                           seconds=time.perf_counter() - t0, error=err)

        def do_HEAD(self):
            bucket, key, q, _ = self._split()
            ident = None
            err = False
            lease = None
            try:
                ident = self._auth(b"", "Read", bucket)
                lease = self._qos(ident)
                if not key:
                    gw._require_bucket(bucket)
                    self._send(200)
                    return
                entry = gw.get_object_entry(bucket, key)
                self._send(200, b"",
                           entry.attributes.mime
                           or "application/octet-stream",
                           {"Content-Length":
                            str(entry.attributes.file_size),
                            "Accept-Ranges": "bytes",
                            "ETag": f'"{_etag(entry)}"'})
            except Exception as e:
                err = True
                self._fail(e)
            finally:
                if lease is not None:
                    lease.release()
                gw.account(ident, bucket, "", error=err)

        def do_PUT(self):
            bucket, key, q, _ = self._split()
            gw.metrics.counter("request_total", method="PUT").inc()
            body = self._body()
            t0 = time.perf_counter()
            ident = None
            err = False
            lease = None
            try:
                ident = self._auth(body, "Write" if key else "Admin",
                                   bucket)
                lease = self._qos(ident)
                if not key:
                    gw.create_bucket(bucket)
                    self._send(200)
                elif "partNumber" in q and "uploadId" in q:
                    etag = gw.upload_part(bucket, key, q["uploadId"],
                                          int(q["partNumber"]), body)
                    self._send(200, b"", extra={"ETag": f'"{etag}"'})
                elif "x-amz-copy-source" in self.headers:
                    src = urllib.parse.unquote(
                        self.headers["x-amz-copy-source"]).lstrip("/")
                    sb, _, sk = src.partition("/")
                    # copying also READS the source bucket (identity is
                    # already authenticated; just authorize)
                    if ident is not None and not ident.can("Read", sb):
                        raise AuthError(
                            "AccessDenied",
                            f"Read on {sb} not permitted for "
                            f"{ident.name}")
                    self._send(200, gw.copy_object(bucket, key, sb, sk))
                else:
                    etag = gw.put_object(
                        bucket, key, body,
                        self.headers.get("Content-Type", ""))
                    self._send(200, b"", extra={"ETag": f'"{etag}"'})
            except Exception as e:
                err = True
                self._fail(e)
            finally:
                if lease is not None:
                    lease.release()
                gw.account(ident, bucket, key, n_in=len(body),
                           seconds=time.perf_counter() - t0, error=err)

        def do_POST(self):
            if urllib.parse.urlsplit(self.path).path == \
                    "/cache/invalidate":
                # Maintenance-job fan-out (docs/jobs.md): drop cached
                # chunks of a volume a job just rewrote.
                try:
                    self._send(200, json.dumps(
                        invalidation_mod.handle_event(
                            json.loads(self._body() or b"{}"))
                    ).encode(), ctype="application/json")
                except (ValueError, KeyError) as e:
                    self._send(400, json.dumps(
                        {"error": str(e)}).encode(),
                        ctype="application/json")
                return
            bucket, key, q, _ = self._split()
            body = self._body()
            ident = None
            err = False
            lease = None
            try:
                ident = self._auth(body, "Write", bucket)
                lease = self._qos(ident)
                if "uploads" in q:
                    self._send(200, gw.initiate_multipart(bucket, key))
                elif "uploadId" in q:
                    self._send(200, gw.complete_multipart(
                        bucket, key, q["uploadId"]))
                else:
                    raise S3Error("InvalidArgument",
                                  "unsupported POST")
            except Exception as e:
                err = True
                self._fail(e)
            finally:
                if lease is not None:
                    lease.release()
                gw.account(ident, bucket, "", n_in=len(body),
                           error=err)

        def do_DELETE(self):
            bucket, key, q, _ = self._split()
            gw.metrics.counter("request_total", method="DELETE").inc()
            ident = None
            err = False
            lease = None
            try:
                ident = self._auth(b"", "Write" if key else "Admin",
                                   bucket)
                lease = self._qos(ident)
                if "uploadId" in q:
                    gw.abort_multipart(q["uploadId"], bucket)
                    self._send(204)
                elif not key:
                    gw.delete_bucket(bucket)
                    self._send(204)
                else:
                    gw.delete_object(bucket, key)
                    self._send(204)
            except Exception as e:
                err = True
                self._fail(e)
            finally:
                if lease is not None:
                    lease.release()
                gw.account(ident, bucket, "", error=err)

    return tracing.instrument_http_handler(
        httpserver.admission_gate(Handler), "s3")


def parse_identities(cfg: dict) -> list[Identity]:
    """{"identities": [{"name", "credentials": [{"accessKey",
    "secretKey"}], "actions": [...]}]} — the reference's s3.json
    shape, shared by the -config file and the filer-stored config."""
    out = []
    for ident in cfg.get("identities", []):
        for cred in ident.get("credentials", []):
            out.append(Identity(
                name=ident.get("name", cred["accessKey"]),
                access_key=cred["accessKey"],
                secret_key=cred["secretKey"],
                actions=tuple(ident.get("actions", ["Admin"]))))
    return out


def load_identities(path: str) -> list[Identity]:
    import json

    with open(path) as f:
        return parse_identities(json.load(f))


def main(argv: list[str]) -> int:
    import argparse
    import signal

    p = argparse.ArgumentParser(prog="s3")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8333)
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.add_argument("-master", default="",
                   help="master url to push usage accounting to")
    p.add_argument("-config", default="",
                   help="identities JSON (empty = open access)")
    p.add_argument("-toml", default="",
                   help="server TOML ([ingress], [qos], [retry])")
    from ..util import tls as tls_mod
    tls_mod.add_security_flag(p)
    args = p.parse_args(argv)
    tls_mod.install_from_flag(args)
    qos = None
    if args.toml:
        from ..util import config as config_mod
        from ..util import retry as retry_mod
        conf = config_mod.load(args.toml)
        httpserver.configure_from(conf)
        retry_mod.configure_from(conf)
        tracing.configure_from(conf)
        qos = httpserver.qos_from_conf(conf)
    idents = load_identities(args.config) if args.config else None
    gw = S3Gateway(args.filer, ip=args.ip, port=args.port,
                   identities=idents, master_url=args.master,
                   qos=qos).start()
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    stop.wait()
    gw.stop()
    return 0
