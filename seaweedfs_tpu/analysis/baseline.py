"""Checked-in violation baseline: CI fails only on NEW findings.

The baseline is a JSON list of fingerprinted findings
(``seaweedfs_tpu/analysis/baseline.json``). Fingerprints hash the rule
+ qualname + flagged source text — not line numbers — so unrelated
edits above a baselined site do not churn the file. Each entry may
carry a ``justification`` explaining why the violation is accepted;
``--write-baseline`` preserves justifications across rewrites.
"""

from __future__ import annotations

import json
from pathlib import Path

from .findings import Finding


def load_baseline(path: Path) -> dict:
    if not path.exists():
        return {"version": 1, "findings": []}
    data = json.loads(path.read_text())
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"malformed baseline {path}: expected "
                         "{'version': 1, 'findings': [...]}")
    return data


def write_baseline(path: Path, findings: list[Finding],
                   previous: dict | None = None) -> dict:
    old_just = {e["fingerprint"]: e.get("justification", "")
                for e in (previous or {}).get("findings", [])}
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        e = f.to_json()
        just = old_just.get(f.fingerprint, "")
        if just:
            e["justification"] = just
        entries.append(e)
    data = {"version": 1, "findings": entries}
    path.write_text(json.dumps(data, indent=1) + "\n")
    return data


def diff_baseline(findings: list[Finding], baseline: dict
                  ) -> tuple[list[Finding], list[dict]]:
    """-> (new findings not in baseline, stale baseline entries)."""
    known = {e["fingerprint"] for e in baseline.get("findings", [])}
    current = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in known]
    stale = [e for e in baseline.get("findings", [])
             if e["fingerprint"] not in current]
    return new, stale


def prune_baseline(path: Path, findings: list[Finding]) -> list[dict]:
    """Drop baseline entries whose fingerprints match no current
    finding (``seaweedlint --prune-baseline``); justifications on
    surviving entries are untouched. Returns the pruned entries."""
    baseline = load_baseline(path)
    _new, stale = diff_baseline(findings, baseline)
    if stale:
        dead = {e["fingerprint"] for e in stale}
        baseline["findings"] = [e for e in baseline["findings"]
                                if e["fingerprint"] not in dead]
        path.write_text(json.dumps(baseline, indent=1) + "\n")
    return stale
