"""seaweedlint — project-native static analysis for seaweedfs_tpu.

Go's SeaweedFS leans on ``go vet`` and the race detector; this package
is the Python-side equivalent, specialized to the concurrency and
resource idioms THIS codebase actually uses (30+ lock sites across
cache/, cluster/, storage/, filer/, span and handle lifecycles, a
Prometheus-text metrics registry). It is pure stdlib ``ast`` — no jax,
no grpc — so it runs anywhere in milliseconds.

Rule families (see docs/static_analysis.md for the catalog):

- SW1xx  locks: a cross-module lock-acquisition graph built from
  ``with <lock>:`` sites plus a resolved call graph; reports
  lock-order cycles (SW101, error), nested-acquire sites (SW102,
  info), and blocking I/O — sleep/socket/RPC/subprocess (error) or
  file I/O (warning) — performed while a lock is held (SW103).
- SW2xx  resources: files/sockets/channels opened without ``with`` /
  ``finally`` closure (SW201), tracing spans not context-managed
  (SW202).
- SW3xx  exceptions: handlers that swallow silently — ``pass`` with no
  logging (SW301; error in server/heartbeat loops, else warning),
  bare ``except:`` (SW302, error).
- SW4xx  metrics: unbounded label cardinality at util/stats call
  sites — f-string / ``str()`` / %-format label values (SW401, error),
  variable label values and dynamic metric names (SW402, info).

Findings diff against a checked-in baseline
(``seaweedfs_tpu/analysis/baseline.json``) so CI fails only on NEW
violations; inline ``# seaweedlint: disable=SW103 — reason`` pragmas
suppress deliberate sites at the line.

Run: ``python -m seaweedfs_tpu.analysis`` (alias ``scripts/seaweedlint``),
gate: ``scripts/lint_gate.sh``.

The runtime complement — a lock-order *recorder* that watches real
acquisitions under ``SEAWEED_LOCKCHECK=1`` — lives in
``seaweedfs_tpu/util/lockcheck.py``.
"""

from .findings import Finding, SEVERITIES  # noqa: F401
from .engine import analyze_paths, analyze_sources  # noqa: F401
from .baseline import load_baseline, write_baseline, diff_baseline  # noqa: F401
