"""SW8xx: thread-role shared-state race rules.

Consumes the thread-role model (threads.py): which roles reach each
function, the guaranteed lockset on every path into it, and every
shared-state access with its lexically-held locks. The Eraser framing:
an attribute is race-free when the intersection of locksets over all
its cross-role accesses is non-empty; these rules flag the static
shadow of that invariant.

SW801 (error)   instance/module attribute written from >=2 thread
                roles (or one multi-instance role) with an empty
                lockset intersection across the writes.
SW802 (warning) compound read-modify-write (``x += 1``,
                check-then-set) on a shared attribute outside any
                lock — atomic-looking code that is two bytecodes.
SW803 (warning) unguarded dict/list/set mutation on a shared
                collection (CPython's GIL keeps single ops from
                corrupting, but iterate-while-mutate and
                check-then-act still break).
SW804 (error)   publish-before-init: ``self`` handed to a thread /
                queue inside ``__init__`` (``.start()``, ``.put(self)``)
                with attributes still assigned afterwards — the new
                thread can observe a half-built object.

Lifecycle methods (``__init__``, ``close``, ``stop``, ``join``, ...)
are happens-before windows: their writes never count toward the
>=2-roles test (see threads.steady_roles). Deliberate designs get an
inline pragma with justification; everything else is a bug.
"""

from __future__ import annotations

from .findings import Finding
from .threads import Access, ThreadModel, build_thread_model, steady_roles

#: Attributes that are synchronization primitives or documented
#: single-writer fields; assigning a Lock/Event is how you make
#: things safe, not a race.
_LOCKY_ATTR = ("lock", "cond", "event", "sem")


def _locky(attr: str) -> bool:
    low = attr.lower()
    return any(t in low for t in _LOCKY_ATTR)


def _qual(func_key: str) -> str:
    return func_key


def _site(acc: Access) -> str:
    return f"{acc.path}:{acc.line}"


def _roles_str(roles) -> str:
    return "{" + ", ".join(sorted(roles)) + "}"


def _is_shared(model: ThreadModel, writes: list[Access]) -> tuple:
    """(shared?, union of steady roles) for one (owner, attr) group.

    Shared means: steady-state writes from >=2 distinct roles, or from
    one role that is multi-instance (two threads of the same role race
    each other just fine).
    """
    union: set = set()
    for a in writes:
        union |= steady_roles(model, a)
    multi = union & model.multi_roles
    return (len(union) >= 2 or bool(multi)), union


def check_races(fp) -> list[Finding]:
    model = build_thread_model(fp)
    return rules_over_model(model)


def rules_over_model(model: ThreadModel) -> list[Finding]:
    out: list[Finding] = []

    # group steady-state accesses per (owner, attr)
    groups: dict[tuple, list[Access]] = {}
    for a in model.accesses:
        groups.setdefault((a.owner, a.attr), []).append(a)

    sw801_attrs: set[tuple] = set()

    for (owner, attr), accs in sorted(groups.items()):
        if _locky(attr):
            continue
        writes = [a for a in accs if a.kind in ("write", "rmw")
                  and steady_roles(model, a)]
        mutates = [a for a in accs if a.kind == "mutate"
                   and steady_roles(model, a)]

        # ---- SW801: cross-role writes, empty lockset intersection ----
        if writes:
            shared, union = _is_shared(model, writes)
            if shared:
                common = None
                for a in writes:
                    eff = model.effective_lockset(a)
                    common = eff if common is None else (common & eff)
                if not common:
                    first = min(writes, key=lambda a: (a.path, a.line))
                    others = sorted(
                        {_site(a) for a in writes} - {_site(first)})
                    sites = ", ".join(others[:4])
                    more = "" if len(others) <= 4 else \
                        f" (+{len(others) - 4} more)"
                    out.append(Finding(
                        "SW801", "error", first.path, first.line,
                        _qual(first.func),
                        f"attribute '{attr}' of {owner} is written from "
                        f"thread roles {_roles_str(union)} with no "
                        f"common lock; other write sites: "
                        f"{sites or 'same line'}{more}",
                        extra={"anchors": sorted(
                            {a.line for a in writes
                             if a.path == first.path})}))
                    sw801_attrs.add((owner, attr))

        # ---- SW802: unguarded compound RMW on a shared attribute ----
        if (owner, attr) not in sw801_attrs:
            owner_roles = model.owner_roles(owner)
            shared_owner = len(owner_roles) >= 2 or \
                bool(owner_roles & model.multi_roles)
            if shared_owner:
                for a in writes:
                    if a.kind != "rmw" and not a.compound:
                        continue
                    if model.effective_lockset(a):
                        continue
                    what = "check-then-set" if a.compound else \
                        "read-modify-write"
                    out.append(Finding(
                        "SW802", "warning", a.path, a.line,
                        _qual(a.func),
                        f"compound {what} on shared attribute "
                        f"'{attr}' of {owner} outside any lock "
                        f"(reachable roles "
                        f"{_roles_str(steady_roles(model, a))}); "
                        f"two threads interleave between the read "
                        f"and the write"))

        # ---- SW803: unguarded container mutation on shared owner ----
        if mutates and (owner, attr) in model.containers:
            owner_roles = model.owner_roles(owner)
            shared_owner = len(owner_roles) >= 2 or \
                bool(owner_roles & model.multi_roles)
            if shared_owner:
                bad = [a for a in mutates
                       if not model.effective_lockset(a)]
                # one finding per attr, anchored at the first bad site
                if bad and len(
                        {r for a in mutates
                         for r in steady_roles(model, a)}) >= 1:
                    roles_here = set()
                    for a in bad:
                        roles_here |= steady_roles(model, a)
                    if len(roles_here) >= 2 or \
                            roles_here & model.multi_roles or \
                            len(owner_roles) >= 2:
                        first = min(bad, key=lambda a: (a.path, a.line))
                        kind = model.containers[(owner, attr)]
                        sites = sorted({_site(a) for a in bad})
                        out.append(Finding(
                            "SW803", "warning", first.path, first.line,
                            _qual(first.func),
                            f"unguarded {kind} mutation "
                            f"({first.detail}) on shared collection "
                            f"'{attr}' of {owner} (owner reachable "
                            f"from roles {_roles_str(owner_roles)}; "
                            f"{len(sites)} unguarded site(s))",
                            extra={"anchors": sorted(
                                {a.line for a in bad
                                 if a.path == first.path})}))

    # ---- SW804: publish-before-init ----
    for init_key, (pub_line, desc) in sorted(model.publishes.items()):
        late = [a for a in model.accesses
                if a.func == init_key and a.in_init
                and a.kind in ("write", "rmw")
                and a.line > pub_line]
        if not late:
            continue
        first = min(late, key=lambda a: a.line)
        attrs = ", ".join(sorted({a.attr for a in late})[:5])
        out.append(Finding(
            "SW804", "error", first.path, pub_line, _qual(init_key),
            f"object published before construction completes: "
            f"{desc} at line {pub_line}, but attribute(s) {attrs} "
            f"assigned after (first at line {first.line}); the "
            f"spawned thread can observe a half-built object",
            extra={"anchors": sorted({a.line for a in late})}))

    return out
