"""Per-file rules: resource hygiene, swallowed exceptions, metrics.

These need no cross-module resolution, but they do reuse the parsed
tree held by ModuleInfo so each file is parsed once.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .findings import Finding
from .model import ModuleInfo

#: function names whose silent except handlers are errors, not
#: warnings: background loops where a swallowed exception means a
#: silently dead server thread (heartbeat/reaper/pusher/sync...).
_LOOPY_FN_RE = re.compile(
    r"(heartbeat|_loop|^_?run\b|serve|reap|worker|daemon|push|watch"
    r"|sync|vacuum)", re.IGNORECASE)

_LOG_CALL_RE = re.compile(
    r"(glog|logging|logger|log)\.(v|info|warning|error|exception|debug"
    r"|critical)$")


# ---------------------------------------------------------------------------
# SW201 / SW202 — resource hygiene
# ---------------------------------------------------------------------------

def _opener(node: ast.Call, mi: ModuleInfo) -> Optional[str]:
    """Classify a call that creates a closeable resource."""
    fn = node.func
    if isinstance(fn, ast.Name):
        if fn.id == "open":
            return "file handle"
        if fn.id == "socket":
            tgt = mi.from_imports.get("socket")
            if tgt and tgt[0] == "socket":
                return "socket"
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        mod = mi.imports.get(fn.value.id, "")
        if mod == "socket" and fn.attr in ("socket",
                                           "create_connection"):
            return "socket"
        if mod == "grpc" and fn.attr in ("insecure_channel",
                                         "secure_channel"):
            return "gRPC channel"
        if fn.attr == "dial":  # util/tls.py dial() -> grpc channel
            return "gRPC channel"
    return None


def _is_span_call(node: ast.Call, mi: ModuleInfo) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        mod = mi.imports.get(fn.value.id, "")
        return fn.attr in ("span", "start_trace") and \
            mod.endswith("tracing")
    if isinstance(fn, ast.Name):
        tgt = mi.from_imports.get(fn.id)
        return tgt is not None and tgt[1] in ("span", "start_trace") \
            and tgt[0].endswith("tracing")
    return False


_SCOPE_BOUNDARY = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                   ast.Lambda)


def _walk_scope(root: ast.AST):
    """ast.walk that does NOT descend into nested function/class
    scopes — their bodies run under their own locks and lifetimes."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _SCOPE_BOUNDARY):
            stack.extend(ast.iter_child_nodes(node))


class _FuncResourceCheck(ast.NodeVisitor):
    """Within one function body: resources opened vs. closed."""

    def __init__(self, mi: ModuleInfo, qualname: str,
                 findings: list[Finding]):
        self.mi = mi
        self.qualname = qualname
        self.findings = findings
        #: var -> (kind, line) for resources assigned to a local name
        self.opened: dict[str, tuple[str, int]] = {}
        self.closed: dict[str, list[int]] = {}      # var -> close lines
        self.escaped: set[str] = set()              # ownership left fn
        self.finally_ranges: list[tuple[int, int]] = []
        self.with_lines: set[int] = set()

    # nested scopes manage their own resources — do not descend
    def visit_FunctionDef(self, node) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # -- collection --

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            self.with_lines.add(item.context_expr.lineno)
            if isinstance(item.context_expr, ast.Name):
                self.escaped.add(item.context_expr.id)
        self.generic_visit(node)

    visit_AsyncWith = visit_With

    def visit_Try(self, node: ast.Try) -> None:
        if node.finalbody:
            first = node.finalbody[0].lineno
            last = max(getattr(s, "end_lineno", s.lineno)
                       for s in node.finalbody)
            self.finally_ranges.append((first, last))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call) and \
                len(node.targets) == 1:
            kind = _opener(node.value, self.mi)
            t = node.targets[0]
            if kind and isinstance(t, ast.Name):
                self.opened[t.id] = (kind, node.lineno)
        # storing an opened resource anywhere (self.f = x,
        # registry[k] = x, g = x) transfers ownership out of this scope
        if isinstance(node.value, ast.Name) and \
                node.value.id in self.opened:
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript,
                                  ast.Name)):
                    self.escaped.add(node.value.id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.attr in ("close", "shutdown", "stop", "release"):
            self.closed.setdefault(fn.value.id, []).append(node.lineno)
        # a resource passed to another call transfers ownership
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in self.opened:
                self.escaped.add(arg.id)
        # immediate-use leak: open(p).read() — nothing ever closes it
        kind = _opener(node, self.mi)
        if kind and isinstance(getattr(node, "_parent", None),
                               ast.Attribute):
            self.findings.append(Finding(
                "SW201", "error", self.mi.path, node.lineno,
                self.qualname,
                f"{kind} opened and used inline is never closed "
                f"(use a with block)"))
        self.generic_visit(node)

    def _escape_expr(self, value) -> None:
        for n in ast.walk(value) if value is not None else ():
            if isinstance(n, ast.Name) and n.id in self.opened:
                self.escaped.add(n.id)

    def visit_Return(self, node: ast.Return) -> None:
        self._escape_expr(node.value)
        self.generic_visit(node)

    def visit_Yield(self, node: ast.Yield) -> None:
        self._escape_expr(node.value)
        self.generic_visit(node)

    # -- verdicts --

    def finish(self) -> None:
        for var, (kind, line) in self.opened.items():
            if var in self.escaped:
                continue
            closes = self.closed.get(var, [])
            if not closes:
                self.findings.append(Finding(
                    "SW201", "error", self.mi.path, line, self.qualname,
                    f"{kind} '{var}' is never closed in this function "
                    f"(and never escapes it)"))
            elif not any(lo <= ln <= hi for ln in closes
                         for lo, hi in self.finally_ranges):
                self.findings.append(Finding(
                    "SW201", "warning", self.mi.path, line,
                    self.qualname,
                    f"{kind} '{var}' is closed, but not on the "
                    f"exception path (use with/finally)"))


def check_resources(mi: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for scope, qual in _function_scopes(mi):
        chk = _FuncResourceCheck(mi, qual, findings)
        _annotate_parents(scope)
        for stmt in scope.body:
            chk.visit(stmt)
        chk.finish()
        # SW202: span handles created outside a with / decorator
        for node in _walk_scope(scope):
            if isinstance(node, ast.Call) and _is_span_call(node, mi):
                parent = getattr(node, "_parent", None)
                if isinstance(parent, (ast.withitem, ast.Return)):
                    continue
                if isinstance(parent, ast.Call):  # start_trace(...) arg
                    continue
                findings.append(Finding(
                    "SW202", "warning", mi.path, node.lineno, qual,
                    "tracing span created outside a with-block; it "
                    "will never close (and never records)"))
    return findings


def _annotate_parents(root: ast.AST) -> None:
    for parent in ast.walk(root):
        for child in ast.iter_child_nodes(parent):
            child._parent = parent


def _function_scopes(mi: ModuleInfo):
    """Yield (function node, qualname) for every def, however nested."""
    def rec(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                yield child, f"{mi.name}:{prefix}{child.name}"
                yield from rec(child, f"{prefix}{child.name}.")
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, f"{prefix}{child.name}.")
            else:
                yield from rec(child, prefix)
    yield from rec(mi.tree, "")


# ---------------------------------------------------------------------------
# SW301 / SW302 — swallowed exceptions
# ---------------------------------------------------------------------------

def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue  # bare docstring/ellipsis
        return False
    return True


def _handler_logs_or_raises(handler: ast.ExceptHandler) -> bool:
    """True when the handler surfaces the exception somehow: raises,
    logs, or captures ``as e`` and actually uses the binding (the
    worker-thread idiom ``errors.append(e)`` re-raised elsewhere)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            try:
                text = ast.unparse(node.func)
            except Exception:  # pragma: no cover
                continue
            if _LOG_CALL_RE.search(text):
                return True
        if handler.name and isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and \
                node.id == handler.name:
            return True
    return False


def check_exceptions(mi: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for scope, qual in _function_scopes(mi):
        in_while: set[int] = set()
        for node in _walk_scope(scope):
            if isinstance(node, ast.While):
                for sub in ast.walk(node):
                    in_while.add(id(sub))
        for node in _walk_scope(scope):
            if not isinstance(node, ast.Try):
                continue
            for h in node.handlers:
                bare = h.type is None
                if bare and not _handler_logs_or_raises(h):
                    findings.append(Finding(
                        "SW302", "error", mi.path, h.lineno, qual,
                        "bare except swallows SystemExit/"
                        "KeyboardInterrupt; catch Exception (and log "
                        "or re-raise)"))
                    continue
                if _handler_is_silent(h):
                    if isinstance(h.type, ast.Name) and h.type.id in (
                            "KeyboardInterrupt", "GeneratorExit",
                            "StopIteration"):
                        continue  # silent pass on these is the idiom
                    fn_name = qual.split(":")[-1].rsplit(".", 1)[-1]
                    hot = bool(_LOOPY_FN_RE.search(fn_name)) or \
                        id(node) in in_while
                    findings.append(Finding(
                        "SW301", "error" if hot else "warning",
                        mi.path, h.lineno, qual,
                        "exception silently swallowed"
                        + (" inside a server/heartbeat loop — a dead "
                           "thread would leave no trace" if hot
                           else " — log it (glog.v is cheap) or "
                           "narrow the except")))
    return findings


# ---------------------------------------------------------------------------
# SW401 / SW402 — metrics label hygiene
# ---------------------------------------------------------------------------

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


def _unbounded_value(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.JoinedStr):
        return "f-string"
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        return "%-format"
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in ("str", "repr",
                                                  "format"):
            return f"{fn.id}()"
        if isinstance(fn, ast.Attribute) and fn.attr == "format":
            return ".format()"
    return None


def check_metrics(mi: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for scope, qual in _function_scopes(mi):
        for node in _walk_scope(scope):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_FACTORIES):
                continue
            recv = ""
            try:
                recv = ast.unparse(node.func.value).lower()
            except Exception:  # pragma: no cover
                pass
            if not ("metric" in recv or recv.endswith("stats")
                    or recv == "m" or recv.endswith("registry")):
                continue
            if node.args and not isinstance(node.args[0], ast.Constant):
                findings.append(Finding(
                    "SW402", "info", mi.path, node.lineno, qual,
                    "dynamic metric name — ensure the set of names is "
                    "bounded"))
            for kw in node.keywords:
                if kw.arg is None:
                    continue  # **labels: opaque here
                how = _unbounded_value(kw.value)
                if how:
                    findings.append(Finding(
                        "SW401", "error", mi.path, kw.value.lineno,
                        qual,
                        f"label {kw.arg}={how} builds an unbounded "
                        f"label set; Prometheus series never expire — "
                        f"use a fixed vocabulary"))
                elif isinstance(kw.value, (ast.Name, ast.Attribute)):
                    findings.append(Finding(
                        "SW402", "info", mi.path, kw.value.lineno,
                        qual,
                        f"label {kw.arg} from a variable — confirm its "
                        f"value set is bounded"))
    return findings


def check_local(mi: ModuleInfo) -> list[Finding]:
    return (check_resources(mi) + check_exceptions(mi)
            + check_metrics(mi))
