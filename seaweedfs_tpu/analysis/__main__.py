"""seaweedlint CLI.

    python -m seaweedfs_tpu.analysis [paths...]
        [--baseline FILE | --no-baseline] [--write-baseline]
        [--prune-baseline] [--fail-stale]
        [--gate error|warning|none] [--format human|json|sarif]
        [--stats] [--families] [--no-cache]
        [--budget-seconds S] [--verbose]

Exit codes: 0 clean (or all findings baselined), 1 new findings at or
above the gate severity (or stale baseline under --fail-stale, or
runtime over --budget-seconds), 2 bad invocation.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from . import cache as result_cache
from .baseline import (diff_baseline, load_baseline, prune_baseline,
                       write_baseline)
from .engine import analyze_paths
from .findings import SEVERITIES, Finding, to_sarif

_REPO_ROOT = Path(__file__).resolve().parents[2]
_DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def _summarize(findings: list[Finding]) -> str:
    by = {s: 0 for s in SEVERITIES}
    for f in findings:
        by[f.severity] += 1
    return (f"{len(findings)} finding(s): {by['error']} error, "
            f"{by['warning']} warning, {by['info']} info")


def _print_stats(timings: dict[str, float], total: float) -> None:
    print("seaweedlint --stats: per-rule-family wall time")
    width = max(len(k) for k in timings) if timings else 10
    for label, secs in sorted(timings.items(), key=lambda kv: -kv[1]):
        share = 100.0 * secs / total if total else 0.0
        print(f"  {label:<{width}}  {secs:7.3f}s  {share:5.1f}%")
    print(f"  {'total':<{width}}  {total:7.3f}s")


def _family(rule: str) -> str:
    # "SW103" -> "SW1xx"; anything oddly shaped keeps its own row
    return rule[:3] + "xx" if len(rule) == 5 and \
        rule.startswith("SW") else rule


def _family_table(findings: list[Finding], new: list[Finding],
                  suppressed: list[Finding]) -> list[str]:
    """Per-rule-family triage table: how many findings are NEW (would
    gate), how many ride in the baseline, how many an inline pragma
    deliberately silenced, plus ungated info chatter."""
    new_ids = {id(f) for f in new}
    fams: dict[str, list[int]] = {}

    def row(rule):
        return fams.setdefault(_family(rule), [0, 0, 0, 0])

    for f in findings:
        if f.severity == "info":
            row(f.rule)[3] += 1
        elif id(f) in new_ids:
            row(f.rule)[0] += 1
        else:
            row(f.rule)[1] += 1
    for f in suppressed:
        row(f.rule)[2] += 1
    lines = ["seaweedlint --families: findings by rule family",
             f"  {'family':<8}{'new':>6}{'baselined':>11}"
             f"{'pragma-d':>10}{'info':>6}"]
    total = [0, 0, 0, 0]
    for fam in sorted(fams):
        n, b, p, i = fams[fam]
        total = [total[0] + n, total[1] + b,
                 total[2] + p, total[3] + i]
        lines.append(f"  {fam:<8}{n:>6}{b:>11}{p:>10}{i:>6}")
    lines.append(f"  {'total':<8}{total[0]:>6}{total[1]:>11}"
                 f"{total[2]:>10}{total[3]:>6}")
    return lines


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="seaweedlint",
        description="project-native concurrency & resource analyzer")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to analyze "
                         "(default: seaweedfs_tpu)")
    ap.add_argument("--baseline", type=Path, default=None,
                    help=f"baseline file (default {_DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(preserves justifications)")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline entries whose fingerprints no "
                         "longer match any finding, keep the rest")
    ap.add_argument("--fail-stale", action="store_true",
                    help="exit non-zero when stale baseline entries "
                         "remain (CI mode)")
    ap.add_argument("--gate", choices=["error", "warning", "none"],
                    default="warning",
                    help="fail on new findings at/above this severity "
                         "(default: warning)")
    ap.add_argument("--format", choices=["human", "json", "sarif"],
                    default=None, dest="fmt",
                    help="output format (default: human)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="alias for --format=json")
    ap.add_argument("--stats", action="store_true",
                    help="print per-rule-family wall time and cache "
                         "hit/miss counts")
    ap.add_argument("--families", action="store_true",
                    help="print a per-rule-family triage table "
                         "(new vs baselined vs pragma'd)")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not write the incremental "
                         "result cache (.seaweedlint_cache.json)")
    ap.add_argument("--budget-seconds", type=float, default=0.0,
                    help="fail if the analysis run exceeds this many "
                         "seconds (0 = no budget)")
    ap.add_argument("--verbose", action="store_true",
                    help="also print info-level findings")
    args = ap.parse_args(argv)

    fmt = args.fmt or ("json" if args.as_json else "human")
    root = _REPO_ROOT
    paths = args.paths or ["seaweedfs_tpu"]
    timings: dict[str, float] = {}
    suppressed: list[Finding] = []
    t0 = time.perf_counter()
    # Incremental cache: reuse the previous run's findings when no
    # analyzed file (and no rule module) changed — see cache.py for
    # why reuse is all-or-nothing. The probe itself is just stats.
    findings = None
    cache_hits = cache_misses = 0
    cache_state = "disabled"
    cache_path = root / result_cache.DEFAULT_CACHE
    if not args.no_cache:
        version = result_cache.rules_version()
        keys = result_cache.file_keys(paths, root)
        entry, cache_hits, cache_misses = result_cache.load(
            cache_path, version, keys)
        if entry is not None:
            findings, suppressed = entry
            cache_state = "hit"
        else:
            cache_state = "miss"
    if findings is None:
        findings = analyze_paths(paths, root, timings,
                                 suppressed_out=suppressed)
        if not args.no_cache:
            result_cache.store(cache_path, version, keys,
                               findings, suppressed)
    elapsed = time.perf_counter() - t0

    baseline_path = args.baseline or _DEFAULT_BASELINE
    if args.write_baseline:
        prev = load_baseline(baseline_path)
        gated = [f for f in findings if f.severity != "info"]
        write_baseline(baseline_path, gated, prev)
        print(f"wrote {len(gated)} finding(s) to {baseline_path}")
        return 0
    if args.prune_baseline:
        pruned = prune_baseline(
            baseline_path, [f for f in findings
                            if f.severity != "info"])
        print(f"pruned {len(pruned)} stale entr"
              f"{'y' if len(pruned) == 1 else 'ies'} from "
              f"{baseline_path}")
        for e in pruned:
            print(f"  - {e['rule']} {e['path']}:{e.get('line', '?')} "
                  f"{e['fingerprint']}")
        return 0

    if args.no_baseline:
        new, stale = findings, []
    else:
        baseline = load_baseline(baseline_path)
        new, stale = diff_baseline(
            [f for f in findings if f.severity != "info"], baseline)
        new = new + [f for f in findings if f.severity == "info"]

    gate_rank = {"none": len(SEVERITIES), "warning": 1, "error": 2}
    threshold = gate_rank[args.gate]
    gating = [f for f in new
              if SEVERITIES.index(f.severity) >= threshold]
    shown = [f for f in new
             if args.verbose or f.severity != "info"]

    over_budget = args.budget_seconds > 0 and \
        elapsed > args.budget_seconds
    stale_fail = args.fail_stale and bool(stale)

    if fmt == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in shown],
            "gating": len(gating),
            "stale_baseline": stale,
            "summary": _summarize(findings),
            "elapsed_seconds": round(elapsed, 3),
        }, indent=1))
    elif fmt == "sarif":
        print(json.dumps(to_sarif(shown), indent=1))
    else:
        for f in shown:
            print(f.format())
        if stale:
            print(f"note: {len(stale)} baseline entr"
                  f"{'y is' if len(stale) == 1 else 'ies are'} stale "
                  f"(fixed) — run --prune-baseline to drop them")
        print(f"seaweedlint: {_summarize(findings)}; "
              f"{len(gating)} new at gate severity "
              f"'{args.gate}'")
    if args.families and fmt == "human":
        for line in _family_table(findings, new, suppressed):
            print(line)
    if args.stats:
        _print_stats(timings, elapsed)
        print(f"  cache: {cache_state} ({cache_hits} file(s) "
              f"unchanged, {cache_misses} changed/new/removed)")
    if over_budget:
        print(f"seaweedlint: runtime budget exceeded: {elapsed:.1f}s "
              f"> {args.budget_seconds:.1f}s", file=sys.stderr)
    if stale_fail:
        print(f"seaweedlint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (--fail-stale); "
              f"run scripts/seaweedlint --prune-baseline",
              file=sys.stderr)
    return 1 if (gating or over_budget or stale_fail) else 0


if __name__ == "__main__":
    sys.exit(main())
