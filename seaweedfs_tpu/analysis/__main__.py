"""seaweedlint CLI.

    python -m seaweedfs_tpu.analysis [paths...]
        [--baseline FILE | --no-baseline] [--write-baseline]
        [--gate error|warning|none] [--json] [--verbose]

Exit codes: 0 clean (or all findings baselined), 1 new findings at or
above the gate severity, 2 bad invocation.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .baseline import diff_baseline, load_baseline, write_baseline
from .engine import analyze_paths
from .findings import SEVERITIES, Finding

_REPO_ROOT = Path(__file__).resolve().parents[2]
_DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def _summarize(findings: list[Finding]) -> str:
    by = {s: 0 for s in SEVERITIES}
    for f in findings:
        by[f.severity] += 1
    return (f"{len(findings)} finding(s): {by['error']} error, "
            f"{by['warning']} warning, {by['info']} info")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="seaweedlint",
        description="project-native concurrency & resource analyzer")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to analyze "
                         "(default: seaweedfs_tpu)")
    ap.add_argument("--baseline", type=Path, default=None,
                    help=f"baseline file (default {_DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(preserves justifications)")
    ap.add_argument("--gate", choices=["error", "warning", "none"],
                    default="warning",
                    help="fail on new findings at/above this severity "
                         "(default: warning)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--verbose", action="store_true",
                    help="also print info-level findings")
    args = ap.parse_args(argv)

    root = _REPO_ROOT
    paths = args.paths or ["seaweedfs_tpu"]
    findings = analyze_paths(paths, root)

    baseline_path = args.baseline or _DEFAULT_BASELINE
    if args.write_baseline:
        prev = load_baseline(baseline_path)
        gated = [f for f in findings if f.severity != "info"]
        write_baseline(baseline_path, gated, prev)
        print(f"wrote {len(gated)} finding(s) to {baseline_path}")
        return 0

    if args.no_baseline:
        new, stale = findings, []
    else:
        baseline = load_baseline(baseline_path)
        new, stale = diff_baseline(
            [f for f in findings if f.severity != "info"], baseline)
        new = new + [f for f in findings if f.severity == "info"]

    gate_rank = {"none": len(SEVERITIES), "warning": 1, "error": 2}
    threshold = gate_rank[args.gate]
    gating = [f for f in new
              if SEVERITIES.index(f.severity) >= threshold]
    shown = [f for f in new
             if args.verbose or f.severity != "info"]

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in shown],
            "gating": len(gating),
            "stale_baseline": stale,
            "summary": _summarize(findings),
        }, indent=1))
    else:
        for f in shown:
            print(f.format())
        if stale:
            print(f"note: {len(stale)} baseline entr"
                  f"{'y is' if len(stale) == 1 else 'ies are'} stale "
                  f"(fixed) — run --write-baseline to prune")
        print(f"seaweedlint: {_summarize(findings)}; "
              f"{len(gating)} new at gate severity "
              f"'{args.gate}'")
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
