"""Per-module AST model: locks, calls, blocking ops, attribute types.

One ``ModuleCollector`` pass per file produces a ``ModuleInfo``; the
cross-module lock graph (lockgraph.py) and the local rules
(local_rules.py) both consume it, so every file is parsed exactly once.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Optional

#: Attribute / variable names that denote a lock even without a visible
#: ``threading.Lock()`` assignment (inherited, dict-of-locks, ...).
_LOCKY_RE = re.compile(r"(^|_)(r?lock|locks|mu|mutex|cond)(_|s$|$|\[)",
                       re.IGNORECASE)

_SOCKET_BLOCKING_ATTRS = {"connect", "connect_ex", "accept", "recv",
                          "recvfrom", "recv_into", "sendall", "sendto",
                          "makefile", "getresponse"}
_SUBPROCESS_FNS = {"run", "Popen", "call", "check_call", "check_output"}
_OS_FILE_FNS = {"fsync", "replace", "rename", "truncate"}
_CAMEL_RE = re.compile(r"^[A-Z][A-Za-z0-9]*$")


def looks_locky(name: str) -> bool:
    return bool(_LOCKY_RE.search(name))


@dataclass
class LockDef:
    lock_id: str        # "mod.Class.attr" / "mod.name"
    kind: str           # "Lock" | "RLock" | "Condition" | "unknown"
    line: int
    alias_of: Optional[str] = None   # Condition(self._lock) -> that lock


@dataclass
class FuncInfo:
    key: str            # "mod:Class.meth" / "mod:func"
    module: str
    line: int
    name: str
    #: lock_id -> first with-statement line acquiring it in this body
    acquires: dict[str, int] = field(default_factory=dict)
    #: direct nesting: (outer_id, inner_id, line of inner with)
    nest_edges: list[tuple[str, str, int]] = field(default_factory=list)
    #: (ref, line, held lock ids at the call, with_lines of held locks)
    calls: list[tuple[tuple, int, tuple[str, ...], tuple[int, ...]]] = \
        field(default_factory=list)
    #: (category, description, line, held ids, with_lines of held locks)
    blocking: list[tuple[str, str, int, tuple[str, ...],
                         tuple[int, ...]]] = field(default_factory=list)


@dataclass
class ClassInfo:
    name: str
    line: int
    lock_defs: dict[str, LockDef] = field(default_factory=dict)  # by attr
    #: self.attr = SomeProjectClass(...)  ->  "mod:SomeProjectClass"
    attr_types: dict[str, str] = field(default_factory=dict)
    methods: dict[str, FuncInfo] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    name: str                     # dotted module name
    path: str                     # repo-relative path
    tree: ast.Module
    #: import alias -> dotted module ("np" -> "numpy")
    imports: dict[str, str] = field(default_factory=dict)
    #: from-imported name -> (module, original name)
    from_imports: dict[str, tuple[str, str]] = field(default_factory=dict)
    module_locks: dict[str, LockDef] = field(default_factory=dict)
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    functions: dict[str, FuncInfo] = field(default_factory=dict)


def _resolve_relative(module: str, target: Optional[str],
                      level: int) -> str:
    """Resolve ``from ..util import x`` against ``module``'s package."""
    if level == 0:
        return target or ""
    parts = module.split(".")
    # the module itself is not a package; level 1 = its own package
    base = parts[:-level] if level <= len(parts) else []
    if target:
        base = base + target.split(".")
    return ".".join(base)


def _lock_ctor(node: ast.expr,
               mi: ModuleInfo) -> Optional[tuple[str, ast.Call]]:
    """'threading.Lock()' / 'Lock()' -> ("Lock", call node)."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute) and \
            isinstance(fn.value, ast.Name) and \
            mi.imports.get(fn.value.id, fn.value.id) == "threading":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        tgt = mi.from_imports.get(fn.id)
        if tgt and tgt[0] == "threading":
            name = tgt[1]
    if name in ("Lock", "RLock", "Condition"):
        return name, node
    return None


class ModuleCollector(ast.NodeVisitor):
    """Single-pass collector for one module."""

    def __init__(self, name: str, path: str, tree: ast.Module):
        self.mi = ModuleInfo(name=name, path=path, tree=tree)
        self._class: Optional[ClassInfo] = None
        self._func: Optional[FuncInfo] = None
        #: (lock_id, with_line) stack while visiting a function body
        self._held: list[tuple[str, int]] = []

    def collect(self) -> ModuleInfo:
        self.visit(self.mi.tree)
        return self.mi

    # ---- imports ----

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.mi.imports[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = _resolve_relative(self.mi.name, node.module,
                                node.level or 0)
        for a in node.names:
            if a.name == "*":
                continue
            self.mi.from_imports[a.asname or a.name] = (mod, a.name)
            # "from ..util import tracing" imports a MODULE; record it
            # in imports too so "tracing.span" resolves.
            self.mi.imports.setdefault(a.asname or a.name,
                                       f"{mod}.{a.name}" if mod
                                       else a.name)

    # ---- scopes ----

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self._class = self._class, ClassInfo(node.name, node.lineno)
        self.mi.classes[node.name] = self._class
        self.generic_visit(node)
        self._class = prev

    def _visit_func(self, node) -> None:
        ci = self._class
        key = (f"{self.mi.name}:{ci.name}.{node.name}" if ci
               else f"{self.mi.name}:{node.name}")
        prev_f, prev_h = self._func, self._held
        self._func = FuncInfo(key=key, module=self.mi.name,
                              line=node.lineno, name=node.name)
        self._held = []
        if ci and node.name not in ci.methods:
            ci.methods[node.name] = self._func
        elif not ci and node.name not in self.mi.functions:
            self.mi.functions[node.name] = self._func
        self.generic_visit(node)
        self._func, self._held = prev_f, prev_h

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # ---- lock definitions ----

    def _register_lock(self, target: ast.expr, value: ast.expr,
                       line: int) -> None:
        ctor = _lock_ctor(value, self.mi)
        if ctor is None:
            # self.attr = ProjectClass(...) -> attribute type
            if (self._class is not None
                    and isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                    and isinstance(value, ast.Call)):
                cls_key = self._resolve_class(value.func)
                if cls_key:
                    self._class.attr_types[target.attr] = cls_key
            return
        kind, call = ctor
        alias = None
        if kind == "Condition" and call.args:
            alias = self._lock_ref(call.args[0])
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self" and self._class is not None:
            lid = f"{self.mi.name}.{self._class.name}.{target.attr}"
            self._class.lock_defs[target.attr] = LockDef(
                lid, kind, line, alias)
        elif isinstance(target, ast.Name) and self._func is None:
            lid = f"{self.mi.name}.{target.id}"
            self.mi.module_locks[target.id] = LockDef(lid, kind, line,
                                                      alias)

    def _resolve_class(self, fn: ast.expr) -> Optional[str]:
        """Map a constructor callee to 'module:Class' if it names a
        class imported from (or defined in) this project."""
        if isinstance(fn, ast.Name):
            if fn.id in self.mi.classes:
                return f"{self.mi.name}:{fn.id}"
            tgt = self.mi.from_imports.get(fn.id)
            if tgt and tgt[1][:1].isupper():
                return f"{tgt[0]}:{tgt[1]}"
        elif isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name):
            mod = self.mi.imports.get(fn.value.id)
            if mod and fn.attr[:1].isupper():
                return f"{mod}:{fn.attr}"
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._register_lock(t, node.value, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._register_lock(node.target, node.value, node.lineno)
        self.generic_visit(node)

    # ---- lock references / acquisition ----

    def _lock_ref(self, expr: ast.expr) -> Optional[str]:
        return resolve_lock_ref(
            expr, self.mi, self._class,
            self._func.key if self._func else None)

    def lock_kind(self, lock_id: str) -> str:
        for defs in (self.mi.module_locks,
                     *(c.lock_defs for c in self.mi.classes.values())):
            for d in defs.values():
                if d.lock_id == lock_id:
                    return d.kind
        return "unknown"

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            lid = self._lock_ref(item.context_expr)
            if lid is None:
                continue
            f = self._func
            if f is not None:
                f.acquires.setdefault(lid, node.lineno)
                held_ids = [h for h, _ in self._held] + \
                    [a for a, _ in acquired]
                for outer in dict.fromkeys(held_ids):
                    if outer != lid or self.lock_kind(lid) == "Lock":
                        f.nest_edges.append((outer, lid, node.lineno))
            acquired.append((lid, node.lineno))
        self._held.extend(acquired)
        self.generic_visit(node)
        del self._held[len(self._held) - len(acquired):]

    visit_AsyncWith = visit_With

    # ---- calls: blocking classification + call graph refs ----

    def _callee_text(self, fn: ast.expr) -> str:
        try:
            return ast.unparse(fn)
        except Exception:  # pragma: no cover — unparse is total on exprs
            return ""

    def _blocking_category(self, node: ast.Call) -> Optional[tuple[str,
                                                                   str]]:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv = self._callee_text(fn.value)
            root = recv.split(".")[0].split("(")[0]
            root_mod = self.mi.imports.get(root, "")
            if fn.attr == "sleep" and (root_mod == "time"
                                       or root == "time"):
                return "sleep", f"{recv}.sleep()"
            if fn.attr in _SOCKET_BLOCKING_ATTRS and "sock" in recv.lower():
                return "socket", f"{recv}.{fn.attr}()"
            if fn.attr == "urlopen" or root_mod.startswith("urllib"):
                return "network", f"{recv}.{fn.attr}()"
            if root_mod == "requests":
                return "network", f"requests.{fn.attr}()"
            if root_mod == "subprocess" and fn.attr in _SUBPROCESS_FNS:
                return "subprocess", f"subprocess.{fn.attr}()"
            if root_mod == "os" and fn.attr in _OS_FILE_FNS:
                return "file", f"os.{fn.attr}()"
            if _CAMEL_RE.match(fn.attr) and "stub" in recv.lower():
                return "rpc", f"{recv}.{fn.attr}()"
        elif isinstance(fn, ast.Name):
            if fn.id == "open":
                return "file", "open()"
            if fn.id == "sleep" and \
                    self.mi.from_imports.get("sleep", ("", ""))[0] == \
                    "time":
                return "sleep", "sleep()"
            if fn.id == "urlopen":
                return "network", "urlopen()"
        return None

    def _call_ref(self, fn: ast.expr) -> Optional[tuple]:
        return call_ref(fn, self.mi)

    def visit_Call(self, node: ast.Call) -> None:
        f = self._func
        if f is not None:
            held = tuple(dict.fromkeys(h for h, _ in self._held))
            wlines = tuple(ln for _, ln in self._held)
            cat = self._blocking_category(node)
            if cat is not None:
                f.blocking.append((cat[0], cat[1], node.lineno, held,
                                   wlines))
            ref = self._call_ref(node.func)
            if ref is not None:
                f.calls.append((ref, node.lineno, held, wlines))
        self.generic_visit(node)


def resolve_lock_ref(expr: ast.expr, mi: ModuleInfo,
                     cls: Optional[ClassInfo],
                     func_key: Optional[str]) -> Optional[str]:
    """Resolve a with-context expression to a lock id, or None.

    Shared by the collector above and the thread-role model
    (threads.py): both layers must agree on what lock a ``with``
    statement holds so the static SW8xx locksets line up with the
    SW1xx lock graph.
    """
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name):
        base, attr = expr.value.id, expr.attr
        if base == "self" and cls is not None:
            d = cls.lock_defs.get(attr)
            if d is not None:
                return d.alias_of or d.lock_id
            if looks_locky(attr):
                return f"{mi.name}.{cls.name}.{attr}"
            return None
        mod = mi.imports.get(base)
        if mod and looks_locky(attr):
            return f"{mod}.{attr}"
        if looks_locky(attr):  # other_obj._lock — name-scoped
            return f"{mi.name}.<{base}>.{attr}"
        return None
    if isinstance(expr, ast.Name):
        d = mi.module_locks.get(expr.id)
        if d is not None:
            return d.alias_of or d.lock_id
        tgt = mi.from_imports.get(expr.id)
        if tgt and looks_locky(expr.id):
            return f"{tgt[0]}.{tgt[1]}"
        if looks_locky(expr.id):
            scope = func_key or mi.name
            return f"{scope}.{expr.id}"
        return None
    if isinstance(expr, ast.Subscript):
        text = ast.unparse(expr.value)
        if looks_locky(text):
            scope = func_key or mi.name
            return f"{scope}.{text}[]"
    return None


def call_ref(fn: ast.expr, mi: ModuleInfo) -> Optional[tuple]:
    """Classify a call's callee expression into a resolvable reference.

    Shared by the lock graph and the value-flow engine (dataflow.py) so
    both layers agree on what a call site *is* before either resolves
    it against the project call graph.
    """
    if isinstance(fn, ast.Name):
        return ("name", fn.id)
    if isinstance(fn, ast.Attribute):
        v = fn.value
        if isinstance(v, ast.Name):
            if v.id == "self":
                return ("self", fn.attr)
            if v.id in mi.imports:
                return ("alias", v.id, fn.attr)
            return ("unique", fn.attr)
        if isinstance(v, ast.Attribute) and \
                isinstance(v.value, ast.Name) and v.value.id == "self":
            return ("selfattr", v.attr, fn.attr)
        return ("unique", fn.attr)
    return None


def collect_module(name: str, path: str, source: str) -> ModuleInfo:
    tree = ast.parse(source, filename=path)
    return ModuleCollector(name, path, tree).collect()
