"""Finding record + stable fingerprints + inline pragma parsing."""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field

#: Ranked severities; the gate fails on anything >= its threshold.
SEVERITIES = ("info", "warning", "error")

_PRAGMA_RE = re.compile(
    r"#\s*seaweedlint:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s*[—-]|$)")


@dataclass
class Finding:
    rule: str          # e.g. "SW103"
    severity: str      # "error" | "warning" | "info"
    path: str          # repo-relative posix path
    line: int          # 1-based
    qualname: str      # "module:Class.func" or "module:<module>"
    message: str
    fingerprint: str = ""
    extra: dict = field(default_factory=dict)

    def sort_key(self):
        return (-SEVERITIES.index(self.severity), self.path, self.line,
                self.rule)

    def format(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.severity}] {self.message}")

    def to_json(self) -> dict:
        return {"fingerprint": self.fingerprint, "rule": self.rule,
                "severity": self.severity, "path": self.path,
                "line": self.line, "qualname": self.qualname,
                "message": self.message}


def fingerprint_findings(findings: list[Finding],
                         sources: dict[str, str]) -> None:
    """Assign line-drift-stable fingerprints in place.

    Hash (rule, path, qualname, normalized source text of the flagged
    line) — NOT the line number, so inserting code above a finding does
    not churn the baseline. Identical lines in the same function get an
    occurrence index so two real violations never collapse into one
    baseline entry.
    """
    seen: dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        lines = sources.get(f.path, "").splitlines()
        src = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        base = (f.rule, f.path, f.qualname, src)
        n = seen.get(base, 0)
        seen[base] = n + 1
        raw = "|".join((*base[:3], src, str(n)))
        f.fingerprint = hashlib.sha1(raw.encode()).hexdigest()[:16]


_SARIF_LEVELS = {"error": "error", "warning": "warning", "info": "note"}

#: Per-rule SARIF metadata: short name, default severity, help text.
#: Rules not listed fall back to severity-derived metadata; the SW8xx
#: family is enumerated so editors render actionable guidance
#: (docs/static_analysis.md holds the full catalog).
RULE_META: dict[str, dict] = {
    "SW801": {
        "name": "UnlockedSharedAttributeWrite",
        "severity": "error",
        "help": (
            "Attribute is written from two or more thread roles with "
            "an empty guaranteed-lockset intersection: no single lock "
            "consistently protects it. Guard every write with one "
            "lock, confine the attribute to a single thread, or "
            "pragma a deliberate single-writer/atomic-rebind design "
            "with a justification."),
    },
    "SW802": {
        "name": "CompoundUpdateOutsideLock",
        "severity": "warning",
        "help": (
            "Read-modify-write (`x += 1`) or check-then-set on a "
            "shared attribute outside any lock: two threads can "
            "interleave between the read and the write and lose an "
            "update. Take the guarding lock around the whole "
            "compound step."),
    },
    "SW803": {
        "name": "UnguardedSharedCollectionMutation",
        "severity": "warning",
        "help": (
            "A dict/list/set reachable from multiple thread roles is "
            "mutated without a lock. Single CPython ops are "
            "GIL-atomic, but iteration, multi-step updates, and "
            "free-threaded builds are not — guard the collection or "
            "document the single-writer protocol."),
    },
    "SW804": {
        "name": "PublishBeforeInit",
        "severity": "error",
        "help": (
            "`self` escapes to another thread (Thread(target=...), "
            "registry, callback) before __init__ finishes assigning "
            "attributes; the spawned thread can observe a half-built "
            "object. Finish construction, then publish."),
    },
}


def to_sarif(findings: list[Finding], tool_version: str = "2") -> dict:
    """SARIF 2.1.0 document for CI/editor consumption
    (``seaweedlint --format=sarif``). Rules with :data:`RULE_META`
    entries (the SW8xx race family) are emitted even when they have
    no findings in this run, so consumers always see their help text
    and default severity."""
    rules: dict[str, dict] = {}

    def rule_obj(rule: str, severity: str) -> dict:
        meta = RULE_META.get(rule)
        if meta is None:
            return {"id": rule,
                    "defaultConfiguration": {
                        "level": _SARIF_LEVELS.get(severity, "note")}}
        return {
            "id": rule,
            "name": meta["name"],
            "shortDescription": {"text": meta["name"]},
            "help": {"text": meta["help"]},
            "helpUri": "docs/static_analysis.md",
            "defaultConfiguration": {
                "level": _SARIF_LEVELS.get(meta["severity"], "note")},
        }

    for rule in RULE_META:
        rules[rule] = rule_obj(rule, RULE_META[rule]["severity"])
    results = []
    for f in findings:
        rules.setdefault(f.rule, rule_obj(f.rule, f.severity))
        results.append({
            "ruleId": f.rule,
            "level": _SARIF_LEVELS.get(f.severity, "note"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line)},
                },
                "logicalLocations": [{"fullyQualifiedName": f.qualname}],
            }],
            "partialFingerprints": {
                "seaweedlint/v1": f.fingerprint},
        })
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "seaweedlint",
                "version": tool_version,
                "informationUri":
                    "docs/static_analysis.md",
                "rules": sorted(rules.values(),
                                key=lambda r: r["id"]),
            }},
            "results": results,
        }],
    }


def suppressed_rules(source_line: str) -> set[str]:
    """Rules disabled by an inline pragma on this source line.

    ``# seaweedlint: disable=SW103,SW201 — holding the cache lock over
    the disk tier is the design``  →  {"SW103", "SW201"}; ``disable=all``
    suppresses every rule on the line.
    """
    m = _PRAGMA_RE.search(source_line)
    if not m:
        return set()
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def is_suppressed(finding: Finding, sources: dict[str, str],
                  anchor_lines: tuple[int, ...] = ()) -> bool:
    """A pragma suppresses on the flagged line, the line above it, or
    any anchor line (e.g. the ``with <lock>:`` statement a blocking
    call was found under) and the line above that."""
    lines = sources.get(finding.path, "").splitlines()
    candidates = []
    for ln in (finding.line, *anchor_lines):
        candidates.extend((ln, ln - 1))
    for ln in candidates:
        if 0 < ln <= len(lines):
            rules = suppressed_rules(lines[ln - 1])
            if "all" in rules or finding.rule in rules:
                return True
    return False
