"""SW9xx: durability rules — rename commit points must be persisted.

SW901 (warning)  a raw ``os.replace``/``os.rename`` call whose
                 enclosing function neither fsyncs the source before
                 the rename nor fsyncs the destination's parent
                 directory after it. A rename is the classic commit
                 point (vacuum's ``.cpd``→``.dat`` swap, a downloaded
                 ``.part`` moving into place, a ``.tmp`` sidecar
                 install) and on most filesystems it is NOT durable by
                 itself: the source bytes can be lost (rename-before-
                 data) and the rename itself lives in the directory,
                 which needs its own fsync. ``util/durability.py``'s
                 :func:`durable_replace` is the sanctioned idiom —
                 fsync source, replace, fsync parent dir — and that
                 module is the rule's one exemption.

The crash-recovery tests (tests/test_crashfs.py) prove the failure
mode this rule guards against: under crashfs replay, an un-fsynced
rename can be reordered ahead of its data writes, publishing a name
whose bytes never arrived.
"""

from __future__ import annotations

import ast
from typing import Optional

from .findings import Finding
from .model import ModuleInfo

#: The module allowed to call os.replace raw — it IS the idiom.
_SANCTIONED = ("util/durability.py",)

#: Call names that persist file CONTENTS (legal "fsync the source
#: before renaming" evidence).
_SRC_SYNCERS = ("fsync", "durable_replace", "drain", "barrier", "sync")

#: Call names that persist the DIRECTORY entry after the rename.
_DIR_SYNCERS = ("fsync", "fsync_dir", "durable_replace")

_SCOPE = (ast.FunctionDef, ast.AsyncFunctionDef)


def _callee_name(node: ast.Call, mi: ModuleInfo) -> Optional[str]:
    """Resolved short name of the callee: ``os.fsync`` -> "fsync" only
    when ``os`` really is the os module; ``durability.durable_replace``
    / a from-imported ``durable_replace`` / a method ``f.sync()`` all
    reduce to their attribute name."""
    fn = node.func
    if isinstance(fn, ast.Name):
        tgt = mi.from_imports.get(fn.id)
        return tgt[1] if tgt else fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _is_os_rename(node: ast.Call, mi: ModuleInfo) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        if mi.imports.get(fn.value.id, "") == "os" and \
                fn.attr in ("replace", "rename"):
            return True
    if isinstance(fn, ast.Name):
        tgt = mi.from_imports.get(fn.id)
        return tgt is not None and tgt[0] == "os" and \
            tgt[1] in ("replace", "rename")
    return False


def _check_function(mi: ModuleInfo, fn: ast.AST, qual: str,
                    out: list[Finding]) -> None:
    renames: list[ast.Call] = []
    src_sync_lines: list[int] = []
    dir_sync_lines: list[int] = []
    # walk the function body without descending into nested defs —
    # a nested function's barrier runs on ITS schedule, not ours
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _SCOPE):
            continue
        stack.extend(ast.iter_child_nodes(node))
        if not isinstance(node, ast.Call):
            continue
        if _is_os_rename(node, mi):
            renames.append(node)
            continue
        name = _callee_name(node, mi)
        if name in _SRC_SYNCERS:
            src_sync_lines.append(node.lineno)
        if name in _DIR_SYNCERS:
            dir_sync_lines.append(node.lineno)

    for call in renames:
        missing = []
        if not any(ln <= call.lineno for ln in src_sync_lines):
            missing.append("fsync of the source before it")
        if not any(ln >= call.lineno for ln in dir_sync_lines):
            missing.append("fsync of the parent directory after it")
        if not missing:
            continue
        out.append(Finding(
            "SW901", "warning", mi.path, call.lineno, qual,
            f"rename commit point without {' or '.join(missing)} — "
            f"not durable across power loss; use "
            f"util/durability.durable_replace (or fsync_dir) so the "
            f"rename and the bytes it publishes both persist"))


def check_durability(modules: dict[str, ModuleInfo]) -> list[Finding]:
    out: list[Finding] = []
    for mi in modules.values():
        if mi.path.endswith(_SANCTIONED):
            continue
        # module-level statements count as one scope
        _check_function(
            mi, ast.Module(
                body=[n for n in mi.tree.body
                      if not isinstance(n, _SCOPE)], type_ignores=[]),
            f"{mi.name}:<module>", out)

        def _walk_defs(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _SCOPE):
                    q = f"{prefix}.{child.name}" if prefix \
                        else child.name
                    _check_function(mi, child, f"{mi.name}:{q}", out)
                    _walk_defs(child, q)
                elif isinstance(child, ast.ClassDef):
                    _walk_defs(child,
                               f"{prefix}.{child.name}" if prefix
                               else child.name)

        _walk_defs(mi.tree, "")
    return out
