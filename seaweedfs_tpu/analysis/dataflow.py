"""Interprocedural value-flow engine for seaweedlint.

Per-function abstract interpretation over the same project call graph
the lock analysis uses (model.call_ref + lockgraph.resolve_call):
every function body is walked with a taint environment mapping local
names to sets of *tokens* —

- ``("pool", line)``   — (a view of) a pooled host buffer acquired
  locally via ``<poolish>.acquire()`` (HostBufferPool protocol);
- ``("param", i)``     — (a view of) the function's i-th parameter
  (``self`` counts, so method summaries compose through receivers);
- ``("dfn", spec)``    — a donated jitted callable: the result of
  ``jax.jit(..., donate_argnums=spec)``, directly or via a project
  function whose summary says it returns one.

Tokens flow through assignments, tuple/list displays, subscripts and
slices, numpy view-returning calls (``ascontiguousarray``/``asarray``
*may* return their input — the PR 12 trap), view methods
(``reshape``/``ravel``/``T``/...), comprehensions, and — the
interprocedural part — resolved project calls, via per-function
summaries (returns-view-of-param, returns-pooled, releases-param,
param-escapes-to-sink, returns-donated-callable) iterated to a
fixpoint so helper chains compose.

Copies (``.copy()``, ``.flatten()``, ``np.array``, arithmetic) kill
taint — that is exactly why the PR 12 fix (``flatten()`` instead of
``ascontiguousarray``) reads as safe here.

The walk also records the *events* the rule families consume:

- escapes: a tainted value handed to an async sink (``.put`` /
  ``.submit`` — token-protected submits are marked), returned,
  yielded, or stored on an object;
- releases: ``<poolish>.release(x)`` / ``recycle*(x)`` of a tainted
  value — textual, plus interprocedural via releases-param summaries;
- uses: loads of tainted names (for use-after-release ordering);
- donated_use: a load of a name after it was passed at a donated
  position of a ``("dfn", spec)`` callable;
- raw network calls, ``http_request`` routing and ``deadline_scope``
  entry (the SW6xx facts);

Branch sensitivity is deliberately coarse: every event carries a
branch path (tuple of body ids), and rules only pair events whose
branch paths are prefix-comparable — a release in an ``if`` arm never
pairs with a use in the sibling ``else`` arm.

buffer_rules.py / net_rules.py consume this; jax_rules.py is a
separate lexical pass (loops + jit/device_put/static_argnums need no
value flow beyond the donated-callable tokens handled here).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Optional

from .lockgraph import Project, resolve_call
from .model import ModuleInfo, call_ref

#: numpy module-level calls whose result may alias argument 0.
#: ``ascontiguousarray``/``asarray`` are the sharp edge: they return
#: the *input itself* when it is already contiguous/an ndarray.
_NP_VIEW_FNS = {
    "ascontiguousarray", "asarray", "asfortranarray", "asanyarray",
    "frombuffer", "reshape", "ravel", "transpose", "squeeze",
    "swapaxes", "moveaxis", "atleast_1d", "atleast_2d", "atleast_3d",
    "broadcast_to", "expand_dims", "split", "array_split", "hsplit",
    "vsplit", "dsplit",
}

#: ndarray methods returning a view of the receiver.
_VIEW_METHODS = {"reshape", "ravel", "view", "transpose", "squeeze",
                 "swapaxes", "diagonal"}

#: ndarray methods guaranteed to copy (or reduce) — taint killers.
_COPY_METHODS = {"copy", "flatten", "tobytes", "astype", "tolist",
                 "sum", "min", "max", "mean", "all", "any", "item"}

_RELEASE_RE = re.compile(r"(release|recycle)", re.IGNORECASE)
_POOL_RE = re.compile(r"pool", re.IGNORECASE)
_TOKEN_RE = re.compile(r"token", re.IGNORECASE)

_EMPTY: frozenset = frozenset()
_MAX_ROUNDS = 4


def _dotted(e: ast.expr) -> str:
    """Cheap dotted-name text for Name/Attribute chains ('' otherwise)."""
    if isinstance(e, ast.Name):
        return e.id
    if isinstance(e, ast.Attribute):
        base = _dotted(e.value)
        return f"{base}.{e.attr}" if base else e.attr
    return ""


@dataclass
class Summary:
    """Composable interprocedural facts about one function."""

    returns_view_of: frozenset = _EMPTY      # param indices
    returns_pooled: bool = False
    returns_donated: Optional[tuple] = None  # donate spec or "all"
    param_released: frozenset = _EMPTY       # param indices
    #: param index -> (sink, protected)
    param_sinks: dict = field(default_factory=dict)
    raw_net: tuple = ()                      # ((desc, line), ...)
    enters_deadline: bool = False

    def facts(self) -> tuple:
        return (self.returns_view_of, self.returns_pooled,
                self.returns_donated, self.param_released,
                tuple(sorted(self.param_sinks.items())),
                self.raw_net, self.enters_deadline)


@dataclass
class Event:
    kind: str            # escape | release | use | donated_use
    line: int
    tokens: frozenset
    branch: tuple        # branch path; prefix-comparable events pair
    sink: str = ""       # queue.put | submit | return | yield | store | call
    protected: bool = False
    detail: str = ""


@dataclass
class FlowFunc:
    key: str
    module: str
    path: str
    name: str
    line: int
    params: list
    parent: Optional[str]          # enclosing function key, if nested
    is_method: bool
    node: object = field(repr=False, default=None)
    acquires: list = field(default_factory=list)   # (line, recv text)
    events: list = field(default_factory=list)
    resolved_calls: list = field(default_factory=list)  # (callee, line)
    summary: Summary = field(default_factory=Summary)
    has_project_calls: bool = False


@dataclass
class FlowProject:
    modules: dict
    proj: Project
    flows: dict = field(default_factory=dict)      # key -> FlowFunc


# --------------------------------------------------------------------------
# function discovery — keyed exactly like model.py so lockgraph's
# resolver lands on the matching FlowFunc
# --------------------------------------------------------------------------

def _discover(mi: ModuleInfo, flows: dict) -> None:
    def walk(node, cls: Optional[str], parent: Optional[str]) -> None:
        for ch in ast.iter_child_nodes(node):
            if isinstance(ch, ast.ClassDef):
                walk(ch, cls if cls is not None else ch.name, parent)
            elif isinstance(ch, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = (f"{mi.name}:{cls}.{ch.name}" if cls
                       else f"{mi.name}:{ch.name}")
                a = ch.args
                params = [p.arg for p in (*a.posonlyargs, *a.args)]
                if key not in flows:
                    flows[key] = FlowFunc(
                        key=key, module=mi.name, path=mi.path,
                        name=ch.name, line=ch.lineno, params=params,
                        parent=parent, is_method=bool(cls), node=ch)
                walk(ch, cls, key)
            else:
                walk(ch, cls, parent)

    walk(mi.tree, None, None)


# --------------------------------------------------------------------------
# per-function abstract interpretation
# --------------------------------------------------------------------------

class _Walker:
    def __init__(self, fp: FlowProject, mi: ModuleInfo, ff: FlowFunc,
                 summaries: dict):
        self.fp = fp
        self.mi = mi
        self.ff = ff
        self.summaries = summaries
        self.env: dict[str, frozenset] = {
            p: frozenset({("param", i)}) for i, p in enumerate(ff.params)}
        self.events: list[Event] = []
        self.acquires: list = []
        self.resolved_calls: list = []
        self.returns_tokens: set = set()
        self.raw_net: list = []
        self.enters_deadline = False
        self.has_project_calls = False
        self.donated: dict[str, int] = {}   # name -> donation line
        self.pool_names: set[str] = set()   # names bound to *Pool(...)
        self.branch: tuple = ()
        self._branch_seq = 0
        self._mute_use = 0
        self.tokenish = self._token_prepass(ff.node)

    # -- prepass: names ever bound to a *Token(...) constructor --------

    @staticmethod
    def _token_prepass(node) -> set:
        out = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                if _TOKEN_RE.search(_dotted(n.value.func) or ""):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
        return out

    # -- driver --------------------------------------------------------

    def run(self) -> None:
        for st in self.ff.node.body:
            self.stmt(st)

    def event(self, kind: str, line: int, tokens: frozenset, *,
              sink: str = "", protected: bool = False,
              detail: str = "") -> None:
        self.events.append(Event(kind, line, tokens, self.branch,
                                 sink=sink, protected=protected,
                                 detail=detail))

    def _sub_branch(self):
        self._branch_seq += 1
        return self.branch + (self._branch_seq,)

    def _body(self, stmts, new_branch: bool) -> None:
        prev = self.branch
        if new_branch:
            self.branch = self._sub_branch()
        for st in stmts:
            self.stmt(st)
        self.branch = prev

    # -- statements ----------------------------------------------------

    def stmt(self, st) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # separate scope, analyzed on its own
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(st, "value", None)
            toks = self.expr(value) if value is not None else _EMPTY
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            for t in targets:
                self.assign(t, toks)
        elif isinstance(st, ast.Return):
            toks = self.expr(st.value)
            if toks:
                self.returns_tokens |= toks
                self.event("escape", st.lineno, toks, sink="return")
        elif isinstance(st, ast.Expr):
            self.expr(st.value)
        elif isinstance(st, ast.If):
            self.expr(st.test)
            self._body(st.body, True)
            self._body(st.orelse, True)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            it = self.expr(st.iter)
            self.assign(st.target, it)
            self._body(st.body, False)
            self._body(st.orelse, True)
        elif isinstance(st, ast.While):
            self.expr(st.test)
            self._body(st.body, False)
            self._body(st.orelse, True)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                toks = self.expr(item.context_expr)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, toks)
            self._body(st.body, False)
        elif isinstance(st, ast.Try):
            self._body(st.body, False)
            for h in st.handlers:
                self._body(h.body, True)
            self._body(st.orelse, True)
            self._body(st.finalbody, False)
        elif isinstance(st, (ast.Raise, ast.Assert, ast.Delete)):
            for n in ast.iter_child_nodes(st):
                if isinstance(n, ast.expr):
                    self.expr(n)
        # pass/break/continue/import/global/nonlocal: nothing flows

    def assign(self, target, toks: frozenset) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = toks
            self.donated.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self.assign(el, toks)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, toks)
        elif isinstance(target, (ast.Attribute, ast.Subscript)):
            self.expr(target.value)
            if toks and any(t[0] in ("pool", "param") for t in toks):
                self.event("escape", target.lineno, toks, sink="store")

    # -- expressions ---------------------------------------------------

    def expr(self, e) -> frozenset:
        if e is None:
            return _EMPTY
        if isinstance(e, ast.Name):
            toks = self.env.get(e.id, _EMPTY)
            if e.id in self.donated and not self._mute_use:
                self.event("donated_use", e.lineno, toks,
                           detail=f"{e.id!r} was donated to a jitted "
                                  f"call at line {self.donated[e.id]}")
            if toks and not self._mute_use and \
                    any(t[0] == "pool" for t in toks):
                self.event("use", e.lineno, toks, detail=e.id)
            return toks
        if isinstance(e, ast.Call):
            return self.call(e)
        if isinstance(e, ast.Attribute):
            base = self.expr(e.value)
            return base if e.attr == "T" else _EMPTY
        if isinstance(e, ast.Subscript):
            base = self.expr(e.value)
            self.expr(e.slice)
            return base
        if isinstance(e, ast.Starred):
            return self.expr(e.value)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            out = _EMPTY
            for el in e.elts:
                out |= self.expr(el)
            return out
        if isinstance(e, ast.Dict):
            out = _EMPTY
            for k in e.keys:
                if k is not None:
                    self.expr(k)
            for v in e.values:
                out |= self.expr(v)
            return out
        if isinstance(e, ast.IfExp):
            self.expr(e.test)
            return self.expr(e.body) | self.expr(e.orelse)
        if isinstance(e, ast.NamedExpr):
            toks = self.expr(e.value)
            self.assign(e.target, toks)
            return toks
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            for g in e.generators:
                self.assign(g.target, self.expr(g.iter))
                for c in g.ifs:
                    self.expr(c)
            if isinstance(e, ast.DictComp):
                self.expr(e.key)
                return self.expr(e.value)
            return self.expr(e.elt)
        if isinstance(e, ast.Await):
            return self.expr(e.value)
        if isinstance(e, (ast.Yield, ast.YieldFrom)):
            toks = self.expr(e.value)
            if toks:
                self.returns_tokens |= toks
                self.event("escape", e.lineno, toks, sink="yield")
            return _EMPTY
        if isinstance(e, ast.Lambda):
            return _EMPTY  # separate scope
        if isinstance(e, (ast.BoolOp, ast.BinOp, ast.UnaryOp,
                          ast.Compare, ast.JoinedStr, ast.FormattedValue,
                          ast.Slice)):
            for n in ast.iter_child_nodes(e):
                if isinstance(n, ast.expr):
                    self.expr(n)
            return _EMPTY
        return _EMPTY

    # -- calls: sources, sinks, numpy algebra, project summaries -------

    def _poolish(self, recv, recv_text: str) -> bool:
        if _POOL_RE.search(recv_text):
            return True
        return isinstance(recv, ast.Name) and recv.id in self.pool_names

    def _protected(self, c: ast.Call) -> bool:
        for v in (*c.args, *(kw.value for kw in c.keywords)):
            if isinstance(v, ast.Call) and \
                    _TOKEN_RE.search(_dotted(v.func) or ""):
                return True
            if isinstance(v, ast.Name) and (
                    _TOKEN_RE.search(v.id) or v.id in self.tokenish):
                return True
        return False

    def _donate_spec(self, c: ast.Call) -> Optional[tuple]:
        """jax.jit(..., donate_argnums=...) -> donated positions.

        Literal int/tuple-of-ints parse exactly; anything else dynamic
        (a variable, ``tuple(range(n))``) conservatively donates every
        positional arg ("all"). An empty literal tuple donates nothing.
        """
        d = _dotted(c.func)
        leaf = d.rsplit(".", 1)[-1]
        root = d.split(".")[0]
        root_mod = self.mi.imports.get(root, root)
        is_jit = (leaf in ("jit", "pjit")
                  and (root_mod.startswith("jax") or root in ("jit",
                                                              "pjit")))
        if not is_jit:
            return None
        for kw in c.keywords:
            if kw.arg not in ("donate_argnums", "donate_argnames"):
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, ast.Tuple) and all(
                    isinstance(el, ast.Constant) for el in v.elts):
                spec = tuple(el.value for el in v.elts)
                return spec or None
            return ("all",)
        return None

    def _check_net(self, c: ast.Call) -> None:
        d = _dotted(c.func)
        leaf = d.rsplit(".", 1)[-1]
        root = d.split(".")[0]
        root_mod = self.mi.imports.get(root, root)
        if leaf == "urlopen":
            src = self.mi.from_imports.get("urlopen", ("", ""))[0]
            if isinstance(c.func, ast.Attribute) or \
                    src.startswith("urllib") or src == "":
                self.raw_net.append((f"{d}()", c.lineno))
        elif leaf in ("HTTPConnection", "HTTPSConnection") and \
                root_mod.startswith("http"):
            self.raw_net.append((f"{d}()", c.lineno))
        elif leaf == "create_connection" and root_mod == "socket":
            self.raw_net.append((f"{d}()", c.lineno))
        elif leaf == "deadline_scope":
            self.enters_deadline = True

    def call(self, c: ast.Call) -> frozenset:
        line = c.lineno
        fn = c.func
        self._check_net(c)

        recv_toks = _EMPTY
        fn_toks = _EMPTY
        if isinstance(fn, ast.Attribute):
            recv_toks = self.expr(fn.value)
        elif isinstance(fn, ast.Name):
            fn_toks = self.env.get(fn.id, _EMPTY)
        else:
            fn_toks = self.expr(fn)

        argtoks = [self.expr(a) for a in c.args]
        kwtoks = {kw.arg: self.expr(kw.value) for kw in c.keywords}
        all_args = frozenset().union(*argtoks, *kwtoks.values()) \
            if (argtoks or kwtoks) else _EMPTY
        flowing = frozenset(t for t in all_args
                            if t[0] in ("pool", "param"))

        # ---- textual protocol matches (short-circuit resolution) ----
        if isinstance(fn, ast.Attribute):
            attr = fn.attr
            recv_text = _dotted(fn.value)
            if attr == "acquire" and self._poolish(fn.value, recv_text):
                self.acquires.append((line, recv_text))
                return frozenset({("pool", line)})
            if _RELEASE_RE.search(attr) and flowing:
                self._mark_release(line, flowing)
                return _EMPTY
            if attr in ("put", "put_nowait") and flowing:
                self.event("escape", line, flowing, sink="queue.put")
                return _EMPTY
            if attr == "submit" and flowing:
                self.event("escape", line, flowing, sink="submit",
                           protected=self._protected(c))
                return _EMPTY
            root_mod = self.mi.imports.get(recv_text, recv_text)
            if root_mod == "numpy":
                if attr in _NP_VIEW_FNS:
                    return argtoks[0] if argtoks else _EMPTY
                return _EMPTY
            if recv_toks:
                if attr in _VIEW_METHODS:
                    return frozenset(t for t in recv_toks
                                     if t[0] != "dfn")
                return _EMPTY
        elif isinstance(fn, ast.Name):
            if fn.id == "memoryview" and argtoks:
                return argtoks[0]
            if _RELEASE_RE.search(fn.id) and flowing:
                self._mark_release(line, flowing)
                return _EMPTY

        # ---- donated-callable construction / dispatch ----
        spec = self._donate_spec(c)
        if spec is not None:
            return frozenset({("dfn", spec)})
        dfn = [t for t in fn_toks if t[0] == "dfn"]
        if dfn:
            spec = dfn[0][1]
            for i, a in enumerate(c.args):
                if isinstance(a, ast.Name) and \
                        (spec == ("all",) or i in spec):
                    self.donated[a.id] = line
            return _EMPTY

        # ---- project-call resolution + summary application ----
        ref = call_ref(fn, self.mi)
        callee = self._resolve(ref) if ref is not None else None
        if callee is not None:
            self.has_project_calls = True
            self.resolved_calls.append((callee, line))
            s = self.summaries.get(callee)
            if s is not None:
                return self._apply_summary(c, callee, s, recv_toks,
                                           argtoks, kwtoks, line)
            return _EMPTY
        if flowing:
            # leaves the project with tainted args: weak escape, never
            # flagged alone but visible to future rules
            self.event("escape", line, flowing, sink="call",
                       protected=True, detail=_dotted(fn))
        return _EMPTY

    def _mark_release(self, line: int, toks: frozenset) -> None:
        self.event("release", line, toks)

    def _resolve(self, ref: tuple) -> Optional[str]:
        caller_fi = self.fp.proj.funcs.get(self.ff.key)
        if caller_fi is None:
            return None
        callee = resolve_call(self.fp.proj, self.mi, caller_fi, ref)
        if callee is None:
            return None
        target = self.fp.flows.get(callee)
        if target is None:
            return None
        # scope guard: a plain-name ref must not resolve to a function
        # nested inside an UNRELATED function (model keys nested defs
        # flat, so `sink(...)` in one function could otherwise bind to
        # a different function's local helper)
        if ref[0] == "name" and target.parent is not None:
            anc = self.ff.key
            chain = set()
            while anc is not None:
                chain.add(anc)
                anc = self.fp.flows[anc].parent \
                    if anc in self.fp.flows else None
            if target.parent not in chain:
                return None
        return callee

    def _apply_summary(self, c, callee: str, s: Summary, recv_toks,
                       argtoks, kwtoks, line: int) -> frozenset:
        target = self.fp.flows[callee]
        ref_is_attr = isinstance(c.func, ast.Attribute)
        # bind the receiver as arg 0 for method calls through an
        # attribute (obj.meth(a) -> meth(self=obj, a))
        eff = ([recv_toks] + argtoks) if (target.is_method
                                          and ref_is_attr) else argtoks
        for name, toks in kwtoks.items():
            if name in target.params:
                i = target.params.index(name)
                while len(eff) <= i:
                    eff.append(_EMPTY)
                eff[i] = eff[i] | toks
        short = callee.split(":")[-1]
        out = _EMPTY
        for i in s.returns_view_of:
            if i < len(eff):
                out |= eff[i]
        if s.returns_pooled:
            out |= {("pool", line)}
        if s.returns_donated is not None:
            out |= {("dfn", s.returns_donated)}
        for i in s.param_released:
            if i < len(eff) and eff[i]:
                self.event("release", line, frozenset(
                    t for t in eff[i] if t[0] in ("pool", "param")),
                    detail=f"via {short}()")
        for i, (sink, prot) in s.param_sinks.items():
            if i < len(eff) and eff[i]:
                toks = frozenset(t for t in eff[i]
                                 if t[0] in ("pool", "param"))
                if toks:
                    self.event("escape", line, toks, sink=sink,
                               protected=prot, detail=f"via {short}()")
        return out


def _summarize(w: _Walker) -> Summary:
    returns_view_of = frozenset(
        t[1] for t in w.returns_tokens if t[0] == "param")
    returns_pooled = any(t[0] == "pool" for t in w.returns_tokens)
    donated = next((t[1] for t in w.returns_tokens if t[0] == "dfn"),
                   None)
    released = set()
    sinks: dict = {}
    for ev in w.events:
        if ev.kind == "release":
            released |= {t[1] for t in ev.tokens if t[0] == "param"}
        elif ev.kind == "escape" and ev.sink in ("queue.put", "submit"):
            for t in ev.tokens:
                if t[0] == "param" and t[1] not in sinks:
                    sinks[t[1]] = (ev.sink, ev.protected)
    return Summary(returns_view_of=returns_view_of,
                   returns_pooled=returns_pooled,
                   returns_donated=donated,
                   param_released=frozenset(released),
                   param_sinks=sinks,
                   raw_net=tuple(w.raw_net),
                   enters_deadline=w.enters_deadline)


def build_flows(modules: dict[str, ModuleInfo],
                proj: Optional[Project] = None) -> FlowProject:
    """Walk every function to a summary fixpoint; the returned
    FlowProject carries final per-function events for the rules."""
    if proj is None:
        proj = Project(modules)
    fp = FlowProject(modules=modules, proj=proj)
    for mi in modules.values():
        _discover(mi, fp.flows)

    summaries: dict[str, Summary] = {k: Summary() for k in fp.flows}
    active = list(fp.flows.values())
    for _round in range(_MAX_ROUNDS):
        changed = False
        next_active = []
        for ff in active:
            mi = fp.modules[ff.module]
            w = _Walker(fp, mi, ff, summaries)
            # pool-constructor name prepass (cheap, one walk)
            for n in ast.walk(ff.node):
                if isinstance(n, ast.Assign) and \
                        isinstance(n.value, ast.Call) and \
                        "BufferPool" in (_dotted(n.value.func) or ""):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            w.pool_names.add(t.id)
            w.run()
            ff.acquires = w.acquires
            ff.events = w.events
            ff.resolved_calls = w.resolved_calls
            ff.has_project_calls = w.has_project_calls
            new = _summarize(w)
            if new.facts() != summaries[ff.key].facts():
                summaries[ff.key] = new
                changed = True
            if w.has_project_calls:
                next_active.append(ff)
        if not changed:
            break
        # later rounds only re-walk functions whose results can change
        active = next_active
    for ff in fp.flows.values():
        ff.summary = summaries[ff.key]
    return fp
