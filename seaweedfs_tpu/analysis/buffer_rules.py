"""SW5xx — pooled-buffer lifetime & donation rules (dataflow clients).

The bug class PR 12 shipped a hand-fix for: the overlapped pipeline
hands out views of HostBufferPool slabs, recycles the slab when a
BatchToken fires, and ``np.ascontiguousarray`` silently returns the
*input itself* when it is already contiguous — so a "copy" handed to
the async writeback pool was really a view of a buffer the reader was
about to refill. These rules run over the value-flow events
(dataflow.py) and catch that shape statically:

- SW501 (error): a value derived from a pooled-buffer acquire escapes
  to an asynchronous sink (``queue.put`` / ``.submit`` without a
  BatchToken argument) *and* the same buffer is released in the same
  function — the consumer races the recycle. Interprocedural: the
  escape or the release may happen inside a resolved callee.
- SW502 (error): a pooled buffer (or a view of it) is read after the
  buffer was released — straight-line use-after-free. Events pair
  only when their branch paths are prefix-comparable, so an ``if``
  arm's release never pairs with the ``else`` arm's use.
- SW503 (error): a name is read again after being passed at a donated
  position of a ``jax.jit(..., donate_argnums=...)`` callable — the
  XLA buffer is invalid after dispatch (ops/rs_jax.py DONATE
  contract); works through project functions that *return* donated
  callables (``_jitted_apply``-style factories).

The runtime counterpart is util/bufcheck.py (SEAWEED_BUFCHECK=1):
generation-tagged poisoned recycles catch at test time what these
rules cannot prove statically.
"""

from __future__ import annotations

from .dataflow import FlowProject
from .findings import Finding

#: Sinks where the consumer outlives the producing statement.
_ASYNC_SINKS = {"queue.put", "submit"}


def _comparable(a: tuple, b: tuple) -> bool:
    """True when one branch path prefixes the other (same control
    path), so event A can actually precede event B at runtime."""
    n = min(len(a), len(b))
    return a[:n] == b[:n]


def check_buffers(fp: FlowProject) -> list[Finding]:
    findings: list[Finding] = []
    for ff in fp.flows.values():
        releases = [ev for ev in ff.events if ev.kind == "release"
                    and any(t[0] == "pool" for t in ev.tokens)]
        escapes = [ev for ev in ff.events if ev.kind == "escape"
                   and any(t[0] == "pool" for t in ev.tokens)]
        uses = [ev for ev in ff.events if ev.kind == "use"]

        # ---- SW501: pooled view escapes an async sink + same-function
        # release → the sink's consumer races the recycle ----
        for esc in escapes:
            if esc.sink not in _ASYNC_SINKS or esc.protected:
                continue
            esc_roots = {t for t in esc.tokens if t[0] == "pool"}
            for rel in releases:
                if not (esc_roots & rel.tokens):
                    continue
                if not _comparable(esc.branch, rel.branch):
                    continue
                acq_line = min(t[1] for t in (esc_roots & rel.tokens))
                via = f" ({esc.detail})" if esc.detail else ""
                findings.append(Finding(
                    "SW501", "error", ff.path, esc.line, ff.key,
                    f"view of pooled buffer (acquired line {acq_line}) "
                    f"escapes to async {esc.sink}{via} without a "
                    f"BatchToken, and the buffer is released at line "
                    f"{rel.line} — the write can read a recycled "
                    f"buffer (the PR 12 race); copy the data "
                    f"(flatten()) or gate the release on a token",
                    extra={"anchors": (rel.line,)}))
                break

        # ---- SW502: use (or escape) of a pooled view after its
        # buffer was released in the same straight-line region ----
        for rel in releases:
            rel_roots = {t for t in rel.tokens if t[0] == "pool"}
            for ev in (*uses, *escapes):
                if ev.line <= rel.line:
                    continue
                if not _comparable(ev.branch, rel.branch):
                    continue
                hit = rel_roots & {t for t in ev.tokens
                                   if t[0] == "pool"}
                if not hit:
                    continue
                acq_line = min(t[1] for t in hit)
                what = (f"escapes via {ev.sink}" if ev.kind == "escape"
                        else f"is read ({ev.detail})")
                findings.append(Finding(
                    "SW502", "error", ff.path, ev.line, ff.key,
                    f"pooled buffer (acquired line {acq_line}) "
                    f"released at line {rel.line} but a view of it "
                    f"{what} afterwards — use-after-release",
                    extra={"anchors": (rel.line,)}))
                break  # one finding per release site is enough

        # ---- SW503: read after donation ----
        for ev in ff.events:
            if ev.kind != "donated_use":
                continue
            findings.append(Finding(
                "SW503", "error", ff.path, ev.line, ff.key,
                f"buffer read after donation: {ev.detail}; "
                f"donate_argnums invalidates the argument buffer at "
                f"dispatch (see ops/rs_jax.py DONATE contract) — "
                f"re-materialize or drop the donation",
                extra={}))
    return findings
