"""SW6xx — deadline / retry coverage rules.

The cluster plane has exactly one sanctioned way to speak HTTP:
``util/retry.http_request`` (jittered retries + per-request deadline +
circuit breaker + X-Seaweed-Deadline propagation). These rules police
the perimeter:

- SW601 (error): a raw ``urllib.request.urlopen`` / ``http.client
  HTTP(S)Connection`` / ``socket.create_connection`` call anywhere
  outside util/retry.py itself. Raw calls have no deadline, no
  breaker, and silently drop the cluster deadline header.
- SW602 (warning): a handler/job-path function (``do_GET``-style
  verbs, ``*_handler``, ``handle_*``, ``run_task*``, ``*_job``)
  *transitively* reaches a raw network call with no
  ``deadline_scope`` entered anywhere on the resolved call chain —
  an unbounded stall a client timeout cannot cancel server-side.
  Propagated over the same resolved call graph as the lock rules.
- SW603 (warning): a retry-shaped loop (``while`` + try/except +
  sleep) that consults neither a breaker nor a deadline nor a bounded
  attempt budget — the retry-storm shape util/retry exists to
  prevent.
"""

from __future__ import annotations

import ast
import re

from .dataflow import FlowProject, _dotted
from .findings import Finding

#: The one module allowed to make raw network calls: the wrapper.
_SANCTIONED_PATH = "util/retry.py"

_HANDLER_RE = re.compile(
    r"^do_[A-Z]+$|^handle(_|$)|_handler$|^run_task|_job$|^serve_request")

#: Evidence inside a retry loop that some budget bounds it.
_BUDGET_RE = re.compile(
    r"breaker|deadline|remaining\s*\(|expired\s*\(|attempt|n_tries|"
    r"max_tries|retries\b|budget", re.IGNORECASE)

_MAX_ROUNDS = 12


def _sw601(fp: FlowProject) -> list[Finding]:
    out = []
    for ff in fp.flows.values():
        if ff.path.endswith(_SANCTIONED_PATH):
            continue
        for desc, line in ff.summary.raw_net:
            out.append(Finding(
                "SW601", "error", ff.path, line, ff.key,
                f"raw network call {desc} bypasses "
                f"util.retry.http_request (no deadline, no breaker, "
                f"drops X-Seaweed-Deadline propagation)"))
    return out


def _sw602(fp: FlowProject) -> list[Finding]:
    # eff[f] = first raw-net site reachable from f with no
    # deadline_scope entered on the way (None = covered / none)
    eff: dict[str, tuple | None] = {}
    for key, ff in fp.flows.items():
        if ff.summary.enters_deadline or ff.path.endswith(
                _SANCTIONED_PATH):
            eff[key] = None
        elif ff.summary.raw_net:
            desc, line = ff.summary.raw_net[0]
            eff[key] = (desc, line, "")
    for _ in range(_MAX_ROUNDS):
        changed = False
        for key, ff in fp.flows.items():
            if key in eff:
                continue
            if ff.summary.enters_deadline:
                eff[key] = None
                continue
            for callee, line in ff.resolved_calls:
                hit = eff.get(callee)
                if hit is not None:
                    short = callee.split(":")[-1]
                    chain = f"{short}()" + (f" -> {hit[2]}" if hit[2]
                                            else "")
                    eff[key] = (hit[0], line, chain)
                    changed = True
                    break
        if not changed:
            break
    out = []
    for key, ff in fp.flows.items():
        if not _HANDLER_RE.search(ff.name):
            continue
        hit = eff.get(key)
        if hit is None:
            continue
        desc, line, chain = hit
        via = f" via {chain}" if chain else ""
        out.append(Finding(
            "SW602", "warning", ff.path, line, key,
            f"handler/job path reaches raw network call {desc}{via} "
            f"with no deadline_scope on the chain — an unbounded "
            f"stall the caller cannot cancel; wrap the path in "
            f"util.retry.deadline_scope or route through "
            f"http_request"))
    return out


def _net_in(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            leaf = d.rsplit(".", 1)[-1]
            if leaf in ("urlopen", "http_request", "create_connection",
                        "HTTPConnection", "HTTPSConnection", "request",
                        "getresponse"):
                return True
    return False


def _sleep_in(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and \
                _dotted(n.func).rsplit(".", 1)[-1] == "sleep":
            return True
    return False


def _sw603(fp: FlowProject, sources: dict[str, str]) -> list[Finding]:
    out = []
    for ff in fp.flows.values():
        if ff.path.endswith(_SANCTIONED_PATH):
            continue
        body = ff.node
        for n in ast.walk(body):
            if not isinstance(n, ast.While):
                continue
            has_try = any(isinstance(x, ast.Try) for x in ast.walk(n))
            if not (has_try and _net_in(n) and _sleep_in(n)):
                continue
            lines = sources.get(ff.path, "").splitlines()
            end = getattr(n, "end_lineno", n.lineno) or n.lineno
            region = "\n".join(lines[n.lineno - 1:end])
            if _BUDGET_RE.search(region):
                continue
            out.append(Finding(
                "SW603", "warning", ff.path, n.lineno, ff.key,
                "retry loop (while + try/except + sleep around a "
                "network call) consults no breaker, deadline, or "
                "attempt budget — unbounded retry storm; use "
                "util.retry.http_request or check a CircuitBreaker/"
                "Deadline in the loop"))
    return out


def check_net(fp: FlowProject, sources: dict[str, str]) -> list[Finding]:
    return _sw601(fp) + _sw602(fp) + _sw603(fp, sources)
