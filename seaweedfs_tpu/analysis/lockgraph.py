"""Cross-module lock-acquisition graph + blocking-I/O-under-lock.

Consumes the per-module models, resolves the call graph (self-methods,
module functions, imported functions, ``self.attr.meth()`` via inferred
attribute types, and project-unique method names), propagates "locks
acquired" and "blocking I/O performed" sets to a fixpoint, then:

- SW101 (error): cycles in the lock-order digraph — two locks taken in
  both orders somewhere in the project — and non-reentrant
  ``threading.Lock`` self-cycles.
- SW102 (info): every nested-acquire site (graph edge), so reviewers
  can audit the ordering discipline the cycle check depends on.
- SW103: blocking I/O while a lock is held — directly or through any
  resolved call chain. sleep/socket/network/rpc/subprocess are errors;
  local file I/O is a warning (bounded latency, still worth knowing).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .findings import Finding
from .model import FuncInfo, ModuleInfo

_ERROR_CATEGORIES = {"sleep", "socket", "network", "rpc", "subprocess"}
_MAX_ROUNDS = 12


@dataclass
class _Edge:
    outer: str
    inner: str
    path: str
    line: int
    qualname: str
    via: str = ""      # call-chain text for indirect edges


@dataclass
class Project:
    modules: dict[str, ModuleInfo]
    funcs: dict[str, FuncInfo] = field(default_factory=dict)
    lock_kinds: dict[str, str] = field(default_factory=dict)
    #: method name -> {class_key} across the whole project
    method_owners: dict[str, set[str]] = field(default_factory=dict)

    def __post_init__(self):
        for mi in self.modules.values():
            for d in mi.module_locks.values():
                self.lock_kinds[d.lock_id] = d.kind
            for f in mi.functions.values():
                self.funcs[f.key] = f
            for cname, ci in mi.classes.items():
                for d in ci.lock_defs.values():
                    self.lock_kinds[d.lock_id] = d.kind
                for mname, f in ci.methods.items():
                    self.funcs[f.key] = f
                    self.method_owners.setdefault(mname, set()).add(
                        f"{mi.name}:{cname}")

    def kind(self, lock_id: str) -> str:
        return self.lock_kinds.get(lock_id, "unknown")


def resolve_call(proj: Project, mi: ModuleInfo, caller: FuncInfo,
                 ref: tuple) -> Optional[str]:
    """CallRef -> FuncInfo key, or None when it leaves the project.

    Shared with the value-flow engine (dataflow.py): both layers
    resolve call sites against the same project call graph.
    """
    if ref[0] == "self":
        cls = caller.key.rsplit(":", 1)[1].split(".")[0] \
            if "." in caller.key.rsplit(":", 1)[1] else None
        if cls:
            key = f"{mi.name}:{cls}.{ref[1]}"
            if key in proj.funcs:
                return key
        return None
    if ref[0] == "name":
        key = f"{mi.name}:{ref[1]}"
        if key in proj.funcs:
            return key
        tgt = mi.from_imports.get(ref[1])
        if tgt:
            key = f"{tgt[0]}:{tgt[1]}"
            if key in proj.funcs:
                return key
        return None
    if ref[0] == "alias":
        mod = mi.imports.get(ref[1])
        if mod:
            key = f"{mod}:{ref[2]}"
            if key in proj.funcs:
                return key
        return None
    if ref[0] == "selfattr":
        cls = caller.key.rsplit(":", 1)[1].split(".")[0] \
            if "." in caller.key.rsplit(":", 1)[1] else None
        ci = mi.classes.get(cls) if cls else None
        if ci is not None:
            cls_key = ci.attr_types.get(ref[1])
            if cls_key:
                key = f"{cls_key}.{ref[2]}"
                if key in proj.funcs:
                    return key
        # fall through to the uniqueness heuristic
        ref = ("unique", ref[2])
    if ref[0] == "unique":
        owners = proj.method_owners.get(ref[1], set())
        if len(owners) == 1:
            return f"{next(iter(owners))}.{ref[1]}"
    return None


def _fixpoint(proj: Project):
    """Propagate acquired-lock and blocking sets over the call graph.

    eff_locks[f]  : lock_id -> short call-chain string ("" = direct)
    eff_block[f]  : category -> (description, chain string)
    """
    eff_locks: dict[str, dict[str, str]] = {}
    eff_block: dict[str, dict[str, tuple[str, str]]] = {}
    resolved: dict[str, list[tuple[str, int, tuple, tuple]]] = {}
    for key, f in proj.funcs.items():
        eff_locks[key] = {lid: "" for lid in f.acquires}
        eff_block[key] = {cat: (desc, "")
                          for cat, desc, _ln, _h, _w in f.blocking}
        mi = proj.modules[f.module]
        resolved[key] = [
            (callee, line, held, wlines)
            for ref, line, held, wlines in f.calls
            if (callee := resolve_call(proj, mi, f, ref)) is not None]

    for _ in range(_MAX_ROUNDS):
        changed = False
        for key, calls in resolved.items():
            for callee, _line, _held, _w in calls:
                short = callee.split(":")[-1]
                for lid, chain in eff_locks.get(callee, {}).items():
                    if lid not in eff_locks[key]:
                        eff_locks[key][lid] = \
                            f"{short} -> {chain}" if chain else short
                        changed = True
                for cat, (desc, chain) in eff_block.get(callee,
                                                        {}).items():
                    if cat not in eff_block[key]:
                        eff_block[key][cat] = (
                            desc, f"{short} -> {chain}" if chain
                            else short)
                        changed = True
        if not changed:
            break
    return eff_locks, eff_block, resolved


def _cycles(edges: list[_Edge]) -> list[list[str]]:
    """Elementary cycles via DFS over the lock digraph (it is tiny)."""
    adj: dict[str, set[str]] = {}
    for e in edges:
        adj.setdefault(e.outer, set()).add(e.inner)
    cycles: list[list[str]] = []
    seen_cycles: set[tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: list[str],
            on_path: set[str]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start and len(path) > 1:
                # canonicalize rotation so each cycle reports once
                i = path.index(min(path))
                canon = tuple(path[i:] + path[:i])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(list(canon))
            elif nxt not in on_path and nxt > start:
                # only walk nodes > start: each cycle found from its
                # minimal node exactly once
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for n in sorted(adj):
        dfs(n, n, [n], {n})
    return cycles


def analyze_locks(modules: dict[str, ModuleInfo],
                  proj: Optional[Project] = None) -> list[Finding]:
    if proj is None:
        proj = Project(modules)
    eff_locks, eff_block, resolved = _fixpoint(proj)

    findings: list[Finding] = []
    edges: list[_Edge] = []

    for key, f in proj.funcs.items():
        mi = proj.modules[f.module]

        for outer, inner, line in f.nest_edges:
            edges.append(_Edge(outer, inner, mi.path, line, key))

        for callee, line, held, wlines in resolved[key]:
            for lid, chain in eff_locks.get(callee, {}).items():
                for h in held:
                    if h == lid and proj.kind(lid) != "Lock":
                        continue
                    via = callee.split(":")[-1] + \
                        (f" -> {chain}" if chain else "")
                    edges.append(_Edge(h, lid, mi.path, line, key,
                                       via=via))
            for cat, (desc, chain) in eff_block.get(callee, {}).items():
                if not held or callee == key:
                    continue
                sev = "error" if cat in _ERROR_CATEGORIES else "warning"
                via = callee.split(":")[-1] + \
                    (f" -> {chain}" if chain else "")
                findings.append(Finding(
                    "SW103", sev, mi.path, line, key,
                    f"{desc} ({cat}) reached via {via} while holding "
                    f"{', '.join(held)}",
                    extra={"anchors": wlines}))

        for cat, desc, line, held, wlines in f.blocking:
            if not held:
                continue
            sev = "error" if cat in _ERROR_CATEGORIES else "warning"
            findings.append(Finding(
                "SW103", sev, mi.path, line, key,
                f"{desc} ({cat} I/O) while holding {', '.join(held)}",
                extra={"anchors": wlines}))

    # one SW102 note per distinct nested-acquire site
    seen_sites: set[tuple] = set()
    for e in edges:
        site = (e.path, e.line, e.outer, e.inner)
        if e.outer == e.inner or site in seen_sites:
            continue
        seen_sites.add(site)
        suffix = f" via {e.via}" if e.via else ""
        findings.append(Finding(
            "SW102", "info", e.path, e.line, e.qualname,
            f"nested lock acquisition: {e.outer} -> {e.inner}{suffix}"))

    # self-cycles on a non-reentrant Lock are immediate deadlocks
    for e in edges:
        if e.outer == e.inner and proj.kind(e.outer) == "Lock":
            findings.append(Finding(
                "SW101", "error", e.path, e.line, e.qualname,
                f"non-reentrant threading.Lock {e.outer} re-acquired "
                f"while already held"
                + (f" via {e.via}" if e.via else "")))

    by_pair: dict[tuple[str, str], _Edge] = {}
    for e in edges:
        if e.outer != e.inner:
            by_pair.setdefault((e.outer, e.inner), e)
    for cyc in _cycles([e for e in by_pair.values()]):
        e = by_pair[(cyc[0], cyc[1 % len(cyc)])]
        order = " -> ".join(cyc + [cyc[0]])
        sites = "; ".join(
            f"{by_pair[(a, b)].path}:{by_pair[(a, b)].line}"
            for a, b in zip(cyc, cyc[1:] + cyc[:1]))
        findings.append(Finding(
            "SW101", "error", e.path, e.line, e.qualname,
            f"lock-order cycle: {order} (sites: {sites})"))

    return findings
