"""Thread-role model: which threads can execute which function.

The lock graph (lockgraph.py) knows what locks a function holds; the
dataflow engine (dataflow.py) knows what values flow where. Neither
answers the question every new concurrency PR raises: *which threads
actually run this code?* This module closes that gap with a role
model built from the codebase's own spawning idioms:

- ``threading.Thread(target=f)`` / ``threading.Timer(t, f)`` — the
  pipeline stage threads (pipe.py/writeback.py), daemon pushers and
  reap loops, heartbeat tickers;
- worker-pool ``executor.submit(f, ...)`` where ``f`` resolves to a
  project function;
- ``IngressHTTPServer`` handler dispatch — ``do_GET``-style verb
  methods run on ingress worker-pool threads, many at once.

Each spawn site yields a *role* (named from the ``Thread(name=...)``
literal when present, else the target function). A role is
*multi-instance* when the spawn site sits in a loop or comprehension,
comes from an executor submit, or is ingress dispatch — meaning two
threads of the SAME role can race each other. Roles propagate over
the resolved project call graph (lockgraph.resolve_call) to a
fixpoint, so every function ends up with the set of thread roles that
can reach it; functions reachable from no spawn site carry the
implicit ``main`` role.

On top of the roles the model computes, per function, the *guaranteed
lockset*: the set of locks held on EVERY resolved path into the
function (intersection over call sites of locks-held-at-call, seeded
empty at thread entrypoints and call-graph roots). Combined with the
locally-held locks at an attribute access this gives the Eraser-style
candidate lockset the SW8xx rules (race_rules.py) intersect.

Finally the model records every *shared-state access*: writes,
read-modify-writes, check-then-set sequences, and container mutations
on ``self`` attributes and on locals/params whose project class is
inferable (annotations, ``x = SomeClass(...)`` constructor calls,
inherited through nested-function scopes — the ``st.read_seconds +=``
idiom of the pipeline stage closures), plus writes to ``global``
module state. race_rules.py turns (roles x locksets x accesses) into
SW801-SW804.

Runtime complement: util/racecheck.py observes the same race class
dynamically (per-(object, attr) lockset state machine) under
``SEAWEED_RACECHECK=1``.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Optional

from .dataflow import FlowProject, _dotted
from .lockgraph import Project, resolve_call
from .model import (ClassInfo, ModuleInfo, call_ref, looks_locky,
                    resolve_lock_ref)

_MAX_ROUNDS = 16

#: Method names that mutate a dict/list/set receiver in place.
_MUTATORS = {"append", "extend", "insert", "remove", "add", "discard",
             "update", "setdefault", "pop", "popitem", "clear",
             "appendleft", "extendleft"}

#: __init__ right-hand sides that type an attribute as a plain
#: (unsynchronized) container. queue.Queue / deque are internally
#: locked and deliberately absent.
_CONTAINER_CTORS = {"dict": "dict", "list": "list", "set": "set",
                    "defaultdict": "dict", "OrderedDict": "dict",
                    "Counter": "dict"}

_VERB_RE = re.compile(r"^do_[A-Z]+$")

#: Functions whose writes are construction/teardown, not steady-state
#: concurrency: roles seen here never count toward "written from >=2
#: roles". __init__ is the happens-before-publication window; close/
#: stop/join/shutdown run after the worker threads are quiesced.
_LIFECYCLE_RE = re.compile(
    r"^(__init__|__enter__|__exit__|close|stop|shutdown|join|"
    r"uninstall|reset)$")


@dataclass
class Spawn:
    """One thread-creation site."""
    role: str                 # role name ("ec-pipe-read", "thread:_run")
    target: Optional[str]     # resolved function key, if resolvable
    line: int
    path: str
    func: str                 # spawning function key
    multi: bool               # spawned in a loop / pool / ingress
    kind: str                 # "thread" | "timer" | "submit" | "ingress"


@dataclass
class Access:
    """One shared-state access site."""
    owner: str                # "mod:Class" or "mod:<globals>"
    attr: str
    func: str                 # enclosing function key
    path: str
    line: int
    held: frozenset           # lock ids held lexically at the access
    kind: str                 # "write" | "rmw" | "mutate"
    compound: bool = False    # check-then-set shape
    in_init: bool = False     # inside the owner's __init__
    detail: str = ""          # e.g. the mutating call text


@dataclass
class ThreadModel:
    spawns: list = field(default_factory=list)          # [Spawn]
    #: synchronous project calls: (caller key, callee key, held locks)
    #: — lock ids from the SAME resolver as Access.held, so the
    #: guaranteed-lockset meet and the per-access locksets agree
    calls: list = field(default_factory=list)
    #: function key -> roles that can reach it (never empty after build)
    roles: dict = field(default_factory=dict)
    #: role names where >1 thread instance can exist at once
    multi_roles: set = field(default_factory=set)
    #: function key -> locks held on EVERY path into the function
    guarded: dict = field(default_factory=dict)
    accesses: list = field(default_factory=list)        # [Access]
    #: (owner, attr) -> container kind ("dict"|"list"|"set")
    containers: dict = field(default_factory=dict)
    #: "mod:Class" -> union of roles over the class's methods
    class_roles: dict = field(default_factory=dict)
    #: __init__ key -> (publish line, publish description)
    publishes: dict = field(default_factory=dict)
    #: function keys whose writes are construction/teardown-phase:
    #: lifecycle-named methods plus helpers reachable ONLY from them
    lifecycle: set = field(default_factory=set)

    def roles_of(self, key: str) -> frozenset:
        return self.roles.get(key, frozenset({"main"}))

    def effective_lockset(self, acc: Access) -> frozenset:
        return acc.held | self.guarded.get(acc.func, frozenset())

    def owner_roles(self, owner: str) -> frozenset:
        """Roles that can touch instances of ``owner``: its methods'
        roles plus the roles of every recorded external access."""
        out = set(self.class_roles.get(owner, ()))
        for a in self.accesses:
            if a.owner == owner:
                out |= self.roles_of(a.func)
        return frozenset(out)


# --------------------------------------------------------------------------
# helpers: project-class resolution for annotations / constructor calls
# --------------------------------------------------------------------------

def _class_key(expr: ast.expr, mi: ModuleInfo,
               project_classes: set) -> Optional[str]:
    """Map a constructor callee / annotation to 'mod:Class' when it
    names a class of this project."""
    if isinstance(expr, ast.Subscript):       # Optional[C] / list[C]
        return _class_key(expr.slice, mi, project_classes)
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        name = expr.value.strip("'\"")
        if name in mi.classes:
            key = f"{mi.name}:{name}"
            return key if key in project_classes else None
        tgt = mi.from_imports.get(name)
        if tgt:
            key = f"{tgt[0]}:{tgt[1]}"
            return key if key in project_classes else None
        return None
    if isinstance(expr, ast.Name):
        if expr.id in mi.classes:
            key = f"{mi.name}:{expr.id}"
            return key if key in project_classes else None
        tgt = mi.from_imports.get(expr.id)
        if tgt:
            key = f"{tgt[0]}:{tgt[1]}"
            return key if key in project_classes else None
        return None
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name):
        mod = mi.imports.get(expr.value.id)
        if mod:
            key = f"{mod}:{expr.attr}"
            return key if key in project_classes else None
    return None


def _threading_ctor(c: ast.Call, mi: ModuleInfo) -> Optional[str]:
    """'threading.Thread(...)' / 'Thread(...)' -> "Thread"|"Timer"."""
    fn = c.func
    name = None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and mi.imports.get(fn.value.id, fn.value.id) == "threading":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        tgt = mi.from_imports.get(fn.id)
        if tgt and tgt[0] == "threading":
            name = tgt[1]
    return name if name in ("Thread", "Timer") else None


# --------------------------------------------------------------------------
# per-function walker: spawns + shared-state accesses + publish points
# --------------------------------------------------------------------------

class _FuncWalker:
    def __init__(self, model: ThreadModel, proj: Project,
                 mi: ModuleInfo, ff, cls: Optional[ClassInfo],
                 env: dict, project_classes: set):
        self.model = model
        self.proj = proj
        self.mi = mi
        self.ff = ff            # dataflow.FlowFunc (has .node/.key/...)
        self.cls = cls
        self.cls_key = None
        if cls is not None:
            self.cls_key = f"{mi.name}:{cls.name}"
        self.env = env          # name -> "mod:Class"
        self.project_classes = project_classes
        self.held: list[str] = []
        self.loop_depth = 0
        self.globals_declared: set[str] = set()
        self.is_init = ff.name == "__init__" and ff.is_method
        #: local/self-attr names bound to a Thread/Timer in this body
        self.threadish: set[str] = set()
        #: locals freshly constructed here (``x = C(...)``) that have
        #: not yet escaped — writes to them are pre-publication
        self.fresh: set[str] = set()
        self.publish: Optional[tuple] = None   # (line, description)

    # -- entry ---------------------------------------------------------

    def run(self) -> None:
        for st in self.ff.node.body:
            self.stmt(st)

    # -- shared plumbing ----------------------------------------------

    def _record(self, owner: str, attr: str, line: int, kind: str,
                compound: bool = False, detail: str = "",
                via_self: bool = False,
                pre_pub: bool = False) -> None:
        self.model.accesses.append(Access(
            owner=owner, attr=attr, func=self.ff.key, path=self.ff.path,
            line=line, held=frozenset(self.held), kind=kind,
            compound=compound,
            in_init=(self.is_init and via_self) or pre_pub,
            detail=detail))

    def _owner_of(self, recv: ast.expr) -> tuple[Optional[str], bool]:
        """(owner class key, receiver-is-self) for an attribute
        receiver expression, or (None, False) when untypable."""
        if isinstance(recv, ast.Name):
            if recv.id == "self" and self.cls_key is not None:
                return self.cls_key, True
            owner = self.env.get(recv.id)
            return owner, False
        return None, False

    def _lock_ref(self, expr: ast.expr) -> Optional[str]:
        """Like model.resolve_lock_ref, plus typed receivers: the
        vacuum module's ``with vol._lock:`` (``vol`` a Volume param)
        must yield the SAME lock id as ``with self._lock:`` inside
        Volume methods, or the lockset intersection can never agree."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id != "self":
            ck = self.env.get(expr.value.id)
            if ck is not None and looks_locky(expr.attr):
                mod, cname = ck.split(":", 1)
                omi = self.proj.modules.get(mod)
                oci = omi.classes.get(cname) if omi else None
                if oci is not None:
                    d = oci.lock_defs.get(expr.attr)
                    if d is not None:
                        return d.alias_of or d.lock_id
                return f"{mod}.{cname}.{expr.attr}"
        return resolve_lock_ref(expr, self.mi, self.cls, self.ff.key)

    def _attr_target(self, t: ast.expr) -> Optional[tuple]:
        """(owner, attr, via_self, pre_pub) for an attribute store
        target; pre_pub marks writes to a local constructed in this
        function that has not yet escaped (``err = XError(...);
        err.code = ...`` before the raise)."""
        if isinstance(t, ast.Attribute):
            owner, via_self = self._owner_of(t.value)
            if owner is not None:
                pre_pub = isinstance(t.value, ast.Name) and \
                    t.value.id in self.fresh
                return owner, t.attr, via_self, pre_pub
        return None

    # -- statements ----------------------------------------------------

    def stmt(self, st) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # separate scope: walked as its own FlowFunc
        if isinstance(st, ast.Global):
            self.globals_declared |= set(st.names)
            return
        if isinstance(st, (ast.Assign, ast.AnnAssign)):
            value = st.value
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            for t in targets:
                self._store(t, value, st.lineno)
            if value is not None:
                self._expr(value)
            return
        if isinstance(st, ast.AugAssign):
            hit = self._attr_target(st.target)
            if hit is not None:
                owner, attr, via_self, pre_pub = hit
                self._record(owner, attr, st.lineno, "rmw",
                             via_self=via_self, pre_pub=pre_pub)
            elif isinstance(st.target, ast.Name) and \
                    st.target.id in self.globals_declared:
                self._record(f"{self.mi.name}:<globals>", st.target.id,
                             st.lineno, "rmw")
            elif isinstance(st.target, ast.Subscript):
                self._subscript_store(st.target, st.lineno)
            self._expr(st.value)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Subscript):
                    self._subscript_store(t, st.lineno, op="del")
            return
        if isinstance(st, ast.If):
            self._check_then_set(st)
            self._expr(st.test)
            for s in st.body:
                self.stmt(s)
            for s in st.orelse:
                self.stmt(s)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter)
            self.loop_depth += 1
            for s in st.body:
                self.stmt(s)
            self.loop_depth -= 1
            for s in st.orelse:
                self.stmt(s)
            return
        if isinstance(st, ast.While):
            self._expr(st.test)
            self.loop_depth += 1
            for s in st.body:
                self.stmt(s)
            self.loop_depth -= 1
            for s in st.orelse:
                self.stmt(s)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in st.items:
                lid = self._lock_ref(item.context_expr)
                if lid is not None:
                    acquired.append(lid)
                self._expr(item.context_expr)
            self.held.extend(acquired)
            for s in st.body:
                self.stmt(s)
            del self.held[len(self.held) - len(acquired):]
            return
        if isinstance(st, ast.Import) or isinstance(st, ast.ImportFrom):
            return
        if isinstance(st, ast.Try):
            for s in st.body:
                self.stmt(s)
            for h in st.handlers:
                for s in h.body:
                    self.stmt(s)
            for s in st.orelse:
                self.stmt(s)
            for s in st.finalbody:
                self.stmt(s)
            return
        if isinstance(st, ast.Expr):
            self._expr(st.value)
            return
        if isinstance(st, ast.Match):
            self._expr(st.subject)
            for case in st.cases:
                for s in case.body:
                    self.stmt(s)
            return
        if isinstance(st, (ast.Return, ast.Raise, ast.Assert)):
            for n in ast.iter_child_nodes(st):
                if isinstance(n, ast.expr):
                    self._expr(n)
            return
        # pass / break / continue / import: nothing to see

    def _store(self, t: ast.expr, value, line: int) -> None:
        hit = self._attr_target(t)
        if hit is not None:
            owner, attr, via_self, pre_pub = hit
            self._record(owner, attr, line, "write", via_self=via_self,
                         pre_pub=pre_pub)
            if via_self and self.is_init and value is not None:
                self._note_container(attr, value)
            if value is not None and isinstance(value, ast.Call) and \
                    _threading_ctor(value, self.mi) and via_self:
                self.threadish.add(f"self.{attr}")
            return
        if isinstance(t, ast.Name):
            if t.id in self.globals_declared:
                self._record(f"{self.mi.name}:<globals>", t.id, line,
                             "write")
            if value is not None:
                # local typing: x = SomeProjectClass(...) / Thread(...)
                ck = self._value_class(value)
                if ck is not None:
                    self.env[t.id] = ck
                    # fresh ONLY for a bare constructor call: the
                    # object cannot be shared until it escapes
                    if isinstance(value, ast.Call):
                        self.fresh.add(t.id)
                    else:
                        self.fresh.discard(t.id)
                else:
                    self.fresh.discard(t.id)
                if isinstance(value, ast.Call) and \
                        _threading_ctor(value, self.mi):
                    self.threadish.add(t.id)
            return
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._store(el, None, line)
            return
        if isinstance(t, ast.Subscript):
            self._subscript_store(t, line)

    def _value_class(self, value: ast.expr) -> Optional[str]:
        if isinstance(value, ast.Call):
            return _class_key(value.func, self.mi, self.project_classes)
        if isinstance(value, ast.BoolOp):   # st = stats or PipeStats()
            for v in value.values:
                ck = self._value_class(v)
                if ck is not None:
                    return ck
        return None

    def _note_container(self, attr: str, value: ast.expr) -> None:
        kind = None
        if isinstance(value, ast.Dict) or \
                isinstance(value, ast.DictComp):
            kind = "dict"
        elif isinstance(value, (ast.List, ast.ListComp)):
            kind = "list"
        elif isinstance(value, (ast.Set, ast.SetComp)):
            kind = "set"
        elif isinstance(value, ast.Call):
            leaf = _dotted(value.func).rsplit(".", 1)[-1]
            kind = _CONTAINER_CTORS.get(leaf)
        if kind is not None and self.cls_key is not None:
            self.model.containers[(self.cls_key, attr)] = kind

    def _subscript_store(self, t: ast.Subscript, line: int,
                         op: str = "[]=") -> None:
        if isinstance(t.value, ast.Attribute):
            owner, via_self = self._owner_of(t.value.value)
            if owner is not None:
                self._record(owner, t.value.attr, line, "mutate",
                             detail=op, via_self=via_self)

    def _check_then_set(self, st: ast.If) -> None:
        """``if self.x is None: self.x = ...`` — the compound
        check-then-set SW802 cares about."""
        read: set[tuple] = set()
        for n in ast.walk(st.test):
            if isinstance(n, ast.Attribute) and \
                    isinstance(n.ctx, ast.Load):
                owner, via_self = self._owner_of(n.value)
                if owner is not None:
                    read.add((owner, n.attr, via_self))
        if not read:
            return
        for s in st.body:
            if not isinstance(s, (ast.Assign, ast.AugAssign)):
                continue
            targets = s.targets if isinstance(s, ast.Assign) \
                else [s.target]
            for t in targets:
                hit = self._attr_target(t)
                if hit is None:
                    continue
                owner, attr, via_self, pre_pub = hit
                if (owner, attr, via_self) in read:
                    self._record(owner, attr, s.lineno, "write",
                                 compound=True, via_self=via_self,
                                 pre_pub=pre_pub)

    # -- expressions: spawns, mutating calls, publish points -----------

    def _expr(self, e: ast.expr) -> None:
        if self.fresh:
            # conservative escape: any further appearance of a fresh
            # local in an expression (call arg, raise, return value,
            # even a method call on it) ends its pre-publication window
            for n in ast.walk(e):
                if isinstance(n, ast.Name):
                    self.fresh.discard(n.id)
        for n in ast.walk(e):
            if isinstance(n, ast.Call):
                self._call(n)
            elif isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                # spawns inside a comprehension are multi-instance
                for inner in ast.walk(n):
                    if isinstance(inner, ast.Call):
                        self._call(inner, in_comp=True)

    def _call(self, c: ast.Call, in_comp: bool = False) -> None:
        fn = c.func
        ctor = _threading_ctor(c, self.mi)
        if ctor is not None:
            self._spawn_from_ctor(c, ctor, in_comp)
            return
        # the call itself runs synchronously on this thread — record
        # it with the locks held HERE for role + lockset propagation
        callee = self._resolve_target(fn)
        if callee is not None:
            self.model.calls.append(
                (self.ff.key, callee, frozenset(self.held)))
        if not isinstance(fn, ast.Attribute):
            return
        attr = fn.attr
        # chained threading.Thread(...).start()
        if attr == "start" and isinstance(fn.value, ast.Call) and \
                _threading_ctor(fn.value, self.mi):
            self._publish(c.lineno, "thread started")
            return
        if attr == "start":
            recv = _dotted(fn.value)
            if recv in self.threadish:
                self._publish(c.lineno, f"{recv}.start()")
            return
        if attr in ("put", "put_nowait", "append", "register"):
            if any(isinstance(a, ast.Name) and a.id == "self"
                   for a in c.args):
                self._publish(c.lineno, f"self handed to .{attr}()")
        if attr == "submit" and c.args:
            tkey = self._resolve_target(c.args[0])
            if tkey is not None:
                short = tkey.split(":")[-1]
                self.model.spawns.append(Spawn(
                    role=f"worker:{short}", target=tkey, line=c.lineno,
                    path=self.ff.path, func=self.ff.key, multi=True,
                    kind="submit"))
            return
        if attr in _MUTATORS:
            owner_expr = fn.value
            if isinstance(owner_expr, ast.Attribute):
                owner, via_self = self._owner_of(owner_expr.value)
                if owner is not None:
                    self._record(owner, owner_expr.attr, c.lineno,
                                 "mutate", detail=f".{attr}()",
                                 via_self=via_self)

    def _publish(self, line: int, desc: str) -> None:
        if self.is_init and self.publish is None:
            self.publish = (line, desc)
            self.model.publishes[self.ff.key] = self.publish

    def _spawn_from_ctor(self, c: ast.Call, ctor: str,
                         in_comp: bool) -> None:
        target = None
        name_lit = None
        if ctor == "Thread":
            for kw in c.keywords:
                if kw.arg == "target":
                    target = self._resolve_target(kw.value)
                elif kw.arg == "name" and \
                        isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, str):
                    name_lit = kw.value.value
        else:  # Timer(interval, fn)
            if len(c.args) >= 2:
                target = self._resolve_target(c.args[1])
            for kw in c.keywords:
                if kw.arg == "function":
                    target = self._resolve_target(kw.value)
        if target is None:
            return   # lambda / dynamic target: nothing to propagate to
        short = target.split(":")[-1]
        role = name_lit or (f"timer:{short}" if ctor == "Timer"
                            else f"thread:{short}")
        multi = in_comp or self.loop_depth > 0
        self.model.spawns.append(Spawn(
            role=role, target=target, line=c.lineno, path=self.ff.path,
            func=self.ff.key, multi=multi,
            kind="timer" if ctor == "Timer" else "thread"))

    def _resolve_target(self, expr: ast.expr) -> Optional[str]:
        ref = call_ref(expr, self.mi)
        if ref is None:
            return None
        if ref[0] == "unique":
            # the sole-method-of-that-name heuristic over-resolves
            # stdlib calls (handler.finish() is not the linter's
            # visitor) — a wrong edge here leaks a thread role into
            # an unrelated class, so roles only follow hard edges
            return None
        fi = self.proj.funcs.get(self.ff.key)
        if fi is None:
            return None
        return resolve_call(self.proj, self.mi, fi, ref)


# --------------------------------------------------------------------------
# model construction
# --------------------------------------------------------------------------

def _typing_envs(fp: FlowProject, project_classes: set) -> dict:
    """Per-function name->class env seeded from parameter annotations,
    inherited down nested-function chains (closures see the enclosing
    function's locals — the pipeline's ``reader``/``writer`` stage
    closures type ``st``/``controller`` this way)."""
    envs: dict[str, dict] = {}

    def env_for(key: str) -> dict:
        if key in envs:
            return envs[key]
        ff = fp.flows[key]
        base: dict = {}
        if ff.parent is not None and ff.parent in fp.flows:
            base.update(env_for(ff.parent))
        mi = fp.modules[ff.module]
        args = ff.node.args
        for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
            if a.annotation is not None:
                ck = _class_key(a.annotation, mi, project_classes)
                if ck is not None:
                    base[a.arg] = ck
        envs[key] = base
        return base

    for key in fp.flows:
        env_for(key)
    return envs


def build_thread_model(fp: FlowProject) -> ThreadModel:
    """Build the full model from an already-built FlowProject."""
    proj = fp.proj
    model = ThreadModel()
    project_classes = {
        f"{mi.name}:{cname}"
        for mi in fp.modules.values() for cname in mi.classes}
    envs = _typing_envs(fp, project_classes)

    # ---- pass 1: walk every function body ----
    for key, ff in fp.flows.items():
        mi = fp.modules[ff.module]
        cls = None
        tail = key.rsplit(":", 1)[1]
        if ff.is_method and "." in tail:
            cls = mi.classes.get(tail.split(".")[0])
        w = _FuncWalker(model, proj, mi, ff, cls,
                        dict(envs.get(key, {})), project_classes)
        w.run()

    # ---- pass 2: entry roles ----
    entries: dict[str, set] = {}
    for sp in model.spawns:
        if sp.target is None:
            continue
        entries.setdefault(sp.target, set()).add(sp.role)
        if sp.multi:
            model.multi_roles.add(sp.role)
    for key, ff in fp.flows.items():
        if not ff.is_method:
            continue
        if _VERB_RE.match(ff.name):
            # do_GET-style verb methods: ingress worker-pool dispatch
            entries.setdefault(key, set()).add("ingress")
            model.multi_roles.add("ingress")
            continue
        tail = key.rsplit(":", 1)[1]
        cname = tail.split(".")[0]
        if "Servicer" in cname and not ff.name.startswith("_"):
            # grpc servicer methods run on the server's worker threads
            entries.setdefault(key, set()).add("rpc")
            model.multi_roles.add("rpc")

    # ---- pass 3: role propagation over the resolved call graph ----
    # (call facts come from the walker — pass 1 — so held-lock ids
    # match the per-access ids exactly)
    calls: dict[str, list] = {}
    for caller, callee, held in model.calls:
        calls.setdefault(caller, []).append((callee, 0, held))
    callees_of = {k: [c for c, _l, _h in v] for k, v in calls.items()}
    called = {c for cs in callees_of.values() for c in cs}
    roles: dict[str, set] = {}
    for key in fp.flows:
        roles[key] = set(entries.get(key, ()))
        if key not in called and key not in entries:
            roles[key].add("main")
    for _ in range(_MAX_ROUNDS):
        changed = False
        for key, cs in callees_of.items():
            if key not in roles:
                continue
            src = roles[key]
            if not src:
                continue
            for c in cs:
                tgt = roles.setdefault(c, set())
                if not src <= tgt:
                    tgt |= src
                    changed = True
        if not changed:
            break
    for key in fp.flows:
        if not roles.get(key):
            roles[key] = {"main"}
    model.roles = {k: frozenset(v) for k, v in roles.items()}

    # ---- pass 4a: lifecycle closure ----
    # a private helper called ONLY from lifecycle methods (RaftNode
    # __init__ -> _load) runs in the same happens-before window; its
    # writes must not count as steady-state concurrency, and its
    # call sites must not weaken the guaranteed-lockset meet below.
    callers_of: dict[str, set] = {}
    for key, cs in callees_of.items():
        for c in cs:
            callers_of.setdefault(c, set()).add(key)
    lifecycle = {k for k in fp.flows
                 if _LIFECYCLE_RE.match(
                     k.rsplit(":", 1)[1].split(".")[-1])}
    for _ in range(_MAX_ROUNDS):
        grew = False
        for key in fp.flows:
            if key in lifecycle or key in entries:
                continue
            cs = callers_of.get(key)
            if cs and all(c in lifecycle for c in cs):
                lifecycle.add(key)
                grew = True
        if not grew:
            break
    model.lifecycle = lifecycle - set(entries)

    # ---- pass 4b: guaranteed locksets (meet over call sites) ----
    # entries and roots run lock-free; every other function holds
    # exactly the locks held on ALL resolved paths into it.
    guarded: dict[str, Optional[frozenset]] = {}
    for key in fp.flows:
        if key in entries or key not in called:
            guarded[key] = frozenset()
    for _ in range(_MAX_ROUNDS):
        changed = False
        for key, cs in calls.items():
            if key in model.lifecycle:
                continue   # happens-before callers don't constrain
            g = guarded.get(key)
            if g is None:
                continue
            for callee, _line, held in cs:
                if callee == key:
                    continue
                contrib = g | held
                cur = guarded.get(callee)
                new = contrib if cur is None else (cur & contrib)
                if new != cur:
                    guarded[callee] = new
                    changed = True
        if not changed:
            break
    model.guarded = {k: v for k, v in guarded.items() if v}

    # ---- pass 5: class roles ----
    for key in fp.flows:
        mod, tail = key.rsplit(":", 1)
        if "." in tail:
            ck = f"{mod}:{tail.split('.')[0]}"
            model.class_roles.setdefault(ck, set()).update(
                model.roles[key])
    return model


def steady_roles(model: ThreadModel, acc: Access) -> frozenset:
    """Roles that can perform ``acc`` during steady-state operation:
    the enclosing function's roles, minus nothing — unless the
    function is a lifecycle method (init/teardown) or a helper
    reachable only from one, whose accesses happen before publication
    or after quiesce."""
    if acc.in_init or acc.func in model.lifecycle:
        return frozenset()
    name = acc.func.rsplit(":", 1)[1].split(".")[-1]
    if _LIFECYCLE_RE.match(name):
        return frozenset()
    return model.roles_of(acc.func)
