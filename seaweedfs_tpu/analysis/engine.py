"""Analysis driver: walk files -> per-module models -> findings.

Pass an empty dict as ``timings`` to either analyze_* entry point to
get per-rule-family wall time back (the ``--stats`` report and the
lint_gate runtime budget both read it).
"""

from __future__ import annotations

import ast
import time
from pathlib import Path

from .buffer_rules import check_buffers
from .dataflow import build_flows
from .durability_rules import check_durability
from .findings import Finding, fingerprint_findings, is_suppressed
from .jax_rules import check_jax
from .local_rules import check_local
from .lockgraph import Project, analyze_locks
from .model import ModuleInfo, collect_module
from .net_rules import check_net
from .race_rules import check_races

#: Generated / vendored files the rules should not police.
_EXCLUDE_PARTS = {"__pycache__"}
_EXCLUDE_SUFFIXES = ("_pb2.py",)


def discover_files(paths: list[str], root: Path) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        path = (root / p) if not Path(p).is_absolute() else Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            files.append(path)
    out = []
    for f in files:
        if set(f.parts) & _EXCLUDE_PARTS:
            continue
        if f.name.endswith(_EXCLUDE_SUFFIXES):
            continue
        out.append(f)
    return out


def module_name_for(path: Path, root: Path) -> str:
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        return path.stem
    parts = list(rel.parts)
    parts[-1] = parts[-1][:-3]  # .py
    if parts[-1] == "__init__":
        parts = parts[:-1] or [path.parent.name]
    return ".".join(parts)


def analyze_sources(sources: dict[str, str],
                    module_names: dict[str, str] | None = None,
                    timings: dict[str, float] | None = None,
                    suppressed_out: list[Finding] | None = None
                    ) -> list[Finding]:
    """Analyze {repo-relative path: source text}. The unit the tests
    drive: no filesystem involved.

    ``suppressed_out``, when given, receives the findings an inline
    pragma silenced (the lint_gate summary table counts them).
    """
    t = timings if timings is not None else {}

    def timed(label, fn):
        t0 = time.perf_counter()
        out = fn()
        t[label] = t.get(label, 0.0) + (time.perf_counter() - t0)
        return out

    modules: dict[str, ModuleInfo] = {}
    findings: list[Finding] = []

    def parse():
        for path, src in sorted(sources.items()):
            name = (module_names or {}).get(path) or \
                path[:-3].replace("/", ".")
            try:
                modules[name] = collect_module(name, path, src)
            except SyntaxError as e:
                findings.append(Finding(
                    "SW001", "error", path, e.lineno or 1,
                    f"{name}:<module>", f"syntax error: {e.msg}"))

    timed("parse+model", parse)

    def local():
        out = []
        for mi in modules.values():
            out.extend(check_local(mi))
        return out

    findings.extend(timed("SW2xx-SW4xx local", local))

    proj = timed("callgraph", lambda: Project(modules))
    findings.extend(timed("SW1xx lockgraph",
                          lambda: analyze_locks(modules, proj)))
    fp = timed("dataflow fixpoint", lambda: build_flows(modules, proj))
    findings.extend(timed("SW5xx buffer", lambda: check_buffers(fp)))
    findings.extend(timed("SW6xx net", lambda: check_net(fp, sources)))
    findings.extend(timed("SW7xx jax", lambda: check_jax(modules)))
    findings.extend(timed("SW8xx races", lambda: check_races(fp)))
    findings.extend(timed("SW9xx durability",
                          lambda: check_durability(modules)))

    def finish():
        kept = []
        for f in findings:
            if is_suppressed(f, sources,
                             tuple(f.extra.get("anchors", ()))):
                if suppressed_out is not None:
                    suppressed_out.append(f)
            else:
                kept.append(f)
        fingerprint_findings(kept, sources)
        kept.sort(key=Finding.sort_key)
        return kept

    return timed("suppress+fingerprint", finish)


def analyze_paths(paths: list[str], root: Path,
                  timings: dict[str, float] | None = None,
                  suppressed_out: list[Finding] | None = None
                  ) -> list[Finding]:
    files = discover_files(paths, root)
    sources: dict[str, str] = {}
    names: dict[str, str] = {}
    for f in files:
        try:
            rel = str(f.resolve().relative_to(root.resolve()))
        except ValueError:
            rel = str(f)
        sources[rel] = f.read_text(encoding="utf-8",
                                   errors="replace")
        names[rel] = module_name_for(f, root)
    return analyze_sources(sources, names, timings,
                           suppressed_out=suppressed_out)


def parse_ok(source: str) -> bool:
    """Cheap helper for tests."""
    try:
        ast.parse(source)
        return True
    except SyntaxError:
        return False
