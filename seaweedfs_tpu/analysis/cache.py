"""Incremental result cache for seaweedlint runs.

Every analysis family in the engine is INTERPROCEDURAL — lock order,
buffer dataflow, and the SW8xx thread-role model all propagate facts
over the resolved call graph — so a finding in file A can appear or
vanish when only file B changes. A per-file cache that re-analyzed
changed files in isolation would therefore be unsound (it would miss
cross-file regressions, the worst kind to miss). The cache instead
keeps per-file keys — ``(repo-relative path, mtime_ns, size)`` — plus
a rules version (a hash of the analysis package's own sources, so
editing any rule module invalidates everything), and reuses the
stored run only when EVERY key matches and the file set is identical.
Any mismatch discards the whole entry and re-runs the full analysis.

That all-or-nothing validity rule still pays for the common CI/editor
loop — "nothing changed since the last run" — which drops a ~5 s
analysis to a few dozen ``stat()`` calls. Hit/miss counts stay per
file so ``--stats`` can show how close a run was to reuse.

The cache file lives at the repo root (``.seaweedlint_cache.json``,
gitignored) and is written atomically (tmp + ``os.replace``) so an
interrupted run can never leave a torn entry. ``--no-cache`` bypasses
both the probe and the store.
"""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path

from .engine import discover_files
from .findings import Finding

#: Bump when the on-disk entry layout changes.
CACHE_FORMAT = 1

#: Repo-root-relative cache file name (kept out of git).
DEFAULT_CACHE = ".seaweedlint_cache.json"


def rules_version() -> str:
    """Hash of the analyzer's OWN sources (every ``analysis/*.py``).

    Findings depend on the rules as much as on the analyzed files, so
    editing any rule module must invalidate every cached result.
    """
    h = hashlib.sha1()
    pkg = Path(__file__).resolve().parent
    for p in sorted(pkg.glob("*.py")):
        h.update(p.name.encode())
        h.update(p.read_bytes())
    return h.hexdigest()


def file_keys(paths: list[str], root: Path) -> dict[str, list[int]]:
    """``{repo-relative path: [mtime_ns, size]}`` for the exact file
    set the engine would analyze (same discovery walk, same excludes).
    """
    keys: dict[str, list[int]] = {}
    for f in discover_files(paths, root):
        try:
            rel = str(f.resolve().relative_to(root.resolve()))
        except ValueError:
            rel = str(f)
        # a file deleted mid-walk simply stays out of the key map —
        # the set mismatch forces a full re-run, which is the point
        try:
            st = f.stat()
        except OSError:  # seaweedlint: disable=SW301 — vanished file = cache miss by design
            continue
        keys[rel] = [st.st_mtime_ns, st.st_size]
    return keys


def _to_entry(f: Finding) -> dict:
    return {"rule": f.rule, "severity": f.severity, "path": f.path,
            "line": f.line, "qualname": f.qualname,
            "message": f.message, "fingerprint": f.fingerprint,
            "extra": f.extra}


def _from_entry(d: dict) -> Finding:
    return Finding(d["rule"], d["severity"], d["path"], d["line"],
                   d["qualname"], d["message"],
                   d.get("fingerprint", ""), dict(d.get("extra", {})))


def _jsonable(obj):
    # Finding.extra holds tuples/sets (anchor line numbers etc.);
    # their exact container type is irrelevant once suppression has
    # already run, so lists are a faithful-enough round trip.
    if isinstance(obj, (tuple, set, frozenset)):
        return sorted(obj) if isinstance(obj, (set, frozenset)) \
            else list(obj)
    return str(obj)


def load(cache_path: Path, version: str,
         keys: dict[str, list[int]]
         ) -> tuple[tuple[list[Finding], list[Finding]] | None,
                    int, int]:
    """Probe the cache against the current ``(version, keys)``.

    Returns ``(entry, hits, misses)`` where ``entry`` is
    ``(findings, suppressed)`` on a full hit and ``None`` otherwise;
    ``hits``/``misses`` count per-file key matches either way (a
    deleted file counts as a miss — the file SET must match too).
    """
    try:
        data = json.loads(cache_path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None, 0, len(keys)
    if data.get("cache_format") != CACHE_FORMAT or \
            data.get("rules_version") != version:
        return None, 0, len(keys)
    old = data.get("files", {})
    hits = sum(1 for p, k in keys.items() if old.get(p) == k)
    misses = (len(keys) - hits) + \
        sum(1 for p in old if p not in keys)
    if misses:
        return None, hits, misses
    try:
        findings = [_from_entry(d) for d in data.get("findings", [])]
        suppressed = [_from_entry(d)
                      for d in data.get("suppressed", [])]
    except (KeyError, TypeError):
        return None, 0, len(keys)
    return (findings, suppressed), hits, 0


def store(cache_path: Path, version: str, keys: dict[str, list[int]],
          findings: list[Finding], suppressed: list[Finding]) -> None:
    """Atomically persist a completed run. Best-effort: a read-only
    checkout just runs uncached."""
    data = {
        "cache_format": CACHE_FORMAT,
        "rules_version": version,
        "files": keys,
        "findings": [_to_entry(f) for f in findings],
        "suppressed": [_to_entry(f) for f in suppressed],
    }
    tmp = cache_path.with_name(cache_path.name + ".tmp")
    try:
        tmp.write_text(json.dumps(data, default=_jsonable),
                       encoding="utf-8")
        # seaweedlint: disable=SW901 — pure-speedup cache; losing it re-lints, fsync would slow every run
        os.replace(tmp, cache_path)
    except OSError:
        # cache writes are pure speedup — a read-only checkout or a
        # full disk must not fail the lint run itself
        try:
            tmp.unlink()
        except OSError:  # seaweedlint: disable=SW301 — best-effort tmp cleanup on a best-effort write
            pass
