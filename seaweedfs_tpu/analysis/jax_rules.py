"""SW7xx — JAX dispatch hazards (lexical pass).

The mesh/ops layers keep jitted step builders at module scope behind
caches (``_auto_steps``, ``functools.lru_cache``) precisely because a
``jax.jit``/``shard_map`` constructed inside a pipeline loop retraces
and recompiles every iteration. These rules police dispatch shape:

- SW701 (warning): ``jax.jit`` / ``pjit`` / ``shard_map`` invoked
  lexically inside a for/while loop or comprehension — a
  per-iteration retrace/recompile storm; hoist the jitted callable or
  cache it (parallel/mesh.py's ``_auto_steps`` pattern).
- SW702 (warning): ``jax.device_put`` inside a loop — per-batch H2D
  serializes transfer behind compute; use the pipeline's
  double-buffered prepare path or donation instead.
- SW703 (error): a call of a jitted function passes an unhashable
  literal (list/dict/set/comprehension) at a ``static_argnums``
  position (or a ``static_argnames`` keyword) — TypeError at trace
  time, or a silent cache miss per call if __eq__-abused.
- SW704 (warning): ``jax.device_put`` in a loop whose DATA argument is
  loop-invariant while the DEVICE argument tracks the loop variable —
  the per-device placement loop a sharded restore is tempted to write;
  ONE ``jax.device_put(x, NamedSharding(mesh, spec))`` (or
  ``make_array_from_callback``, ckpt/store.py) places every shard in
  one dispatch. When BOTH arguments depend on the loop variable the
  loop is a legitimate per-shard transfer of distinct blocks and
  neither SW702 nor SW704 fires.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .dataflow import _dotted
from .findings import Finding
from .model import ModuleInfo

_JIT_LEAVES = {"jit", "pjit"}
_SHARD_LEAVES = {"shard_map"}
_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
               ast.DictComp, ast.GeneratorExp)

_SHARD_NAME_RE = re.compile(r"^_?shard_map$")

_DEVICE_KWARGS = {"device", "sharding", "dst"}


def _names(node: Optional[ast.AST]) -> set[str]:
    """Every ``Name`` identifier referenced under ``node``."""
    if node is None:
        return set()
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _jax_call_kind(c: ast.Call, mi: ModuleInfo) -> Optional[str]:
    """-> 'jit' | 'shard_map' | 'device_put' | None."""
    d = _dotted(c.func)
    if not d:
        return None
    leaf = d.rsplit(".", 1)[-1]
    root = d.split(".")[0]
    root_mod = mi.imports.get(root, root)
    from_jax = root_mod.startswith("jax")
    if leaf in _JIT_LEAVES and (from_jax or d == leaf):
        src = mi.from_imports.get(leaf, ("", ""))[0]
        if "." in d or src.startswith("jax") or from_jax:
            return "jit"
    if (_SHARD_NAME_RE.match(leaf) or leaf in _SHARD_LEAVES) and (
            from_jax or "." not in d):
        src = mi.from_imports.get(d, ("", ""))[0]
        if "." in d and not from_jax:
            return None
        if "." in d or src.startswith("jax") or _SHARD_NAME_RE.match(d):
            return "shard_map"
    if leaf == "device_put" and (from_jax or d == leaf):
        return "device_put"
    return None


def _static_spec(c: ast.Call) -> tuple[tuple, tuple]:
    """-> (static positions, static names) parsed from literals."""
    nums: tuple = ()
    names: tuple = ()
    for kw in c.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = (v.value,)
            elif isinstance(v, ast.Tuple) and all(
                    isinstance(el, ast.Constant) for el in v.elts):
                nums = tuple(el.value for el in v.elts)
        elif kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names = (v.value,)
            elif isinstance(v, (ast.Tuple, ast.List)) and all(
                    isinstance(el, ast.Constant) for el in v.elts):
                names = tuple(el.value for el in v.elts)
    return nums, names


class _Scope(ast.NodeVisitor):
    """One function (or module) scope: loop depth + jit tracking."""

    def __init__(self, mi: ModuleInfo, path: str, qualname: str,
                 findings: list):
        self.mi = mi
        self.path = path
        self.qualname = qualname
        self.findings = findings
        self.loop_depth = 0
        #: one set of bound loop-target names per enclosing loop
        self.loop_vars: list[set[str]] = []
        #: name -> (static positions, static names, jit line)
        self.jitted: dict[str, tuple] = {}

    # -- nested scopes are walked separately --
    def visit_FunctionDef(self, node):  # noqa: N802
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _loop(self, node, parts, targets=frozenset()):
        self.loop_depth += 1
        self.loop_vars.append(set(targets))
        for name in parts:
            for ch in getattr(node, name, []) or []:
                self.visit(ch)
        self.loop_vars.pop()
        self.loop_depth -= 1

    def visit_For(self, node):  # noqa: N802
        self.visit(node.iter)
        self._loop(node, ("body",), _names(node.target))
        for ch in node.orelse:
            self.visit(ch)

    visit_AsyncFor = visit_For

    def visit_While(self, node):  # noqa: N802
        self.visit(node.test)
        self._loop(node, ("body",))
        for ch in node.orelse:
            self.visit(ch)

    def _comp(self, node):
        self.loop_depth += 1
        self.loop_vars.append(
            set().union(*(_names(g.target) for g in node.generators)))
        self.generic_visit(node)
        self.loop_vars.pop()
        self.loop_depth -= 1

    visit_ListComp = _comp
    visit_SetComp = _comp
    visit_DictComp = _comp
    visit_GeneratorExp = _comp

    def visit_Assign(self, node):  # noqa: N802
        if isinstance(node.value, ast.Call) and \
                _jax_call_kind(node.value, self.mi) == "jit":
            nums, names = _static_spec(node.value)
            if nums or names:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.jitted[t.id] = (nums, names,
                                             node.value.lineno)
        self.generic_visit(node)

    def visit_Call(self, node):  # noqa: N802
        kind = _jax_call_kind(node, self.mi)
        if kind in ("jit", "shard_map") and self.loop_depth > 0:
            fn = "jax.jit" if kind == "jit" else "shard_map"
            self.findings.append(Finding(
                "SW701", "warning", self.path, node.lineno,
                self.qualname,
                f"{fn}(...) constructed inside a loop — retraces and "
                f"recompiles every iteration (recompile storm); hoist "
                f"it or cache the jitted callable (see "
                f"parallel/mesh.py _auto_steps)"))
        elif kind == "device_put" and self.loop_depth > 0:
            self._check_device_put(node)
        if kind == "jit":
            self._check_inline_static(node)
        self._check_jitted_call(node)
        self.generic_visit(node)

    def _check_device_put(self, node: ast.Call):
        bound = set().union(*self.loop_vars) if self.loop_vars \
            else set()
        data = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "x"), None)
        dev = node.args[1] if len(node.args) > 1 else next(
            (kw.value for kw in node.keywords
             if kw.arg in _DEVICE_KWARGS), None)
        data_dep = bool(_names(data) & bound)
        dev_dep = bool(_names(dev) & bound)
        if dev_dep and not data_dep:
            self.findings.append(Finding(
                "SW704", "warning", self.path, node.lineno,
                self.qualname,
                "jax.device_put of a loop-invariant array onto a "
                "per-iteration device — one device_put with a "
                "NamedSharding (or make_array_from_callback, see "
                "ckpt/store.py restore) places every shard in a "
                "single dispatch"))
        elif dev_dep and data_dep:
            # distinct data onto distinct devices each iteration: a
            # legitimate per-shard transfer, not a dispatch hazard
            return
        else:
            self.findings.append(Finding(
                "SW702", "warning", self.path, node.lineno,
                self.qualname,
                "jax.device_put inside a loop serializes per-batch "
                "H2D behind compute — use the double-buffered "
                "prepare path (pipeline double_buffer) or donation "
                "instead of a fresh transfer per iteration"))

    def _flag_703(self, line, what):
        self.findings.append(Finding(
            "SW703", "error", self.path, line, self.qualname,
            f"unhashable argument ({what}) passed at a static_argnums/"
            f"static_argnames position of a jitted function — static "
            f"args must be hashable (TypeError at trace time)"))

    def _check_static_args(self, call: ast.Call, nums, names):
        for i in nums:
            if isinstance(i, int) and 0 <= i < len(call.args) and \
                    isinstance(call.args[i], _UNHASHABLE):
                self._flag_703(call.args[i].lineno,
                               f"positional arg {i}")
        for kw in call.keywords:
            if kw.arg in names and isinstance(kw.value, _UNHASHABLE):
                self._flag_703(kw.value.lineno, f"keyword {kw.arg!r}")

    def _check_inline_static(self, jit_call: ast.Call):
        # jax.jit(f, static_argnums=...)([...]) — direct dispatch;
        # the parent Call tagged the jit call before traversal reached
        # it, so this fires exactly once
        parent = getattr(jit_call, "_sw_parent_call", None)
        if parent is not None:
            nums, names = _static_spec(jit_call)
            if nums or names:
                self._check_static_args(parent, nums, names)

    def _check_jitted_call(self, node: ast.Call):
        if isinstance(node.func, ast.Name) and \
                node.func.id in self.jitted:
            nums, names, _ = self.jitted[node.func.id]
            self._check_static_args(node, nums, names)
        if isinstance(node.func, ast.Call):
            # generic_visit will reach node.func exactly once
            node.func._sw_parent_call = node


def check_jax(modules: dict[str, ModuleInfo]) -> list[Finding]:
    findings: list[Finding] = []
    for mi in modules.values():
        scopes: list[tuple] = [(mi.tree, f"{mi.name}:<module>")]

        def walk(n, cls):
            for ch in ast.iter_child_nodes(n):
                if isinstance(ch, ast.ClassDef):
                    walk(ch, cls if cls is not None else ch.name)
                elif isinstance(ch, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    qual = (f"{mi.name}:{cls}.{ch.name}" if cls
                            else f"{mi.name}:{ch.name}")
                    scopes.append((ch, qual))
                    walk(ch, cls)
                else:
                    walk(ch, cls)

        walk(mi.tree, None)
        for node, qual in scopes:
            sc = _Scope(mi, mi.path, qual, findings)
            for st in node.body:
                sc.visit(st)
    return findings
