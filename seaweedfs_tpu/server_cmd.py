"""``weed server`` — master + volume server (+ filer) in one process.

Mirrors weed/command/server.go: the common single-node deployment shape,
wiring the same components the standalone commands run, sharing one
process and one config. Also the quickest way to a working cluster:

    python -m seaweedfs_tpu server -dir /data -filer
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .util import config as config_mod
from .util import tls as tls_mod
from .util import glog


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import signal

    p = argparse.ArgumentParser(prog="server")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-master.port", dest="master_port", type=int,
                   default=9333)
    p.add_argument("-volume.port", dest="volume_port", type=int,
                   default=8080)
    p.add_argument("-filer.port", dest="filer_port", type=int,
                   default=8888)
    p.add_argument("-dir", action="append", required=True,
                   help="volume data directory (repeatable)")
    p.add_argument("-volume.max", dest="volume_max", type=int, default=8)
    p.add_argument("-filer", action="store_true",
                   help="also run a filer")
    p.add_argument("-filer.db", dest="filer_db", default="")
    p.add_argument("-master.peers", dest="peers", default="",
                   help="comma-separated master urls for HA")
    p.add_argument("-mdir", default="",
                   help="master meta dir (raft state + sequence)")
    p.add_argument("-dataCenter", default="")
    p.add_argument("-rack", default="")
    p.add_argument("-volume.index", dest="vol_index", default="memory",
                   choices=["memory", "native", "sqlite"])
    p.add_argument("-pulseSeconds", type=float, default=5.0)
    p.add_argument("-config", default="")
    args = p.parse_args(argv)

    conf = config_mod.load(args.config) if args.config else {}
    secret = config_mod.lookup(conf, "jwt.signing.key", "")
    tls_mod.install_from_config(conf)
    from .util import durability as durability_mod
    from .util import faults as faults_mod
    from .util import profiler, retry, tracing
    tracing.configure_from(conf)
    retry.configure_from(conf)
    faults_mod.configure_from(conf)
    durability_mod.configure_from(conf)
    from .storage import scrubber as scrubber_mod
    scrubber_mod.configure_from(conf)
    profiler.configure_from(conf)
    profiler.ensure_started()

    from .cluster.master import MasterServer
    from .cluster.volume_server import VolumeServer
    from .storage.store import Store

    master = MasterServer(
        ip=args.ip, port=args.master_port, secret=secret,
        pulse_seconds=args.pulseSeconds,
        peers=[x for x in args.peers.split(",") if x],
        meta_dir=args.mdir or None,
        trace_ring_size=int(config_mod.lookup(
            conf, "tracing.collector_ring_size", 256)))
    if config_mod.lookup(conf, "slo") is not None:
        master.slo.configure(conf)
    master.start()
    store = Store(args.dir, max_volumes=args.volume_max,
                  needle_map=args.vol_index)
    store.load_existing()
    volume = VolumeServer(
        store, ip=args.ip, port=args.volume_port,
        master_url=args.peers or master.url, secret=secret,
        data_center=args.dataCenter, rack=args.rack,
        pulse_seconds=args.pulseSeconds).start()
    filer = None
    if args.filer:
        from .cluster.filer_server import FilerServer
        from .filer import Filer
        from .filer.stores import MemoryStore, SqliteStore
        fstore = SqliteStore(args.filer_db) if args.filer_db \
            else MemoryStore()
        filer = FilerServer(Filer(fstore), ip=args.ip,
                            port=args.filer_port,
                            master_url=master.url).start()
    glog.info("server up: master %s volume %s%s", master.url,
              volume.url, f" filer {filer.url}" if filer else "")

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    stop.wait()
    if filer:
        filer.stop()
    volume.stop()
    master.stop()
    return 0


def run_compact(argv: Optional[list[str]] = None) -> int:
    """``weed compact`` — offline volume compaction
    (weed/command/compact.go): run the two-phase vacuum on a volume
    that is not being served."""
    import argparse
    from pathlib import Path

    from .storage import vacuum as vacuum_mod
    from .storage.store import volume_base_name
    from .storage.volume import Volume, dat_path

    p = argparse.ArgumentParser(prog="compact")
    p.add_argument("-dir", required=True)
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    args = p.parse_args(argv)
    base = Path(args.dir) / volume_base_name(args.volumeId,
                                             args.collection)
    if not dat_path(base).exists():
        print(f"compact: {dat_path(base)} not found")
        return 1
    before = dat_path(base).stat().st_size
    vol = Volume(base, args.volumeId).load()
    try:
        state = vacuum_mod.compact(vol)
        after = vacuum_mod.commit_compact(vol, state)
    finally:
        vol.close()
    print(f"compact: volume {args.volumeId}: {before} -> {after} bytes "
          f"({(1 - after / max(before, 1)) * 100:.0f}% reclaimed)")
    return 0
