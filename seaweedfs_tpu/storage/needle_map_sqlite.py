"""Disk-backed needle map (needle_map_leveldb.go analog, sqlite here).

The in-RAM CompactMap costs ~100 B/needle of Python-object overhead; a
billion-needle volume cannot load it. This map keeps key -> (offset,
size) in a sqlite table next to the volume (``<base>.sdx``) and replays
only the .idx TAIL beyond a persisted watermark on load — the property
that makes huge volumes reloadable in O(new entries) instead of O(all).

The watermark carries a fingerprint of the .idx head so a REPLACED
index (vacuum commit renames a fresh .cpx over it) is detected and the
map rebuilt rather than corrupted by replaying unrelated bytes.

Same surface as idx.CompactMap (set/get/delete/len/live_entries +
file_count/deleted_count/deleted_bytes/max_key counters), so Volume
treats the two interchangeably.
"""

from __future__ import annotations

import sqlite3
from pathlib import Path
from typing import Iterator, Optional

from .idx import IndexEntry, walk_index_blob
from .types import NEEDLE_MAP_ENTRY_SIZE, TOMBSTONE_FILE_SIZE

_SCHEMA = """
CREATE TABLE IF NOT EXISTS needles (
    key INTEGER PRIMARY KEY,
    offset_units INTEGER NOT NULL,
    size INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS meta (
    k TEXT PRIMARY KEY,
    v BLOB
);
"""


#: Mutations per durable checkpoint (counters + watermark + commit).
CHECKPOINT_EVERY = 4096


class SqliteNeedleMap:
    def __init__(self, db_path: str | Path, generation: int = 0):
        self.db_path = str(db_path)
        #: Index generation — the volume's superblock compact_revision.
        #: Vacuum commit replaces the whole .idx and bumps the revision,
        #: so a stored generation mismatch proves the map describes a
        #: dead index and must be rebuilt. (A content fingerprint is
        #: NOT sufficient: compaction usually preserves the first index
        #: entry byte-for-byte.)
        self.generation = generation
        try:
            self._db = self._connect()
        except sqlite3.DatabaseError:
            # A torn database is disposable — the .idx journal is the
            # durability source of truth; drop and rebuild.
            Path(self.db_path).unlink(missing_ok=True)
            self._db = self._connect()
        self.file_count = int(self._meta("file_count") or 0)
        self.deleted_count = int(self._meta("deleted_count") or 0)
        self.deleted_bytes = int(self._meta("deleted_bytes") or 0)
        self.max_key = int(self._meta("max_key") or 0)
        self.max_offset_units = int(self._meta("max_offset_units") or 0)
        #: Bytes of .idx this map's state reflects. Mutations advance it
        #: in lockstep (Volume journals exactly one entry per set/
        #: delete) and it is committed ATOMICALLY with the data at each
        #: checkpoint, so after any crash the replay point exactly
        #: matches the persisted table state.
        self._applied_bytes = int(self._meta("idx_watermark") or 0)
        self._dirty = 0

    def _connect(self) -> sqlite3.Connection:
        db = sqlite3.connect(self.db_path, check_same_thread=False)
        db.executescript(_SCHEMA)
        db.commit()
        # fsync per checkpoint, not per statement; one open write
        # transaction accumulates mutations between checkpoints.
        db.execute("PRAGMA synchronous=OFF")
        return db

    # ------------- meta helpers -------------

    def _meta(self, k: str) -> Optional[bytes]:
        row = self._db.execute("SELECT v FROM meta WHERE k=?",
                               (k,)).fetchone()
        return row[0] if row else None

    def _set_meta(self, k: str, v) -> None:
        self._db.execute(
            "INSERT INTO meta(k, v) VALUES(?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v=excluded.v", (k, v))

    def _save_counters(self) -> None:
        for k in ("file_count", "deleted_count", "deleted_bytes",
                  "max_key", "max_offset_units"):
            self._set_meta(k, getattr(self, k))

    def _checkpoint(self) -> None:
        self._save_counters()
        self._set_meta("idx_watermark", self._applied_bytes)
        self._set_meta("idx_generation", self.generation)
        self._db.commit()
        self._dirty = 0

    def _mutated(self) -> None:
        self._applied_bytes += NEEDLE_MAP_ENTRY_SIZE
        self._dirty += 1
        if self._dirty >= CHECKPOINT_EVERY:
            self._checkpoint()

    # ------------- CompactMap surface -------------

    def set(self, key: int, offset_units: int, size: int) -> None:
        row = self._db.execute(
            "SELECT size FROM needles WHERE key=?", (key,)).fetchone()
        if row is not None and row[0] != TOMBSTONE_FILE_SIZE:
            self.deleted_count += 1
            self.deleted_bytes += row[0]
        self._db.execute(
            "INSERT INTO needles(key, offset_units, size) VALUES(?,?,?) "
            "ON CONFLICT(key) DO UPDATE SET "
            "offset_units=excluded.offset_units, size=excluded.size",
            (key, offset_units, size))
        self.file_count += 1
        self.max_key = max(self.max_key, key)
        self.max_offset_units = max(self.max_offset_units, offset_units)
        self._mutated()

    def delete(self, key: int) -> bool:
        row = self._db.execute(
            "SELECT offset_units, size FROM needles WHERE key=?",
            (key,)).fetchone()
        if row is None or row[1] == TOMBSTONE_FILE_SIZE:
            return False
        self.deleted_count += 1
        self.deleted_bytes += row[1]
        self._db.execute(
            "UPDATE needles SET size=? WHERE key=?",
            (TOMBSTONE_FILE_SIZE, key))
        self._mutated()
        return True

    def get(self, key: int) -> Optional[IndexEntry]:
        row = self._db.execute(
            "SELECT offset_units, size FROM needles WHERE key=?",
            (key,)).fetchone()
        if row is None or row[1] == TOMBSTONE_FILE_SIZE:
            return None
        return IndexEntry(key, row[0], row[1])

    def __len__(self) -> int:
        return self._db.execute(
            "SELECT COUNT(*) FROM needles WHERE size != ?",
            (TOMBSTONE_FILE_SIZE,)).fetchone()[0]

    def items(self) -> Iterator[IndexEntry]:
        for key, off, size in self._db.execute(
                "SELECT key, offset_units, size FROM needles"):
            yield IndexEntry(key, off, size)

    def live_entries(self) -> list[IndexEntry]:
        return [IndexEntry(k, o, s) for k, o, s in self._db.execute(
            "SELECT key, offset_units, size FROM needles "
            "WHERE size != ? ORDER BY key", (TOMBSTONE_FILE_SIZE,))]

    def close(self) -> None:
        self._checkpoint()
        self._db.close()

    # ------------- idx replay with watermark -------------

    @classmethod
    def load_from_idx(cls, db_path: str | Path, idx_path: str | Path,
                      generation: int = 0) -> "SqliteNeedleMap":
        m = cls(db_path, generation=generation)
        ip = Path(idx_path)
        blob = ip.read_bytes() if ip.exists() else b""
        usable = len(blob) - len(blob) % NEEDLE_MAP_ENTRY_SIZE
        blob = blob[:usable]
        mark = m._applied_bytes
        stored_gen = int(m._meta("idx_generation") or 0)
        if mark > len(blob) or stored_gen != generation:
            # .idx shrank, or was wholly replaced by a vacuum commit
            # (compact_revision moved): the stored map describes a dead
            # file — rebuild from scratch.
            m._db.execute("DELETE FROM needles")
            m.file_count = m.deleted_count = m.deleted_bytes = 0
            m.max_key = m.max_offset_units = 0
            mark = 0
        m._applied_bytes = mark
        for e in walk_index_blob(blob[mark:]):
            if e.is_deleted:
                m.delete(e.key)
            else:
                m.set(e.key, e.offset_units, e.size)
        m._applied_bytes = len(blob)
        m._checkpoint()
        return m
