"""CRC32-C (Castagnoli) needle checksums.

The reference checksums needle data with ``crc32.MakeTable(crc32.
Castagnoli)`` (weed/storage/needle/crc.go; SURVEY.md §2 "Needle codec").
Python's zlib only exposes the IEEE polynomial, so this is a table-driven
CRC32-C with two paths:

- the classic byte loop (:func:`crc32c_slow`) and a slice-by-8 variant,
  bit-exact references and the cheapest choice for small records;
- a vectorized bulk path for large payloads, exploiting that CRC is
  linear over GF(2): the buffer is cut into 64-byte blocks whose raw
  CRC states are advanced **in lockstep across all blocks** with numpy
  table gathers (64 vector steps regardless of length), then combined
  pairwise in a logarithmic fold using precomputed "advance through
  2^k zero bytes" operators. ~1000x fewer Python iterations per MiB
  than slice-by-8 — the difference between a scrub pass that hogs the
  GIL and one the RatePacer actually bounds (storage/scrubber.py).
"""

from __future__ import annotations

import functools

import numpy as np

#: Castagnoli polynomial, reversed representation.
POLY = 0x82F63B78


@functools.lru_cache(maxsize=1)
def _tables() -> np.ndarray:
    """Slice-by-8 tables: t[0] is the classic byte table; t[j] advances a
    byte through j additional zero bytes."""
    t = np.zeros((8, 256), dtype=np.uint32)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (POLY if crc & 1 else 0)
        t[0, i] = crc
    for j in range(1, 8):
        for i in range(256):
            t[j, i] = (t[j - 1, i] >> 8) ^ t[0, t[j - 1, i] & 0xFF]
    return t


def crc32c_slow(data: bytes, crc: int = 0) -> int:
    """Bit-exact reference: one byte at a time."""
    table = _tables()[0]
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ int(table[(crc ^ b) & 0xFF])
    return crc ^ 0xFFFFFFFF


#: Below this many bytes the slice-by-8 loop beats the bulk path's
#: fixed vector-setup cost.
_BULK_THRESHOLD = 1024
#: Bulk-path block width in bytes (2**_W_LOG2).
_W_LOG2 = 6
_W = 1 << _W_LOG2


def _slice8(buf: np.ndarray, crc: int) -> int:
    """Slice-by-8: same result as the byte loop, ~8x fewer Python
    iterations. ``crc`` is the raw (pre-inverted) running state."""
    t = _tables()
    n8 = buf.size // 8
    if n8:
        words = buf[:n8 * 8].reshape(n8, 8)
        for row in range(n8):
            w = words[row]
            c0 = crc ^ (int(w[0]) | (int(w[1]) << 8)
                        | (int(w[2]) << 16) | (int(w[3]) << 24))
            crc = (int(t[7, c0 & 0xFF]) ^ int(t[6, (c0 >> 8) & 0xFF])
                   ^ int(t[5, (c0 >> 16) & 0xFF]) ^ int(t[4, c0 >> 24])
                   ^ int(t[3, w[4]]) ^ int(t[2, w[5]])
                   ^ int(t[1, w[6]]) ^ int(t[0, w[7]]))
    for b in buf[n8 * 8:]:
        crc = (crc >> 8) ^ int(t[0, (crc ^ int(b)) & 0xFF])
    return crc


# -- bulk path: linear-operator tables --------------------------------
#
# Advancing a raw CRC state through zero bytes is linear over GF(2), so
# "advance through d zeros" is a 32x32 bit matrix — represented here,
# like the CRC table itself, as 4x256 lookup tables (one per state
# byte) applied with XORed gathers. The CRC table is linear in its
# index (T[a^b] = T[a]^T[b]), so the byte-step recurrence
# s' = (s>>8) ^ T[(s^b)&0xFF] splits into a state part (the operator
# below) and a data part — which is what lets per-block states be
# computed independently and folded afterwards.


def _op_apply(op: np.ndarray, s: np.ndarray) -> np.ndarray:
    return (op[0][s & np.uint32(0xFF)]
            ^ op[1][(s >> np.uint32(8)) & np.uint32(0xFF)]
            ^ op[2][(s >> np.uint32(16)) & np.uint32(0xFF)]
            ^ op[3][s >> np.uint32(24)])


@functools.lru_cache(maxsize=1)
def _z_powers() -> np.ndarray:
    """``[k]`` advances a raw state through ``2**k`` zero bytes
    (4x256 tables each); built once by operator squaring."""
    t0 = _tables()[0]
    z1 = np.zeros((4, 256), dtype=np.uint32)
    for j in range(4):
        vals = (np.arange(256, dtype=np.uint64) << (8 * j)) \
            .astype(np.uint32)
        z1[j] = (vals >> np.uint32(8)) ^ t0[vals & np.uint32(0xFF)]
    ops = [z1]
    for _ in range(31):
        prev = ops[-1]
        ops.append(np.stack([_op_apply(prev, prev[j])
                             for j in range(4)]))
    return np.stack(ops)


def _advance_zeros(state: int, d: int) -> int:
    """Raw state after ``d`` zero bytes."""
    ops, k = _z_powers(), 0
    while d:
        if d & 1:
            op = ops[k]
            state = int(op[0][state & 0xFF]
                        ^ op[1][(state >> 8) & 0xFF]
                        ^ op[2][(state >> 16) & 0xFF]
                        ^ op[3][state >> 24])
        d >>= 1
        k += 1
    return state


def _bulk(buf: np.ndarray, crc: int) -> int:
    """Vectorized bulk CRC: per-block raw states in lockstep across
    all 64-byte blocks, then a logarithmic pairwise fold. ``crc`` is
    the raw running state; returns the raw state after ``buf``."""
    n = buf.size
    n_blocks = -(-n // _W)
    pow2 = 1 << (n_blocks - 1).bit_length()
    # front-pad to a power-of-two block count: leading zero blocks
    # contribute zero raw state and fold away for free
    padded = np.concatenate(
        [np.zeros(pow2 * _W - n, dtype=np.uint8), buf])
    blocks = padded.reshape(pow2, _W)
    # vectorized slice-by-8 across ALL blocks in lockstep: 8 steps of
    # table gathers regardless of length, with the low state word
    # folded straight from a uint32 view of the data
    t = _tables()
    words = blocks.view(np.uint32) if np.little_endian else None
    states = np.zeros(pow2, dtype=np.uint32)
    ff = np.uint32(0xFF)
    for g in range(_W // 8):
        if words is not None:
            c0 = states ^ words[:, 2 * g]
        else:
            b = blocks[:, 8 * g:8 * g + 4].astype(np.uint32)
            c0 = states ^ (b[:, 0] | (b[:, 1] << np.uint32(8))
                           | (b[:, 2] << np.uint32(16))
                           | (b[:, 3] << np.uint32(24)))
        states = (t[7][c0 & ff] ^ t[6][(c0 >> np.uint32(8)) & ff]
                  ^ t[5][(c0 >> np.uint32(16)) & ff]
                  ^ t[4][c0 >> np.uint32(24)]
                  ^ t[3][blocks[:, 8 * g + 4]]
                  ^ t[2][blocks[:, 8 * g + 5]]
                  ^ t[1][blocks[:, 8 * g + 6]]
                  ^ t[0][blocks[:, 8 * g + 7]])
    ops, k = _z_powers(), _W_LOG2
    while states.size > 1:
        # crc(A||B) = Z^len(B)(crc_raw(A)) ^ crc_raw(B)
        states = _op_apply(ops[k], states[0::2]) ^ states[1::2]
        k += 1
    # the init state rides ahead of the data through all n bytes
    return _advance_zeros(crc, n) ^ int(states[0])


def crc32c(data: bytes | np.ndarray, crc: int = 0) -> int:
    """CRC32-C, bit-exact with the byte loop at any size: slice-by-8
    for small records, the vectorized fold for bulk payloads (needle
    bodies, scrub passes)."""
    buf = np.frombuffer(data, dtype=np.uint8) \
        if not isinstance(data, np.ndarray) else data.astype(np.uint8)
    crc ^= 0xFFFFFFFF
    if buf.size >= _BULK_THRESHOLD:
        crc = _bulk(buf, crc)
    else:
        crc = _slice8(buf, crc)
    return crc ^ 0xFFFFFFFF
