"""CRC32-C (Castagnoli) needle checksums.

The reference checksums needle data with ``crc32.MakeTable(crc32.
Castagnoli)`` (weed/storage/needle/crc.go; SURVEY.md §2 "Needle codec").
Python's zlib only exposes the IEEE polynomial, so this is a table-driven
CRC32-C: a slice-by-8 numpy implementation for bulk data (the tables are
applied with vectorized gathers host-side) with the classic byte loop as
the reference path for tests.
"""

from __future__ import annotations

import functools

import numpy as np

#: Castagnoli polynomial, reversed representation.
POLY = 0x82F63B78


@functools.lru_cache(maxsize=1)
def _tables() -> np.ndarray:
    """Slice-by-8 tables: t[0] is the classic byte table; t[j] advances a
    byte through j additional zero bytes."""
    t = np.zeros((8, 256), dtype=np.uint32)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (POLY if crc & 1 else 0)
        t[0, i] = crc
    for j in range(1, 8):
        for i in range(256):
            t[j, i] = (t[j - 1, i] >> 8) ^ t[0, t[j - 1, i] & 0xFF]
    return t


def crc32c_slow(data: bytes, crc: int = 0) -> int:
    """Bit-exact reference: one byte at a time."""
    table = _tables()[0]
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ int(table[(crc ^ b) & 0xFF])
    return crc ^ 0xFFFFFFFF


def crc32c(data: bytes | np.ndarray, crc: int = 0) -> int:
    """Slice-by-8 CRC32-C — same result as the byte loop, ~8x fewer Python
    iterations. Correctness path; the native module (seaweedfs_tpu/native)
    supplies the fast bulk implementation."""
    buf = np.frombuffer(data, dtype=np.uint8) \
        if not isinstance(data, np.ndarray) else data.astype(np.uint8)
    t = _tables()
    crc ^= 0xFFFFFFFF
    n8 = buf.size // 8
    if n8:
        words = buf[:n8 * 8].reshape(n8, 8)
        for row in range(n8):
            w = words[row]
            c0 = crc ^ (int(w[0]) | (int(w[1]) << 8)
                        | (int(w[2]) << 16) | (int(w[3]) << 24))
            crc = (int(t[7, c0 & 0xFF]) ^ int(t[6, (c0 >> 8) & 0xFF])
                   ^ int(t[5, (c0 >> 16) & 0xFF]) ^ int(t[4, c0 >> 24])
                   ^ int(t[3, w[4]]) ^ int(t[2, w[5]])
                   ^ int(t[1, w[6]]) ^ int(t[0, w[7]]))
    for b in buf[n8 * 8:]:
        crc = (crc >> 8) ^ int(t[0, (crc ^ int(b)) & 0xFF])
    return crc ^ 0xFFFFFFFF
