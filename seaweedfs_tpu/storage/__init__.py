"""On-disk formats: needles, volumes, indexes, EC shards.

Bit-compatible with the reference's weed/storage layouts (SURVEY.md §2, §5);
these files are the interop surface with real SeaweedFS clusters. The
reference mount was empty at survey time, so layouts follow the surveyed
upstream formats — every module docstring records exactly which file it
mirrors.
"""
