r"""Interval math: map a (.dat offset, size) to EC shard intervals.

Mirrors weed/storage/erasure_coding/ec_locate.go (SURVEY.md §2 "EC interval
math", §5 long-context note): a sealed volume is striped row-major across
the k data shards — first in LARGE blocks (1 GiB) while more than one full
large row of data remains, then in SMALL blocks (1 MiB) for the tail (the
last small row zero-padded). Any byte range of the logical .dat maps
deterministically to a list of (shard id, offset inside that shard, size)
intervals; this is the sequence-sharding analog and must stay bit-identical
for shard files to interoperate.

Layout (k = DataShardsCount):

    dat offset axis:  [L0 L1 ... L(k-1)] [L0' ...] ... | [S0 S1 ... S(k-1)] ...
                       \---- large row ----/              \---- small row ---/
    shard s file:     [row0 Ls] [row1 Ls'] ... | [small blocks of s] ...

Shard-local offset of large row r = r * large. Shard-local offset of small
row q = large_rows * large + q * small.
"""

from __future__ import annotations

from dataclasses import dataclass

DATA_SHARDS_COUNT = 10
PARITY_SHARDS_COUNT = 4
TOTAL_SHARDS_COUNT = DATA_SHARDS_COUNT + PARITY_SHARDS_COUNT

LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1 GiB
SMALL_BLOCK_SIZE = 1024 * 1024         # 1 MiB


@dataclass(frozen=True)
class Interval:
    """One contiguous piece of a needle inside one data shard."""

    shard_id: int          # data shard 0..k-1
    inner_block_offset: int  # byte offset inside the shard FILE
    size: int
    is_large_block: bool
    block_index: int       # row index within the large or small region


def large_rows_count(dat_size: int, k: int = DATA_SHARDS_COUNT,
                     large: int = LARGE_BLOCK_SIZE) -> int:
    """Number of full large rows. Matches the reference's processing loop,
    which consumes large rows while MORE than one full row remains (an
    exactly-one-row file is encoded entirely in small blocks)."""
    rows = 0
    remaining = dat_size
    while remaining > large * k:
        rows += 1
        remaining -= large * k
    return rows


def shard_file_size(dat_size: int, k: int = DATA_SHARDS_COUNT,
                    large: int = LARGE_BLOCK_SIZE,
                    small: int = SMALL_BLOCK_SIZE) -> int:
    """Size of each of the k data shard files (parity files match): full
    large rows plus ceil-padded small rows."""
    rows = large_rows_count(dat_size, k, large)
    remaining = dat_size - rows * large * k
    small_rows = -(-remaining // (small * k)) if remaining else 0
    return rows * large + small_rows * small


def locate_data(offset: int, size: int, dat_size: int,
                k: int = DATA_SHARDS_COUNT,
                large: int = LARGE_BLOCK_SIZE,
                small: int = SMALL_BLOCK_SIZE) -> list[Interval]:
    """Split the logical range [offset, offset+size) into shard intervals
    (ec_locate.go LocateData)."""
    if offset < 0 or size < 0:
        raise ValueError("negative offset/size")
    if offset + size > dat_size:
        raise ValueError(
            f"range [{offset}, {offset + size}) beyond dat size {dat_size}")
    rows = large_rows_count(dat_size, k, large)
    large_region = rows * large * k
    out: list[Interval] = []
    pos, end = offset, offset + size
    while pos < end:
        if pos < large_region:
            block, is_large = large, True
            region_off = pos
            base_shard_off = 0
        else:
            block, is_large = small, False
            region_off = pos - large_region
            base_shard_off = rows * large
        row, row_off = divmod(region_off, block * k)
        shard, inner = divmod(row_off, block)
        take = min(end - pos, block - inner)
        out.append(Interval(
            shard_id=shard,
            inner_block_offset=base_shard_off + row * block + inner,
            size=take,
            is_large_block=is_large,
            block_index=row))
        pos += take
    return out
