"""ctypes bridge to the native needle map (native/needle_map.cpp).

Same surface as idx.CompactMap (set/get/delete/len/live_entries/
items/close + the bookkeeping fields the store status, heartbeats, and
vacuum scheduler read) — except ``items()``, which yields only LIVE
entries where CompactMap also yields tombstones (see the method
comment). Entries live in one C open-addressing array
at ~24 B/slot instead of a Python dict at ~200 B/entry — the
weed/storage/needle_map/compact_map.go role (RAM-frugal index is the
Haystack design's core), built in C++ per the native-runtime mandate.
``.idx`` replay happens inside the library in one call, so loading a
multi-million-needle volume skips the per-record Python loop (measured
on this host at 2M entries: 0.11 s vs 9.7 s and ~68 MiB vs ~484 MiB
RSS against the dict CompactMap).

Selected with ``-index native`` on the volume server / Store
(needle_map kind "native"); Volume falls back to the memory CompactMap
with a warning when the native build is unavailable (no g++).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Iterator, List

from .idx import IndexEntry, NEEDLE_MAP_ENTRY_SIZE

_SRC = Path(__file__).resolve().parent.parent / "native" / "needle_map.cpp"
_SO = _SRC.with_name("_needle_map.so")

_lib = None
_lib_lock = threading.Lock()


class NativeUnavailable(RuntimeError):
    pass


def _build() -> Path:
    if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    tmp = _SO.with_suffix(f".so.tmp{os.getpid()}")
    cmd = ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", str(tmp)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        tmp.replace(_SO)
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        raise NativeUnavailable(f"g++ build failed: {detail}") from e
    finally:
        tmp.unlink(missing_ok=True)
    return _SO


def _get_lib():
    global _lib
    with _lib_lock:
        if _lib is None:
            # This lock EXISTS to single-fly the one-time g++ build.
            # seaweedlint: disable=SW103 — intentional build-once lock
            lib = ctypes.CDLL(str(_build()))
            lib.nm_new.restype = ctypes.c_void_p
            lib.nm_new.argtypes = [ctypes.c_uint64]
            lib.nm_free.argtypes = [ctypes.c_void_p]
            lib.nm_set.restype = ctypes.c_int
            lib.nm_set.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_uint32, ctypes.c_uint32]
            lib.nm_delete.restype = ctypes.c_int
            lib.nm_delete.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.nm_get.restype = ctypes.c_int
            lib.nm_get.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.POINTER(ctypes.c_uint32),
                                   ctypes.POINTER(ctypes.c_uint32)]
            lib.nm_live.restype = ctypes.c_uint64
            lib.nm_live.argtypes = [ctypes.c_void_p]
            lib.nm_stats.argtypes = [ctypes.c_void_p] + \
                [ctypes.POINTER(ctypes.c_uint64)] * 5
            lib.nm_dump_live.restype = ctypes.c_uint64
            lib.nm_dump_live.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint64]
            lib.nm_load_idx.restype = ctypes.c_uint64
            lib.nm_load_idx.argtypes = [ctypes.c_void_p,
                                        ctypes.c_char_p, ctypes.c_uint64]
            _lib = lib
    return _lib


def available() -> bool:
    try:
        _get_lib()
        return True
    except NativeUnavailable:
        return False


class NativeNeedleMap:
    """CompactMap drop-in backed by the C open-addressing table."""

    def __init__(self, cap_hint: int = 0) -> None:
        self._lib = _get_lib()
        self._h = self._lib.nm_new(cap_hint)
        if not self._h:
            raise MemoryError("nm_new failed")
        self._lock = threading.Lock()

    def _handle(self):
        """Guard: a NULL handle must raise (like sqlite's
        ProgrammingError after close), never reach the C library —
        ctypes would pass NULL and segfault the process."""
        h = self._h
        if not h:
            raise RuntimeError("needle map is closed")
        return h

    # -- CompactMap surface ----------------------------------------------

    def set(self, key: int, offset_units: int, size: int) -> None:
        with self._lock:
            if self._lib.nm_set(self._handle(), key, offset_units,
                                size) != 0:
                raise MemoryError("needle map allocation failed")

    def delete(self, key: int) -> bool:
        with self._lock:
            return bool(self._lib.nm_delete(self._handle(), key))

    def get(self, key: int):
        off = ctypes.c_uint32()
        size = ctypes.c_uint32()
        with self._lock:
            ok = self._lib.nm_get(self._handle(), key, ctypes.byref(off),
                                  ctypes.byref(size))
        if not ok:
            return None
        return IndexEntry(key, off.value, size.value)

    def __len__(self) -> int:
        with self._lock:
            return int(self._lib.nm_live(self._handle()))

    def live_entries(self) -> List[IndexEntry]:
        with self._lock:
            # count + dump under ONE lock hold: a writer between the
            # two would otherwise silently truncate the listing
            h = self._handle()
            n = int(self._lib.nm_live(h))
            keys = (ctypes.c_uint64 * n)()
            offs = (ctypes.c_uint32 * n)()
            sizes = (ctypes.c_uint32 * n)()
            got = self._lib.nm_dump_live(h, keys, offs, sizes, n)
        out = [IndexEntry(keys[i], offs[i], sizes[i])
               for i in range(got)]
        out.sort(key=lambda e: e.key)
        return out

    def items(self) -> Iterator[IndexEntry]:
        # Divergence from idx.CompactMap.items(): only LIVE entries are
        # yielded — tombstoned keys (size 0xFFFFFFFF) are dropped by
        # nm_dump_live. Callers that need deletion markers (e.g. a
        # vacuum-style diff) must use the CompactMap index kind.
        return iter(self.live_entries())

    def close(self) -> None:
        with self._lock:
            if self._h:
                self._lib.nm_free(self._h)
                self._h = None

    def __del__(self):  # best-effort; close() is the real contract
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    # -- bookkeeping the store/heartbeat/vacuum paths read ----------------

    def _stats(self):
        vals = [ctypes.c_uint64() for _ in range(5)]
        with self._lock:
            self._lib.nm_stats(self._handle(),
                               *[ctypes.byref(v) for v in vals])
        return [v.value for v in vals]

    @property
    def file_count(self) -> int:
        return self._stats()[0]

    @property
    def deleted_count(self) -> int:
        return self._stats()[1]

    @property
    def deleted_bytes(self) -> int:
        return self._stats()[2]

    @property
    def max_offset_units(self) -> int:
        return self._stats()[3]

    @property
    def max_key(self) -> int:
        return self._stats()[4]

    # -- loading ----------------------------------------------------------

    @classmethod
    def load_from_idx(cls, path) -> "NativeNeedleMap":
        blob = Path(path).read_bytes() if Path(path).exists() else b""
        if len(blob) % NEEDLE_MAP_ENTRY_SIZE:
            raise ValueError(
                f"index length {len(blob)} not a multiple of "
                f"{NEEDLE_MAP_ENTRY_SIZE}")
        n = len(blob) // NEEDLE_MAP_ENTRY_SIZE
        m = cls(cap_hint=n)
        if n:
            applied = m._lib.nm_load_idx(m._h, blob, n)
            if applied != n:
                m.close()
                raise MemoryError(
                    f"needle map load failed at record {applied}/{n}")
        return m
