"""Store: disk locations, the volume registry, and EC shard mounts.

Mirrors weed/storage/store.go + disk_location.go + store_ec.go (SURVEY.md
§2 "Store / Volume engine" and "EC read path" rows): a Store owns one or
more directories ("disk locations"), each holding normal volumes
(<base>.dat/.idx) and mounted EC shards (<base>.ec??/.ecx). The volume
server (L3) dispatches every data-plane and admin operation through this
object; heartbeats to the master are built from its `status()` snapshot.

Volume base naming follows the reference: ``<vid>`` or
``<collection>_<vid>`` inside the location directory.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator, Optional

from . import ec_files
from .needle import Needle
from .superblock import ReplicaPlacement, SuperBlock, Ttl
from .volume import Volume, VolumeError, dat_path, idx_path

_BASE_RE = re.compile(r"^(?:(?P<col>.+)_)?(?P<vid>\d+)$")


class StoreError(RuntimeError):
    pass


def volume_base_name(volume_id: int, collection: str = "") -> str:
    return f"{collection}_{volume_id}" if collection else str(volume_id)


def parse_base_name(stem: str) -> tuple[str, int]:
    """'<collection>_<vid>' / '<vid>' -> (collection, vid)."""
    m = _BASE_RE.match(stem)
    if not m:
        raise ValueError(f"not a volume base name: {stem!r}")
    return m.group("col") or "", int(m.group("vid"))


@dataclass
class EcVolumeMount:
    """Local mount state of one EC volume: which shard files this store
    serves (ec_volume.go EcVolume, minus the remote-peer logic that lives
    in the server layer)."""

    base: Path
    collection: str
    volume_id: int
    shard_ids: set[int] = field(default_factory=set)

    @property
    def shard_bits(self) -> ec_files.ShardBits:
        return ec_files.ShardBits.from_ids(sorted(self.shard_ids))


class DiskLocation:
    """One directory of volume/shard files (disk_location.go)."""

    def __init__(self, directory: str | Path, max_volumes: int = 8):
        self.directory = Path(directory)
        self.max_volumes = max_volumes
        if not self.directory.is_dir():
            raise StoreError(f"{self.directory} is not a directory")

    def base_for(self, volume_id: int, collection: str = "") -> Path:
        return self.directory / volume_base_name(volume_id, collection)

    def scan_volumes(self) -> Iterator[tuple[str, int, Path]]:
        """Yield (collection, vid, base) for every <base>.dat present —
        and every .tier sidecar (an S3-tiered volume has no local .dat
        but must still mount on restart)."""
        seen = set()
        for p in sorted(self.directory.glob("*.dat")) + \
                sorted(self.directory.glob("*.tier")):
            try:
                col, vid = parse_base_name(p.stem)
            except ValueError:
                continue
            if (col, vid) in seen:
                continue
            seen.add((col, vid))
            yield col, vid, p.with_suffix("")

    def scan_ec_shards(self) -> Iterator[tuple[str, int, Path, list[int]]]:
        """Yield (collection, vid, base, shard_ids) for bases that have at
        least one .ec?? file AND a .ecx index."""
        seen: dict[Path, list[int]] = {}
        for p in sorted(self.directory.iterdir()):
            m = re.match(r"^\.ec(\d\d)$", p.suffix)
            if not m:
                continue
            seen.setdefault(p.with_suffix(""), []).append(int(m.group(1)))
        for base, ids in seen.items():
            if not ec_files.ecx_path(base).exists():
                continue
            try:
                col, vid = parse_base_name(base.name)
            except ValueError:
                continue
            yield col, vid, base, sorted(ids)


class Store:
    """The storage engine facade the volume server drives (store.go)."""

    def __init__(self, locations: list[str | Path],
                 max_volumes: int = 8, backend: str = "disk",
                 needle_map: str = "memory"):
        if not locations:
            raise StoreError("a store needs at least one disk location")
        self.locations = [DiskLocation(d, max_volumes) for d in locations]
        #: .dat backend kind (storage/backend.py registry) and needle
        #: map kind ("memory" | "native" | "sqlite") applied to every
        #: volume.
        self.backend = backend
        self.needle_map = needle_map
        self.volumes: dict[tuple[str, int], Volume] = {}
        self.ec_mounts: dict[tuple[str, int], EcVolumeMount] = {}
        self.readonly: set[tuple[str, int]] = set()
        # Guards the three registry maps above — and ONLY them. Admin
        # gRPC threads mount/unmount/delete while the heartbeat thread
        # snapshots status() and job workers flip readonly marks; all
        # volume I/O (load/create/close/stat) stays OUTSIDE the lock
        # so a slow disk can never stall the heartbeat.
        self._lock = threading.RLock()

    # -- lifecycle --------------------------------------------------------

    def load_existing(self) -> None:
        """Scan every location and open what's on disk (volume_loading.go;
        EC shards found with their .ecx are auto-mounted the way the
        reference remounts shards on restart). Before opening anything,
        sweep orphaned transfer temporaries — ``.part`` streams (tier
        downloads, replica copies killed mid-transfer) and ``.tmp``
        sidecar writes — whose rename commit point never ran; they are
        garbage by construction (the commit is the rename) and a later
        transfer restarts from scratch."""
        for loc in self.locations:
            removed = 0
            for pattern in ("*.part", "*.tmp"):
                for orphan in loc.directory.glob(pattern):
                    orphan.unlink(missing_ok=True)
                    removed += 1
            if removed:
                from ..util import glog
                glog.info("store: removed %d orphaned transfer "
                          "temporaries under %s", removed,
                          loc.directory)
            for col, vid, base in loc.scan_volumes():
                if (col, vid) not in self.volumes:
                    vol = Volume(base, vid, backend=self.backend,
                                 needle_map=self.needle_map).load()
                    with self._lock:
                        self.volumes[(col, vid)] = vol
                        if vol.readonly:
                            # tiered (.tier sidecar): the durable
                            # read-only marker must survive restarts so
                            # heartbeats never advertise the volume
                            # writable
                            self.readonly.add((col, vid))
            for col, vid, base, ids in loc.scan_ec_shards():
                with self._lock:
                    m = self.ec_mounts.setdefault(
                        (col, vid), EcVolumeMount(base, col, vid))
                    m.shard_ids.update(ids)

    def close(self) -> None:
        for v in list(self.volumes.values()):
            v.close()
        with self._lock:
            self.volumes.clear()
            self.ec_mounts.clear()

    def _pick_location(self) -> DiskLocation:
        """Least-loaded location with free volume slots."""
        def load(loc: DiskLocation) -> int:
            return sum(1 for v in self.volumes.values()
                       if v.base.parent == loc.directory)
        candidates = [l for l in self.locations
                      if load(l) < l.max_volumes]
        if not candidates:
            raise StoreError("no disk location has free volume slots")
        return min(candidates, key=load)

    # -- normal volumes ---------------------------------------------------

    def create_volume(self, volume_id: int, collection: str = "",
                      replica_placement: str = "000", ttl: str = "",
                      version: int = 3) -> Volume:
        key = (collection, volume_id)
        if key in self.volumes:
            raise StoreError(f"volume {volume_id} already exists")
        loc = self._pick_location()
        sb = SuperBlock(
            version=version,
            replica_placement=ReplicaPlacement.parse(replica_placement),
            ttl=Ttl.parse(ttl))
        vol = Volume(loc.base_for(volume_id, collection), volume_id,
                     sb, backend=self.backend,
                     needle_map=self.needle_map).create()
        with self._lock:
            self.volumes[key] = vol
        return vol

    def get_volume(self, volume_id: int, collection: str = "") -> Volume:
        try:
            return self.volumes[(collection, volume_id)]
        except KeyError:
            raise StoreError(f"volume {volume_id} not found") from None

    def has_volume(self, volume_id: int, collection: str = "") -> bool:
        return (collection, volume_id) in self.volumes

    def mark_readonly(self, volume_id: int, collection: str = "") -> None:
        """VolumeMarkReadonly: freeze writes ahead of ec.encode
        (volume server admin gRPC; SURVEY.md §3.1)."""
        self.get_volume(volume_id, collection)  # must exist
        with self._lock:
            self.readonly.add((collection, volume_id))

    def mark_writable(self, volume_id: int, collection: str = "") -> None:
        """VolumeMarkWritable: undo a freeze (balance rollback path)."""
        self.get_volume(volume_id, collection)  # must exist
        with self._lock:
            self.readonly.discard((collection, volume_id))

    def is_readonly(self, volume_id: int, collection: str = "") -> bool:
        return (collection, volume_id) in self.readonly

    # -- cold tier (storage/tier.py choreography) -------------------------

    def tier_move(self, volume_id: int, collection: str = "", *,
                  endpoint: str, bucket: str, object_key: str = "",
                  keep_local: bool = False, access_key: str = "",
                  secret_key: str = "", on_sealed=None):
        """Move a volume's .dat to the S3 tier WITHOUT ever taking the
        volume out of service: seal (read-only; ``on_sealed`` runs so a
        server can heartbeat the freeze before any byte moves — when
        the destination is this cluster's own gateway, the upload's
        chunks must never be assigned to the volume being moved), sync,
        stream the object while reads keep flowing off the still-open
        local fd, then retier() swaps the backend under the reader
        drain. A failed upload rolls the freeze back."""
        from . import tier as tier_mod
        key = (collection, volume_id)
        vol = self.get_volume(volume_id, collection)
        was_readonly = key in self.readonly
        was_vol_readonly = vol.readonly
        # Seal under the VOLUME lock: write_needle checks readonly
        # under the same lock, so every writer either fully landed
        # before this (its bytes reach the sync below) or fails the
        # check — none can append between the sync and the upload.
        with vol._lock:
            vol.readonly = True
        with self._lock:
            self.readonly.add(key)
        if on_sealed is not None:
            on_sealed()
        try:
            vol.sync()
            info = tier_mod.upload_volume_dat(
                vol.base, endpoint, bucket, key=object_key,
                access_key=access_key, secret_key=secret_key,
                remove_local=not keep_local)
        except BaseException:
            if not was_readonly:
                with self._lock:
                    self.readonly.discard(key)
            if not was_vol_readonly:
                with vol._lock:
                    vol.readonly = False
            raise
        vol.retier()
        return info

    def tier_restore(self, volume_id: int, collection: str = ""):
        """Bring a tiered .dat back local and make the volume writable
        again; a non-tiered volume is a clean error with the volume
        left untouched (no close/reopen cycle). Credentials resolve
        from the environment (see tier.TierInfo.maybe_load)."""
        from . import tier as tier_mod
        vol = self.get_volume(volume_id, collection)
        if tier_mod.TierInfo.maybe_load(vol.base) is None:
            raise StoreError(f"volume {volume_id} is not tiered")
        tier_mod.download_volume_dat(vol.base)
        vol.retier()
        with self._lock:
            self.readonly.discard((collection, volume_id))
        return vol.dat_size

    def unmount_volume(self, volume_id: int,
                       collection: str = "") -> None:
        """Stop serving a volume but KEEP its files (the reference's
        VolumeUnmount): the maintenance verb for moving a volume
        directory by hand or freezing it for external tooling."""
        vol = self.get_volume(volume_id, collection)
        vol.close()
        with self._lock:
            self.volumes.pop((collection, volume_id), None)
        # the readonly mark is deliberately KEPT: an operator (or the
        # ec.encode/move choreography) that froze the volume must not
        # find it silently writable again after an unmount/mount cycle

    def mount_volume(self, volume_id: int,
                     collection: str = "") -> None:
        """(Re)open a volume whose files are already in a location
        (VolumeMount): the inverse of unmount_volume."""
        if (collection, volume_id) in self.volumes:
            return
        from . import tier as tier_mod
        for loc in self.locations:
            base = loc.directory / volume_base_name(volume_id,
                                                    collection)
            if dat_path(base).exists() or \
                    tier_mod.TierInfo.path_for(base).exists():
                vol = Volume(base, volume_id, backend=self.backend,
                             needle_map=self.needle_map).load()
                with self._lock:
                    self.volumes[(collection, volume_id)] = vol
                    if vol.readonly:
                        self.readonly.add((collection, volume_id))
                return
        raise StoreError(
            f"no files for volume {volume_id} "
            f"(collection {collection!r}) in any location")

    def delete_volume(self, volume_id: int, collection: str = "") -> None:
        """Drop the .dat/.idx (ec.encode's final step deletes the source
        volume this way)."""
        vol = self.get_volume(volume_id, collection)
        vol.close()
        with self._lock:
            self.volumes.pop((collection, volume_id), None)
            self.readonly.discard((collection, volume_id))
        # .sdx goes too: a leftover sqlite map would resurrect phantom
        # index entries if the volume id is ever re-allocated.
        for p in (dat_path(vol.base), idx_path(vol.base),
                  Path(str(vol.base) + ".sdx")):
            if p.exists():
                p.unlink()

    # -- vacuum -----------------------------------------------------------

    def garbage_ratio(self, volume_id: int, collection: str = ""
                      ) -> float:
        from . import vacuum as vacuum_mod
        return vacuum_mod.garbage_ratio(
            self.get_volume(volume_id, collection))

    def vacuum_volume(self, volume_id: int, collection: str = "",
                      threshold: float = 0.0):
        """Compact away deleted needles when garbage exceeds
        ``threshold`` (volume_vacuum.go Compact + CommitCompact).
        Returns the new .dat size, or None when below threshold."""
        from . import vacuum as vacuum_mod
        return vacuum_mod.vacuum(self.get_volume(volume_id, collection),
                                 threshold)

    # -- data plane -------------------------------------------------------

    def configure_replication(self, volume_id: int,
                              replication: str,
                              collection: str = "") -> None:
        self.get_volume(volume_id, collection).configure_replication(
            replication)

    def write_needle(self, volume_id: int, n: Needle,
                     collection: str = "") -> int:
        if self.is_readonly(volume_id, collection):
            raise StoreError(f"volume {volume_id} is read-only")
        return self.get_volume(volume_id, collection).write_needle(n)

    def read_needle(self, volume_id: int, key: int,
                    cookie: Optional[int] = None,
                    collection: str = "") -> Needle:
        return self.get_volume(volume_id, collection).read_needle(
            key, cookie)

    def delete_needle(self, volume_id: int, key: int,
                      collection: str = "") -> bool:
        return self.get_volume(volume_id, collection).delete_needle(key)

    # -- EC shards --------------------------------------------------------

    def ec_base(self, volume_id: int, collection: str = ""
                ) -> Optional[Path]:
        m = self.ec_mounts.get((collection, volume_id))
        if m is not None:
            return m.base
        for loc in self.locations:
            base = loc.base_for(volume_id, collection)
            if ec_files.ecx_path(base).exists():
                return base
        return None

    def ec_shard_paths(self, volume_id: int, collection: str = ""
                       ) -> dict[int, Path]:
        """shard_id -> file path, looking across ALL disk locations (the
        local-mode analog of asking the master where shards live)."""
        name = volume_base_name(volume_id, collection)
        out: dict[int, Path] = {}
        for loc in self.locations:
            base = loc.directory / name
            for i in ec_files.present_shards(base, 100):
                out.setdefault(i, ec_files.shard_path(base, i))
        return out

    def gather_ec_volume(self, volume_id: int, collection: str = ""
                         ) -> Path:
        """Make every shard of an EC volume reachable under ONE base path
        by symlinking siblings from other locations — the local-mode form
        of ec.rebuild's 'copy missing sibling shards local' step
        (§3.5) before Reconstruct runs. Returns that base."""
        base = self.ec_base(volume_id, collection)
        if base is None:
            raise StoreError(f"no EC volume {volume_id}")
        for sid, path in self.ec_shard_paths(volume_id, collection).items():
            local = ec_files.shard_path(base, sid)
            if not local.exists():
                if local.is_symlink():  # stale/broken link
                    local.unlink()
                # absolute target: a relative one would resolve against
                # the location directory and dangle
                local.symlink_to(path.resolve())
        # the delete journal and volume info may live beside a moved shard
        name = volume_base_name(volume_id, collection)
        for pathfn in (ec_files.ecj_path, ec_files.vif_path):
            local = pathfn(base)
            if local.exists():
                continue
            if local.is_symlink():
                local.unlink()
            for loc in self.locations:
                other = pathfn(loc.directory / name)
                if other.exists() and other.resolve() != local.resolve():
                    local.symlink_to(other.resolve())
                    break
        return base

    def remove_ec_volume_files(self, volume_id: int, collection: str = ""
                               ) -> None:
        """Delete every EC artifact of a volume in every location
        (symlinks and real files both)."""
        name = volume_base_name(volume_id, collection)
        for loc in self.locations:
            base = loc.directory / name
            for i in range(100):
                p = ec_files.shard_path(base, i)
                if p.exists() or p.is_symlink():
                    p.unlink()
            for p in (ec_files.ecx_path(base), ec_files.ecj_path(base),
                      ec_files.vif_path(base)):
                if p.exists() or p.is_symlink():
                    p.unlink()

    def mount_ec_shards(self, volume_id: int, shard_ids: list[int],
                        collection: str = "") -> EcVolumeMount:
        """VolumeEcShardsMount: register local shard files for serving."""
        base = self.ec_base(volume_id, collection)
        if base is None:
            raise StoreError(
                f"no .ecx for volume {volume_id} in any location")
        missing = [i for i in shard_ids
                   if not ec_files.shard_path(base, i).exists()]
        if missing:
            raise StoreError(
                f"shard files missing for volume {volume_id}: {missing}")
        with self._lock:
            m = self.ec_mounts.setdefault(
                (collection, volume_id),
                EcVolumeMount(base, collection, volume_id))
            m.shard_ids.update(shard_ids)
        return m

    def unmount_ec_shards(self, volume_id: int, shard_ids: list[int],
                          collection: str = "") -> None:
        with self._lock:
            m = self.ec_mounts.get((collection, volume_id))
            if m is None:
                return
            m.shard_ids.difference_update(shard_ids)
            if not m.shard_ids:
                del self.ec_mounts[(collection, volume_id)]

    # -- status / heartbeat ----------------------------------------------

    def reconcile_ec_shards(self) -> None:
        """Heartbeat-path self-heal: align EC mounts with DISK REALITY
        so shard files lost underneath a running server (disk fault,
        operator rm) drop out of the next snapshot — the master's
        topology, ec.rebuild's missing-shard view, and peers' read
        routing stay truthful instead of trusting a stale mount table.

        Called from the heartbeat loop only (never from read-only
        snapshots like volume.list): one directory scan per location
        per pulse, shards counted present if ANY location holds them
        (ec.balance moves shards between locations without updating
        the mount base). Defensive pops: admin RPC threads mutate the
        mount table concurrently."""
        from ..util import glog

        reality: dict[tuple[str, int], set] = {}
        for loc in self.locations:
            for col, vid, _base, ids in loc.scan_ec_shards():
                reality.setdefault((col, vid), set()).update(ids)
        for key in list(self.ec_mounts):
            m = self.ec_mounts.get(key)
            if m is None:
                continue
            present = reality.get(key, set())
            gone = sorted(set(m.shard_ids) - present)
            if not gone:
                continue
            glog.warning(
                "volume %d: ec shard file(s) %s vanished from disk; "
                "unmounting them", key[1], gone)
            with self._lock:
                m.shard_ids.intersection_update(present)
                if not m.shard_ids:
                    self.ec_mounts.pop(key, None)

    def status(self) -> dict:
        """Snapshot for heartbeats (§3.4): normal volumes + EC shard bits,
        the payload SendHeartbeat streams to the master."""
        # snapshot under the registry lock; the per-volume stat() I/O
        # below runs on the copy so a slow disk can't block mounts
        with self._lock:
            vol_items = sorted(self.volumes.items())
            readonly = set(self.readonly)
            ec = [{"id": vid, "collection": col,
                   "ec_index_bits": m.shard_bits.bits}
                  for (col, vid), m in sorted(self.ec_mounts.items())]
        vols = []
        for (col, vid), v in vol_items:
            try:
                modified = int(dat_path(v.base).stat().st_mtime)
            except OSError:
                modified = 0
            vols.append({
                "id": vid, "collection": col,
                "size": v.dat_size, "file_count": v.nm.file_count,
                "deleted_count": v.nm.deleted_count,
                "deleted_bytes": v.nm.deleted_bytes,
                "read_only": (col, vid) in readonly,
                "replica_placement": str(v.super_block.replica_placement),
                "version": v.super_block.version,
                "ttl": str(v.super_block.ttl),
                "modified_at_second": modified,
            })
        return {"volumes": vols, "ec_shards": ec}
