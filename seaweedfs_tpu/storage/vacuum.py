"""Volume vacuum: compact away deleted needles, then atomically commit.

Mirrors weed/storage/volume_vacuum.go (SURVEY.md §2 "Store / Volume
engine": ``Compact`` / ``CommitCompact``): deletes only journal
tombstones, so reclaimed space accumulates until a compaction rewrites
the live needles into a fresh ``.cpd``/``.cpx`` pair and renames them
over ``.dat``/``.idx``.

Two phases, same as the reference:

- ``compact(vol)`` — snapshot the .idx length, then copy every needle
  live AS OF the snapshot into ``.cpd`` (superblock compact revision
  +1) while writes keep landing in the old files. Uses pread, so no
  writer lock is held during the bulk copy.
- ``commit_compact(vol)`` — under the volume lock, replay .idx entries
  journaled AFTER the snapshot onto the compact files (the reference's
  ``makeupDiff``), fsync, rename into place, and reload the needle map.

Crash safety: a crash before the final renames leaves ``.cpd``/``.cpx``
behind and the live volume untouched — ``cleanup`` (or the next load)
just deletes them. The rename pair is ordered .idx-last so a torn
commit is detected by load-time checking.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

from ..util import durability, faults
from . import backend as backend_mod
from . import needle as needle_mod
from .idx import CompactMap, IndexEntry, walk_index_blob
from .superblock import SuperBlock
from .types import (NEEDLE_PADDING_SIZE, TOMBSTONE_FILE_SIZE,
                    actual_offset, to_offset_units)
from .volume import Volume, VolumeError, dat_path, idx_path


def cpd_path(base: str | Path) -> Path:
    return Path(str(base) + ".cpd")


def cpx_path(base: str | Path) -> Path:
    return Path(str(base) + ".cpx")


@dataclass
class CompactState:
    """Carried from compact() to commit_compact()."""
    idx_snapshot_bytes: int
    new_super: SuperBlock


def garbage_ratio(vol: Volume) -> float:
    """Deleted bytes / content bytes (topology_vacuum.go's trigger)."""
    size = vol.dat_size
    if size <= 8:
        return 0.0
    return vol.nm.deleted_bytes / max(1, size - 8)


def compact(vol: Volume) -> CompactState:
    """Phase 1: copy live needles to .cpd/.cpx. Writers may continue.

    At most one compaction per volume may be in flight: a second
    compact() (e.g. the master's auto-scan racing an operator's
    volume.vacuum) raises instead of interleaving writes into the same
    .cpd and letting one Commit rename a half-written file live."""
    if vol._dat is None:
        raise VolumeError("volume not open")
    if vol.readonly:
        # Tiered (sidecar present): compacting the local copy would
        # diverge from the S3 bytes, and a later tier.download would
        # put the stale object under the compacted .idx.
        raise VolumeError(
            f"volume {vol.volume_id} is read-only (tiered); "
            f"volume.tier.download before vacuuming")
    with vol._lock:
        if getattr(vol, "vacuum_in_progress", False):
            raise VolumeError(
                f"volume {vol.volume_id}: compaction already in progress")
        # the claim is taken under vol._lock; every later clear runs
        # on the thread that holds the claim, so there is never a
        # concurrent writer
        # seaweedlint: disable=SW801 — claim taken under vol._lock
        vol.vacuum_in_progress = True
    try:
        return _compact_locked(vol)
    except BaseException:
        vol.vacuum_in_progress = False
        cleanup(vol.base)
        raise


def _compact_locked(vol: Volume) -> CompactState:
    with vol._lock:
        vol._idx.flush()
        vol._dat.flush()
        idx_snapshot = idx_path(vol.base).stat().st_size
        idx_snapshot -= idx_snapshot % 16
    # Needle map as of the snapshot (not vol.nm, which keeps moving).
    snap = CompactMap()
    with open(idx_path(vol.base), "rb") as f:
        for e in walk_index_blob(f.read(idx_snapshot)):
            if e.is_deleted:
                snap.delete(e.key)
            else:
                snap.set(e.key, e.offset_units, e.size)
    new_super = SuperBlock(
        version=vol.super_block.version,
        replica_placement=vol.super_block.replica_placement,
        ttl=vol.super_block.ttl,
        compact_revision=(vol.super_block.compact_revision + 1) & 0xFFFF)
    with open(cpd_path(vol.base), "wb") as nd, \
            open(cpx_path(vol.base), "wb") as nx:
        nd.write(new_super.to_bytes())
        _copy_live(snap, vol._dat, vol.super_block.version, nd, nx)
        faults.check("crash.vacuum.compact")
        nd.flush()
        os.fsync(nd.fileno())
        nx.flush()
        os.fsync(nx.fileno())
    return CompactState(idx_snapshot_bytes=idx_snapshot,
                        new_super=new_super)


def _copy_live(snap: CompactMap, dat, version: int, nd, nx
               ) -> None:
    """Append every live needle of ``snap`` to nd/.cpx in offset order
    (preserves locality and keeps the copy sequential on disk)."""
    entries = sorted(
        (e for e in snap._m.values() if not e.is_deleted),
        key=lambda e: e.offset_units)
    for e in entries:
        rec_size = needle_mod.record_size(e.size, version)
        rec = dat.read_at(rec_size, e.byte_offset)
        if len(rec) < rec_size:
            raise VolumeError(
                f"short read compacting needle {e.key}")
        pos = nd.tell()
        if pos % NEEDLE_PADDING_SIZE:
            pad = (-pos) % NEEDLE_PADDING_SIZE
            nd.write(b"\x00" * pad)
            pos += pad
        nd.write(rec)
        nx.write(IndexEntry(e.key, to_offset_units(pos),
                            e.size).to_bytes())


def commit_compact(vol: Volume, state: CompactState) -> int:
    """Phase 2: catch up post-snapshot writes, swap files, reload.
    Returns the new .dat size."""
    if vol._dat is None:
        raise VolumeError("volume not open")
    if not getattr(vol, "vacuum_in_progress", False):
        raise VolumeError(
            f"volume {vol.volume_id}: no compaction in progress")
    if vol.needle_map_kind == "native":
        # Warm the native needle-map library BEFORE draining readers:
        # its first use forks a g++ build, and paying that while
        # holding the volume lock would stall every reader and writer
        # on this volume for the length of a compile.
        from . import needle_map_native
        needle_map_native.available()
    with vol._lock:
        # Drain in-flight readers FIRST: Condition.wait releases the
        # volume lock, so waiting any later (after the diff replay)
        # would let a writer append an acknowledged needle to the old
        # .dat/.idx that the renames below silently discard. Once the
        # drain returns, the lock is held continuously through replay
        # and swap — no reader can touch the dying fd, no writer can
        # land a post-replay record. _swap_pending parks NEW readers so
        # a stream of overlapping reads cannot starve the drain.
        vol._swap_pending = True
        try:
            # Any native-map build was pre-warmed above, outside the lock.
            # seaweedlint: disable=SW103 — lib compile pre-warmed above
            size = _commit_swap_drained(vol, state)
        finally:
            vol._swap_pending = False
            vol._no_readers.notify_all()
    # The compacted files are live: any chunk cache still holding
    # pre-compaction payloads for this volume must drop them before the
    # next read (fans out to every registered ChunkCache). Outside the
    # volume lock — listeners take their own locks.
    from ..cache import invalidation as cache_invalidation

    cache_invalidation.volume_invalidated(vol.volume_id, reason="vacuum")
    return size


def _commit_swap_drained(vol: Volume, state: CompactState) -> int:
    """Diff replay + fd swap; runs under vol._lock with _swap_pending
    set (new readers parked). Factored out of commit_compact so the
    flag clears on every exit path."""
    while vol._readers:
        vol._no_readers.wait()
    vol._idx.flush()
    vol._dat.flush()
    idx_now = idx_path(vol.base).stat().st_size
    idx_now -= idx_now % 16
    with open(cpd_path(vol.base), "r+b") as nd, \
            open(cpx_path(vol.base), "r+b") as nx:
        nd.seek(0, 2)
        nx.seek(0, 2)
        # Replay the diff journal (makeupDiff): appends copy the
        # record across, deletes tombstone the compact index.
        if idx_now > state.idx_snapshot_bytes:
            with open(idx_path(vol.base), "rb") as f:
                f.seek(state.idx_snapshot_bytes)
                diff = f.read(idx_now - state.idx_snapshot_bytes)
            for e in walk_index_blob(diff):
                if e.is_deleted:
                    nx.write(IndexEntry(
                        e.key, 0, TOMBSTONE_FILE_SIZE).to_bytes())
                    continue
                rec_size = needle_mod.record_size(
                    e.size, vol.super_block.version)
                rec = vol._dat.read_at(rec_size, e.byte_offset)
                if len(rec) < rec_size:
                    raise VolumeError(
                        f"short read replaying diff for needle "
                        f"{e.key}: {len(rec)} < {rec_size}")
                pos = nd.tell()
                if pos % NEEDLE_PADDING_SIZE:
                    pad = (-pos) % NEEDLE_PADDING_SIZE
                    nd.write(b"\x00" * pad)
                    pos += pad
                nd.write(rec)
                nx.write(IndexEntry(e.key, to_offset_units(pos),
                                    e.size).to_bytes())
        nd.flush()
        os.fsync(nd.fileno())
        nx.flush()
        os.fsync(nx.fileno())
    # Swap: close handles, rename .cpd/.cpx over .dat/.idx (dat
    # first; load-time checking tolerates a torn pair), reopen. The
    # renames are durable_replace — fsyncing the parent directory is
    # what persists the swap itself; without it a power cut after
    # "commit" could resurrect the garbage-laden pre-compact files.
    vol._dat.close()
    vol._idx.close()
    faults.check("crash.vacuum.precommit")
    try:
        durability.durable_replace(cpd_path(vol.base),
                                   dat_path(vol.base))
    except OSError:
        # Nothing swapped yet: reopen the untouched live files so the
        # volume stays serviceable; abort_compact discards .cpd/.cpx.
        # commit runs holding the vacuum_in_progress claim with
        # readers drained (swap-drain protocol above): exactly one
        # thread touches the handles
        # seaweedlint: disable=SW801 — swap-drain protocol
        vol._dat = backend_mod.open_backend(vol.backend_kind,
                                            dat_path(vol.base))
        # seaweedlint: disable=SW801 — same swap-drain protocol
        vol._idx = open(idx_path(vol.base), "a+b")
        raise
    faults.check("crash.vacuum.midcommit")
    try:
        durability.durable_replace(cpx_path(vol.base),
                                   idx_path(vol.base))
    except OSError:
        # Torn commit: the compacted .dat is live and .cpx is its only
        # index. Keep .cpx on disk (cleanup() preserves this state) and
        # take the volume out of service — the next load() installs it.
        vol._dat = vol._idx = None
        raise
    vol._dat = backend_mod.open_backend(vol.backend_kind,
                                        dat_path(vol.base))
    vol._idx = open(idx_path(vol.base), "a+b")
    vol.super_block = state.new_super
    if hasattr(vol.nm, "close"):
        vol.nm.close()
    # seaweedlint: disable=SW801 — same swap-drain protocol
    vol.nm = vol._load_needle_map()
    vol.vacuum_in_progress = False
    return vol._dat.size()


def cleanup(base: str | Path) -> None:
    """Remove leftover compact files (crash before commit).

    Unlink order matters: load() reads a ``.cpx``-present/``.cpd``-absent
    state as "crash between the commit renames" and installs the .cpx
    over the live .idx. Deleting .cpd first would make an interrupted
    cleanup fabricate exactly that state from a merely-aborted compaction
    — installing a STALE index over a valid one. Deleting .cpx first
    leaves at worst a .cpd-only state, which load() discards.

    And a genuinely torn commit must be preserved here, not cleaned:
    commit's first rename CONSUMES .cpd (``.cpd`` → ``.dat``), so a
    .cpx-present/.cpd-absent state proves the compacted .dat is already
    live and the .cpx is the only index matching it. An error-path
    abort_compact (e.g. the master's VacuumVolumeCleanup after a failed
    commit) deleting that .cpx would strand the new .dat with the stale
    pre-compact .idx — unrecoverable. Leave it for load() to finish."""
    cpx, cpd = cpx_path(base), cpd_path(base)
    if not cpd.exists():
        return  # nothing, or a torn commit whose .cpx load() will install
    if cpx.exists():
        cpx.unlink()
    cpd.unlink()


def abort_compact(vol: Volume) -> None:
    """Drop an in-flight compaction: delete its files, clear the
    in-progress flag (the VacuumVolumeCleanup rpc)."""
    cleanup(vol.base)
    vol.vacuum_in_progress = False


def vacuum(vol: Volume, threshold: float = 0.0) -> Optional[int]:
    """Compact + commit when garbage_ratio exceeds ``threshold``.
    Returns the new size, or None when below threshold (or when the
    volume is tiered read-only — the master's auto-scan must skip those
    silently, not error every pulse)."""
    if vol.readonly or garbage_ratio(vol) <= threshold:
        return None
    state = compact(vol)
    try:
        return commit_compact(vol, state)
    except BaseException:
        abort_compact(vol)
        raise
