"""S3-tier volume backend (weed/storage/backend s3_backend +
weed/shell command_volume_tier_upload.go / _download.go analogs).

A SEALED volume's ``.dat`` moves to an S3 endpoint — in this
environment the project's own loopback S3 gateway (gateway/s3.py), so
the whole tier is testable in-process — while the hot index (.idx)
stays local, which is the reference's tiering split: cold data bytes
remote, needle lookups local. A ``<base>.tier`` JSON sidecar records
where the bytes live; ``Volume.load`` sees the sidecar (with no local
``.dat``) and opens an :class:`S3TierFile`, after which every needle
read becomes an HTTP range GET through a small block cache. Tiered
volumes are read-only, exactly like the reference's tiered volumes
(writes require ``volume.tier.download`` first).

TPU-first note: the block cache uses large (1 MiB) aligned blocks so a
streaming EC encode of a tiered volume hits the gateway with a few big
sequential ranges (what an object store is good at) rather than one
request per needle.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import urllib.error
import urllib.request
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Optional

from ..util import durability, faults, retry

TIER_SUFFIX = ".tier"
BLOCK = 1024 * 1024
MAX_CACHED_BLOCKS = 64


class TierError(RuntimeError):
    pass


@dataclass
class TierInfo:
    """Sidecar contents: where the .dat bytes live (the reference's
    VolumeInfo.files[].backend_name + key, master_pb VolumeTierInfo)."""

    endpoint: str          # http(s)://host:port
    bucket: str
    key: str
    size: int
    access_key: str = ""
    secret_key: str = ""
    region: str = "us-east-1"

    @staticmethod
    def path_for(base: str | Path) -> Path:
        return Path(str(base) + TIER_SUFFIX)

    def save(self, base: str | Path) -> None:
        """Persist WITHOUT credentials: the sidecar sits in the data
        directory (readable by backups etc.); keys are resolved at load
        time from the environment (SEAWEEDFS_TPU_TIER_ACCESS_KEY /
        _SECRET_KEY), matching the reference's config-not-data-file
        placement of backend credentials."""
        p = self.path_for(base)
        tmp = p.with_suffix(p.suffix + ".tmp")
        d = asdict(self)
        d.pop("access_key", None)
        d.pop("secret_key", None)
        tmp.write_text(json.dumps(d, indent=1))
        os.chmod(tmp, 0o600)
        # durable rename: the sidecar is the marker that the S3 copy is
        # authoritative — losing it to a power cut while keeping the
        # (possibly stale-tracked) local .dat would fork the truth
        durability.durable_replace(tmp, p)

    @classmethod
    def maybe_load(cls, base: str | Path) -> Optional["TierInfo"]:
        p = cls.path_for(base)
        if not p.exists():
            return None
        try:
            info = cls(**json.loads(p.read_text()))
        except (ValueError, TypeError) as e:
            raise TierError(f"corrupt tier sidecar {p}: {e}") from e
        if not info.access_key:
            info.access_key = os.environ.get(
                "SEAWEEDFS_TPU_TIER_ACCESS_KEY", "")
            info.secret_key = os.environ.get(
                "SEAWEEDFS_TPU_TIER_SECRET_KEY", "")
        return info


def _object_url(info: TierInfo) -> str:
    import urllib.parse as up

    ep = info.endpoint.rstrip("/")
    if "://" not in ep:
        ep = "http://" + ep
    return f"{ep}/{info.bucket}/{up.quote(info.key)}"


def _signed(info: TierInfo, method: str, url: str, headers: dict,
            body: bytes = b"") -> dict:
    if not info.access_key:
        return headers
    from ..gateway.s3_auth import sign_request_headers
    return sign_request_headers(method, url, headers, body,
                                info.access_key, info.secret_key,
                                region=info.region)


class S3TierFile:
    """Read-only BackendStorageFile over an S3 object (range GETs +
    block cache). Registered as backend kind "s3"; constructed from the
    ``.tier`` sidecar next to the (absent) ``.dat``."""

    def __init__(self, info: TierInfo, name: str = ""):
        self.info = info
        self.name = name or _object_url(info)
        #: offset-aligned block -> bytes, LRU by insertion refresh.
        #: Guarded by _cache_lock: read_at is called concurrently by
        #: volume-server reader threads (the Volume drops its lock for
        #: pread), and OrderedDict eviction racing move_to_end would
        #: KeyError. The ranged GET itself runs OUTSIDE the lock so a
        #: slow fetch doesn't serialize unrelated readers.
        self._cache: "collections.OrderedDict[int, bytes]" = \
            collections.OrderedDict()
        self._cache_lock = threading.Lock()

    @classmethod
    def from_dat_path(cls, path: str | Path,
                      create: bool = False) -> "S3TierFile":
        if create:
            raise TierError("cannot create a new volume on the s3 tier; "
                            "tier an existing sealed volume instead")
        base = str(path)
        if base.endswith(".dat"):
            base = base[:-4]
        info = TierInfo.maybe_load(base)
        if info is None:
            raise TierError(f"no {TIER_SUFFIX} sidecar for {path}")
        return cls(info, name=str(path))

    # -- reads ------------------------------------------------------------

    def _fetch(self, start: int, end: int) -> bytes:
        """One ranged GET of [start, end) from the object store."""
        url = _object_url(self.info)
        headers = {"Range": f"bytes={start}-{end - 1}"}
        try:
            return retry.http_request(
                url, headers=_signed(self.info, "GET", url, headers),
                point="tier.copy").data
        except urllib.error.HTTPError as e:
            raise TierError(
                f"s3 tier read {url} [{start}:{end}): "
                f"{e.code}") from e
        except urllib.error.URLError as e:
            raise TierError(f"s3 tier unreachable: {e}") from e

    def _block(self, bno: int) -> bytes:
        with self._cache_lock:
            blk = self._cache.get(bno)
            if blk is not None:
                self._cache.move_to_end(bno)
                return blk
        start = bno * BLOCK
        end = min(start + BLOCK, self.info.size)
        blk = self._fetch(start, end)  # outside the lock (slow I/O)
        with self._cache_lock:
            self._cache[bno] = blk
            while len(self._cache) > MAX_CACHED_BLOCKS:
                self._cache.popitem(last=False)
        return blk

    def read_at(self, size: int, offset: int) -> bytes:
        if offset >= self.info.size or size <= 0:
            return b""
        end = min(offset + size, self.info.size)
        parts = []
        pos = offset
        while pos < end:
            bno = pos // BLOCK
            blk = self._block(bno)
            lo = pos - bno * BLOCK
            hi = min(end - bno * BLOCK, len(blk))
            parts.append(blk[lo:hi])
            pos = bno * BLOCK + hi
            if hi <= lo:  # short object vs recorded size
                break
        return b"".join(parts)

    def size(self) -> int:
        return self.info.size

    # -- mutations: tiered volumes are sealed read-only -------------------

    def write_at(self, data: bytes, offset: int) -> int:
        raise TierError("tiered volume is read-only; "
                        "volume.tier.download it first")

    def append(self, data: bytes) -> int:
        raise TierError("tiered volume is read-only; "
                        "volume.tier.download it first")

    def truncate(self, size: int) -> None:
        raise TierError("tiered volume is read-only")

    def flush(self) -> None:
        pass

    def sync(self) -> None:
        pass

    def close(self) -> None:
        self._cache.clear()


# -- tier movement (shell volume.tier.upload / .download) ------------------

def upload_volume_dat(base: str | Path, endpoint: str, bucket: str,
                      key: str = "", access_key: str = "",
                      secret_key: str = "", region: str = "us-east-1",
                      remove_local: bool = True,
                      chunk: int = 8 * 1024 * 1024) -> TierInfo:
    """Move ``<base>.dat`` to the S3 endpoint and write the sidecar.

    Upload is a single streamed PUT (the gateway accepts arbitrary
    sizes; multipart is unnecessary over loopback). With
    ``remove_local`` the local ``.dat`` is deleted after the sidecar is
    durably in place — crash between PUT and unlink leaves both copies,
    never neither."""
    base = str(base)
    dat = Path(base + ".dat")
    if not dat.exists():
        raise TierError(f"{dat} does not exist")
    size = dat.stat().st_size
    info = TierInfo(endpoint=endpoint, bucket=bucket,
                    key=key or (Path(base).name + ".dat"), size=size,
                    access_key=access_key, secret_key=secret_key,
                    region=region)
    url = _object_url(info)
    body = dat.read_bytes() if size <= chunk else None
    if body is not None:
        retry.http_request(url, data=body, method="PUT",
                           headers=_signed(info, "PUT", url, {}, body),
                           point="tier.copy", timeout=300)
    else:
        # stream from disk: urllib sends file-like bodies chunked; the
        # signature (when auth is on) must then be computed over the
        # full content, so large signed uploads buffer per-chunk via
        # multipart instead
        if info.access_key:
            _multipart_upload(info, dat, chunk)
        else:
            # file-like body: can't buffer through http_request (it
            # would defeat the streaming); fault point only
            faults.check("tier.copy")
            with open(dat, "rb") as f:
                req = urllib.request.Request(
                    url, data=f, method="PUT",
                    headers={"Content-Length": str(size)})
                # seaweedlint: disable=SW601 — streaming PUT with a file-like body: routing through http_request would buffer the whole volume (PR 5); deadline is the explicit 1h transfer timeout, fault injection covers retry testing
                with urllib.request.urlopen(req, timeout=3600):
                    pass
    info.save(base)
    if remove_local:
        dat.unlink()
    return info


def _multipart_upload(info: TierInfo, dat: Path, chunk: int) -> None:
    """SigV4 multipart upload through the gateway's multipart API."""
    import re

    base_url = _object_url(info)
    r = retry.http_request(
        base_url + "?uploads", method="POST",
        headers=_signed(info, "POST", base_url + "?uploads", {}),
        point="tier.copy", timeout=60)
    m = re.search(rb"<UploadId>([^<]+)</UploadId>", r.data)
    if not m:
        raise TierError("multipart initiate returned no UploadId")
    upload_id = m.group(1).decode()
    with open(dat, "rb") as f:
        part = 1
        while True:
            piece = f.read(chunk)
            if not piece:
                break
            url = f"{base_url}?partNumber={part}&uploadId={upload_id}"
            retry.http_request(
                url, data=piece, method="PUT",
                headers=_signed(info, "PUT", url, {}, piece),
                point="tier.copy", timeout=600)
            part += 1
    url = f"{base_url}?uploadId={upload_id}"
    retry.http_request(url, data=b"", method="POST",
                       headers=_signed(info, "POST", url, {}),
                       point="tier.copy", timeout=600)


def download_volume_dat(base: str | Path,
                        chunk: int = 8 * 1024 * 1024) -> None:
    """Bring a tiered ``.dat`` back to local disk and drop the sidecar
    (command_volume_tier_download.go): download to ``.dat.part``, fsync,
    rename, THEN remove the sidecar — a crash leaves a consistent state
    at every step."""
    base = str(base)
    info = TierInfo.maybe_load(base)
    if info is None:
        raise TierError(f"volume {base} is not tiered")
    dat = Path(base + ".dat")
    part = Path(base + ".dat.part")
    url = _object_url(info)
    # streamed to disk chunk-by-chunk: fault point only (buffering the
    # whole object through http_request would defeat the streaming)
    faults.check("tier.copy")
    req = urllib.request.Request(
        url, headers=_signed(info, "GET", url, {}), method="GET")
    # seaweedlint: disable=SW601 — streaming GET to disk chunk-by-chunk: http_request would buffer the whole object; deadline is the explicit 1h transfer timeout, fault injection covers retry testing
    with urllib.request.urlopen(req, timeout=3600) as r, \
            open(part, "wb") as f:
        while True:
            piece = r.read(chunk)
            if not piece:
                break
            f.write(piece)
        f.flush()
        os.fsync(f.fileno())
    got = part.stat().st_size
    if got != info.size:
        part.unlink()
        raise TierError(f"tier download size mismatch: got {got}, "
                        f"sidecar says {info.size}")
    faults.check("crash.tier.download")
    # already fsynced above; the parent-dir fsync in durable_replace is
    # what makes the rename itself survive power loss
    durability.durable_replace(part, dat, fsync_src=False)
    TierInfo.path_for(base).unlink()
