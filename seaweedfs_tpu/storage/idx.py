"""Needle index files (.idx journal, .ecx sorted index) and the in-RAM map.

Mirrors weed/storage/idx/ + weed/storage/needle_map/ (SURVEY.md §2 "Needle
map"): the .idx file is an append-only journal of 16-byte big-endian
entries (key u64, offset u32 in 8-byte units, size u32); later entries for
a key supersede earlier ones; size == 0xFFFFFFFF (tombstone) records a
delete. The .ecx file is the same entry format but sorted by key and
deduplicated — the immutable index an EC volume serves lookups from
(ec_encoder.go WriteSortedFileFromIdx).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from .types import (NEEDLE_MAP_ENTRY_SIZE, TOMBSTONE_FILE_SIZE,
                    actual_offset, is_deleted_size)

_ENTRY = struct.Struct(">QII")


@dataclass(frozen=True)
class IndexEntry:
    key: int
    offset_units: int  # multiply by 8 for the byte offset
    size: int

    @property
    def byte_offset(self) -> int:
        return actual_offset(self.offset_units)

    @property
    def is_deleted(self) -> bool:
        return is_deleted_size(self.size)

    def to_bytes(self) -> bytes:
        return _ENTRY.pack(self.key, self.offset_units, self.size)

    @classmethod
    def from_bytes(cls, buf: bytes, off: int = 0) -> "IndexEntry":
        key, offset_units, size = _ENTRY.unpack_from(buf, off)
        return cls(key, offset_units, size)


def walk_index_blob(blob: bytes) -> Iterator[IndexEntry]:
    """Yield entries from raw .idx/.ecx bytes (idx.WalkIndexFile)."""
    if len(blob) % NEEDLE_MAP_ENTRY_SIZE:
        raise ValueError(
            f"index length {len(blob)} not a multiple of "
            f"{NEEDLE_MAP_ENTRY_SIZE}")
    for off in range(0, len(blob), NEEDLE_MAP_ENTRY_SIZE):
        yield IndexEntry.from_bytes(blob, off)


def walk_index_file(path) -> Iterator[IndexEntry]:
    with open(path, "rb") as f:
        yield from walk_index_blob(f.read())


class CompactMap:
    """In-RAM needle map: key -> live IndexEntry (needle_map/compact_map.go
    in spirit; a dict here — the Go version's segmented arrays exist to
    shave GC pressure, which Python doesn't benefit from)."""

    def __init__(self) -> None:
        self._m: dict[int, IndexEntry] = {}
        self.file_count = 0
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.max_offset_units = 0
        self.max_key = 0  # heartbeat max_file_key, maintained O(1)

    def set(self, key: int, offset_units: int, size: int) -> None:
        old = self._m.get(key)
        if old is not None and not old.is_deleted:
            self.deleted_count += 1
            self.deleted_bytes += old.size
        self._m[key] = IndexEntry(key, offset_units, size)
        self.file_count += 1
        self.max_offset_units = max(self.max_offset_units, offset_units)
        self.max_key = max(self.max_key, key)

    def delete(self, key: int) -> bool:
        old = self._m.get(key)
        if old is None or old.is_deleted:
            return False
        self.deleted_count += 1
        self.deleted_bytes += old.size
        self._m[key] = IndexEntry(key, old.offset_units,
                                  TOMBSTONE_FILE_SIZE)
        return True

    def get(self, key: int) -> Optional[IndexEntry]:
        e = self._m.get(key)
        if e is None or e.is_deleted:
            return None
        return e

    def __len__(self) -> int:
        return sum(1 for e in self._m.values() if not e.is_deleted)

    def items(self) -> Iterator[IndexEntry]:
        return iter(self._m.values())

    def live_entries(self) -> list[IndexEntry]:
        return sorted((e for e in self._m.values() if not e.is_deleted),
                      key=lambda e: e.key)

    @classmethod
    def load_from_idx(cls, path) -> "CompactMap":
        m = cls()
        for e in walk_index_file(path):
            if e.is_deleted:
                m.delete(e.key)
            else:
                m.set(e.key, e.offset_units, e.size)
        return m


def write_sorted_ecx_from_idx(idx_path, ecx_path) -> int:
    """.idx journal -> sorted, deduplicated .ecx (ec_encoder.go
    WriteSortedFileFromIdx). Returns the number of live entries written.

    Entries deleted before sealing never reach the .ecx; deletes after
    sealing go to the .ecj journal instead (ec_volume_delete.go).
    """
    m = CompactMap.load_from_idx(idx_path)
    live = m.live_entries()
    with open(ecx_path, "wb") as f:
        for e in live:
            f.write(e.to_bytes())
    return len(live)


def search_ecx_blob(blob: bytes, key: int) -> Optional[IndexEntry]:
    """Binary-search a sorted .ecx blob for ``key`` (ec_volume.go
    SearchNeedleFromSortedIndex)."""
    lo, hi = 0, len(blob) // NEEDLE_MAP_ENTRY_SIZE
    while lo < hi:
        mid = (lo + hi) // 2
        e = IndexEntry.from_bytes(blob, mid * NEEDLE_MAP_ENTRY_SIZE)
        if e.key == key:
            return e
        if e.key < key:
            lo = mid + 1
        else:
            hi = mid
    return None


def search_ecx_file(path, key: int) -> Optional[IndexEntry]:
    """Binary-search the .ecx file on disk without loading it fully."""
    with open(path, "rb") as f:
        f.seek(0, 2)
        n = f.tell() // NEEDLE_MAP_ENTRY_SIZE
        lo, hi = 0, n
        while lo < hi:
            mid = (lo + hi) // 2
            f.seek(mid * NEEDLE_MAP_ENTRY_SIZE)
            e = IndexEntry.from_bytes(f.read(NEEDLE_MAP_ENTRY_SIZE))
            if e.key == key:
                return e
            if e.key < key:
                lo = mid + 1
            else:
                hi = mid
    return None
