"""Needle record codec — the unit of storage inside a volume.

Mirrors weed/storage/needle/ (needle.go, needle_read_write.go; SURVEY.md §2
"Needle codec"): a needle on disk is

    header:  Cookie u32 | NeedleId u64 | Size u32          (16 B, big-endian)
    body:    DataSize u32 | Data | Flags u8 | [optional fields by flag]
    tail:    Checksum u32 (CRC32-C of Data)
             [version 3 only: AppendAtNs u64]
    padding: zeros to the next 8-byte boundary

``Size`` in the header counts the body only. Optional fields (each gated by
a flag bit): Name (u8 len + bytes), Mime (u8 len + bytes), LastModified
(5 bytes, big-endian seconds), Ttl (2 bytes: count + unit), Pairs (u16 len
+ bytes). Version 1 (body = raw data, no DataSize/Flags) is read-supported
for old volumes; writes always use the requested version (default 3).
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

from . import crc as crc_mod
from .types import (NEEDLE_CHECKSUM_SIZE, NEEDLE_HEADER_SIZE,
                    NEEDLE_PADDING_SIZE, TIMESTAMP_SIZE)

# Flag bits (needle.go).
FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_DELETE = 0x40
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES = 5
TTL_BYTES = 2

_HEADER = struct.Struct(">IQI")


class NeedleError(ValueError):
    pass


@dataclass
class Needle:
    """In-memory needle; ``id`` is the 64-bit needle key, ``cookie`` the
    32-bit anti-guessing token embedded in the public file id."""

    cookie: int
    id: int
    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    last_modified: int = 0  # unix seconds, 5 bytes on disk
    ttl: bytes = b"\x00\x00"  # (count, unit) encoded
    pairs: bytes = b""
    append_at_ns: int = 0  # version 3 timestamp
    checksum: int | None = None  # filled on parse; None -> computed

    # -- body assembly ----------------------------------------------------

    def _effective_flags(self) -> int:
        f = self.flags
        if self.name:
            f |= FLAG_HAS_NAME
        if self.mime:
            f |= FLAG_HAS_MIME
        if self.last_modified:
            f |= FLAG_HAS_LAST_MODIFIED
        if self.ttl != b"\x00\x00":
            f |= FLAG_HAS_TTL
        if self.pairs:
            f |= FLAG_HAS_PAIRS
        return f

    def body_bytes(self, version: int = 3) -> bytes:
        if version == 1:
            return self.data
        f = self._effective_flags()
        parts = [struct.pack(">I", len(self.data)), self.data,
                 bytes([f & 0xFF])]
        if f & FLAG_HAS_NAME:
            if len(self.name) > 255:
                raise NeedleError("name longer than 255 bytes")
            parts += [bytes([len(self.name)]), self.name]
        if f & FLAG_HAS_MIME:
            if len(self.mime) > 255:
                raise NeedleError("mime longer than 255 bytes")
            parts += [bytes([len(self.mime)]), self.mime]
        if f & FLAG_HAS_LAST_MODIFIED:
            parts.append(self.last_modified.to_bytes(LAST_MODIFIED_BYTES,
                                                     "big"))
        if f & FLAG_HAS_TTL:
            parts.append(self.ttl)
        if f & FLAG_HAS_PAIRS:
            parts += [struct.pack(">H", len(self.pairs)), self.pairs]
        return b"".join(parts)

    def to_bytes(self, version: int = 3) -> bytes:
        """Full on-disk record including header, checksum, timestamp and
        padding — ready to append to a .dat file."""
        body = self.body_bytes(version)
        checksum = self.checksum if self.checksum is not None \
            else crc_mod.crc32c(self.data)
        parts = [_HEADER.pack(self.cookie, self.id, len(body)), body,
                 struct.pack(">I", checksum)]
        if version == 3:
            ns = self.append_at_ns or time.time_ns()
            parts.append(struct.pack(">Q", ns))
        raw = b"".join(parts)
        pad = (-len(raw)) % NEEDLE_PADDING_SIZE
        return raw + b"\x00" * pad

    def disk_size(self, version: int = 3) -> int:
        return len(self.to_bytes(version))

    # -- parsing ----------------------------------------------------------

    @classmethod
    def parse(cls, buf: bytes, version: int = 3,
              verify_checksum: bool = True) -> "Needle":
        """Parse one full needle record (header + body + tail)."""
        if len(buf) < NEEDLE_HEADER_SIZE:
            raise NeedleError("short needle header")
        cookie, nid, size = _HEADER.unpack_from(buf, 0)
        body = buf[NEEDLE_HEADER_SIZE:NEEDLE_HEADER_SIZE + size]
        if len(body) != size:
            raise NeedleError("short needle body")
        n = cls(cookie=cookie, id=nid)
        pos = NEEDLE_HEADER_SIZE + size
        if version == 1:
            n.data = bytes(body)
        else:
            if size < 5:
                raise NeedleError("needle body too short for v2/v3")
            data_size = struct.unpack_from(">I", body, 0)[0]
            if 4 + data_size + 1 > size:
                raise NeedleError("data size exceeds body")
            n.data = bytes(body[4:4 + data_size])
            off = 4 + data_size
            f = body[off]
            off += 1
            n.flags = f
            def _need(n_bytes: int) -> None:
                # Explicit bounds check: Python slices never raise on
                # truncation, so a corrupt body would otherwise parse
                # silently with empty/zero fields.
                if off + n_bytes > size:
                    raise NeedleError("truncated optional fields")

            if f & FLAG_HAS_NAME:
                _need(1)
                ln = body[off]
                _need(1 + ln)
                n.name = bytes(body[off + 1:off + 1 + ln])
                off += 1 + ln
            if f & FLAG_HAS_MIME:
                _need(1)
                ln = body[off]
                _need(1 + ln)
                n.mime = bytes(body[off + 1:off + 1 + ln])
                off += 1 + ln
            if f & FLAG_HAS_LAST_MODIFIED:
                _need(LAST_MODIFIED_BYTES)
                n.last_modified = int.from_bytes(
                    body[off:off + LAST_MODIFIED_BYTES], "big")
                off += LAST_MODIFIED_BYTES
            if f & FLAG_HAS_TTL:
                _need(TTL_BYTES)
                n.ttl = bytes(body[off:off + TTL_BYTES])
                off += TTL_BYTES
            if f & FLAG_HAS_PAIRS:
                _need(2)
                ln = struct.unpack_from(">H", body, off)[0]
                _need(2 + ln)
                n.pairs = bytes(body[off + 2:off + 2 + ln])
                off += 2 + ln
        if len(buf) < pos + NEEDLE_CHECKSUM_SIZE:
            raise NeedleError("missing checksum")
        n.checksum = struct.unpack_from(">I", buf, pos)[0]
        pos += NEEDLE_CHECKSUM_SIZE
        if version == 3:
            if len(buf) < pos + TIMESTAMP_SIZE:
                raise NeedleError("missing v3 timestamp")
            n.append_at_ns = struct.unpack_from(">Q", buf, pos)[0]
        if verify_checksum and version != 1:
            actual = crc_mod.crc32c(n.data)
            if actual != n.checksum:
                raise NeedleError(
                    f"crc mismatch: stored {n.checksum:#x}, "
                    f"computed {actual:#x}")
        return n


def parse_header(buf: bytes) -> tuple[int, int, int]:
    """(cookie, id, size) from the first 16 bytes."""
    if len(buf) < NEEDLE_HEADER_SIZE:
        raise NeedleError("short needle header")
    return _HEADER.unpack_from(buf, 0)


def record_size(body_size: int, version: int = 3) -> int:
    """On-disk record length for a given header ``Size`` value."""
    raw = NEEDLE_HEADER_SIZE + body_size + NEEDLE_CHECKSUM_SIZE
    if version == 3:
        raw += TIMESTAMP_SIZE
    return raw + ((-raw) % NEEDLE_PADDING_SIZE)
