"""EC artifact files: .ec00-.ec13 shard names, .ecj delete journal, .vif.

Mirrors weed/storage/erasure_coding/ (ec_encoder.go ToExt, ec_volume.go,
ec_volume_delete.go, ec_volume_info.go; SURVEY.md §2, §5):

* shard files ``<base>.ec00`` .. ``.ec13`` — raw striped blocks;
* ``.ecj`` — append-only journal of deleted needle ids (8-byte big-endian
  each), replayed over the .ecx when decoding back to a normal volume;
* ``.vif`` — VolumeInfo as JSON (the reference serializes the VolumeInfo
  protobuf with jsonpb; the field names here match its JSON form).
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator


def shard_ext(shard_id: int) -> str:
    """ec_encoder.go ToExt: ".ec00" ... ".ec13" (always two digits)."""
    if not 0 <= shard_id <= 99:
        raise ValueError(f"shard id {shard_id} out of range")
    return f".ec{shard_id:02d}"


def shard_path(base: str | Path, shard_id: int) -> Path:
    return Path(str(base) + shard_ext(shard_id))


def ecx_path(base: str | Path) -> Path:
    return Path(str(base) + ".ecx")


def ecj_path(base: str | Path) -> Path:
    return Path(str(base) + ".ecj")


def vif_path(base: str | Path) -> Path:
    return Path(str(base) + ".vif")


# -- .ecj delete journal ----------------------------------------------------


def ecj_append(base: str | Path, needle_id: int) -> None:
    """Record a post-seal delete (ec_volume_delete.go
    markNeedleDeleted writes the 8-byte needle id)."""
    with open(ecj_path(base), "ab") as f:
        f.write(struct.pack(">Q", needle_id))


def ecj_read(base: str | Path) -> list[int]:
    p = ecj_path(base)
    if not p.exists():
        return []
    blob = p.read_bytes()
    if len(blob) % 8:
        raise ValueError(f"{p} length {len(blob)} not a multiple of 8")
    return [struct.unpack_from(">Q", blob, o)[0]
            for o in range(0, len(blob), 8)]


def ecj_deleted_set(base: str | Path) -> set[int]:
    return set(ecj_read(base))


# -- .vif volume info -------------------------------------------------------


@dataclass
class VolumeInfo:
    """Subset of volume_server_pb.VolumeInfo the EC path uses; serialized
    as JSON like the reference's jsonpb-saved .vif."""

    version: int = 3
    replication: str = ""
    ttl: str = ""
    dat_file_size: int = 0  # true .dat size (pre-padding), for decode
    # RS geometry used at encode time (BASELINE config 4 parametrization);
    # 0 means the RS(10,4) default.
    data_shards: int = 0
    parity_shards: int = 0

    def save(self, base: str | Path) -> None:
        doc = {"version": self.version}
        if self.replication:
            doc["replication"] = self.replication
        if self.ttl:
            doc["ttl"] = self.ttl
        if self.dat_file_size:
            doc["datFileSize"] = self.dat_file_size
        if self.data_shards:
            doc["dataShards"] = self.data_shards
        if self.parity_shards:
            doc["parityShards"] = self.parity_shards
        vif_path(base).write_text(json.dumps(doc))

    @classmethod
    def load(cls, base: str | Path) -> "VolumeInfo":
        p = vif_path(base)
        if not p.exists():
            return cls()
        doc = json.loads(p.read_text())
        return cls(version=int(doc.get("version", 3)),
                   replication=doc.get("replication", ""),
                   ttl=doc.get("ttl", ""),
                   dat_file_size=int(doc.get("datFileSize", 0)),
                   data_shards=int(doc.get("dataShards", 0)),
                   parity_shards=int(doc.get("parityShards", 0)))


# -- shard presence ---------------------------------------------------------


def present_shards(base: str | Path, total: int = 14) -> list[int]:
    return [i for i in range(total) if shard_path(base, i).exists()]


class ShardBits:
    """Bitmask of mounted shards, as sent in heartbeats
    (ec_volume_info.go ShardBits)."""

    def __init__(self, bits: int = 0):
        self.bits = bits

    @classmethod
    def from_ids(cls, ids) -> "ShardBits":
        b = 0
        for i in ids:
            b |= 1 << i
        return cls(b)

    def add(self, shard_id: int) -> "ShardBits":
        return ShardBits(self.bits | (1 << shard_id))

    def remove(self, shard_id: int) -> "ShardBits":
        return ShardBits(self.bits & ~(1 << shard_id))

    def has(self, shard_id: int) -> bool:
        return bool(self.bits >> shard_id & 1)

    def ids(self) -> list[int]:
        return [i for i in range(self.bits.bit_length())
                if self.bits >> i & 1]

    def count(self) -> int:
        return bin(self.bits).count("1")

    def __eq__(self, other) -> bool:
        return isinstance(other, ShardBits) and self.bits == other.bits

    def __repr__(self) -> str:
        return f"ShardBits({self.ids()})"
