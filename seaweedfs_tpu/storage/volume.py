"""Append-only volume files: .dat + .idx lifecycle.

Mirrors weed/storage/ (volume.go, volume_read_write.go, volume_loading.go;
SURVEY.md §2 "Store / Volume engine"): a volume is an append-only .dat file
opened with an 8-byte superblock, needle records appended 8-byte aligned,
and a parallel .idx journal recording (key, offset, size) per write plus
tombstones per delete. Loading replays the .idx into a CompactMap; reads
seek straight to the needle (the Haystack O(1)-seek property).

Also hosts the synthetic volume generator used by tests and benchmarks
(the reference's ec_test.go builds its fixture volume the same way).
"""

from __future__ import annotations

import os
import struct
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

import numpy as np

from ..util import durability, faults
from . import backend as backend_mod
from . import needle as needle_mod
from .idx import CompactMap, IndexEntry, walk_index_blob
from .superblock import SuperBlock
from .types import (NEEDLE_HEADER_SIZE, NEEDLE_MAP_ENTRY_SIZE,
                    NEEDLE_PADDING_SIZE, TOMBSTONE_FILE_SIZE,
                    to_offset_units)


class VolumeError(RuntimeError):
    pass


def dat_path(base: str | Path) -> Path:
    return Path(str(base) + ".dat")


def idx_path(base: str | Path) -> Path:
    return Path(str(base) + ".idx")


class Volume:
    """A single writable/readable volume addressed by its base path
    (``<dir>/<collection_>?<vid>`` without extension).

    ``backend`` selects the .dat storage implementation by name
    (storage/backend.py registry: "disk", "mmap", ...); ``needle_map``
    selects the index implementation ("memory" CompactMap or the
    disk-backed "sqlite" map for volumes whose index exceeds RAM)."""

    def __init__(self, base: str | Path, volume_id: int = 0,
                 super_block: Optional[SuperBlock] = None,
                 backend: str = "disk", needle_map: str = "memory"):
        self.base = Path(base)
        self.volume_id = volume_id
        self.super_block = super_block or SuperBlock()
        self.backend_kind = backend
        #: The operator-configured kind, never mutated — backend_kind
        #: tracks the CURRENT backend ("s3" while tiered) and retier()
        #: restores this one when the .dat comes back local.
        self._configured_backend = backend
        self.needle_map_kind = needle_map
        self.nm = CompactMap()
        self._dat: Optional[backend_mod.BackendStorageFile] = None
        self._idx = None
        #: Guard: at most one compaction in flight (storage/vacuum.py).
        self.vacuum_in_progress = False
        #: Set when a .tier sidecar exists (the durable copy is on the
        #: S3 tier): writes are refused even on a kept local .dat, or
        #: they would silently diverge from the tiered bytes.
        self.readonly = False
        # Appends mutate shared file-handle state; reads use os.pread on
        # the raw fd, so only writers serialize (volume server threads
        # hit one Volume concurrently). Readers register under the lock
        # (consistent needle-map + fd snapshot) and pread outside it;
        # commit_compact drains them via _no_readers before closing and
        # swapping the fd — otherwise a read could hit a closed (or
        # kernel-reused) descriptor, or pre-compact offsets on the
        # compacted file.
        self._lock = threading.RLock()
        self._readers = 0
        #: True while commit_compact drains readers for the fd swap; new
        #: readers block on _no_readers until it clears, so a stream of
        #: overlapping reads cannot starve the swap.
        self._swap_pending = False
        self._no_readers = threading.Condition(self._lock)

    # -- lifecycle --------------------------------------------------------

    def create(self) -> "Volume":
        if dat_path(self.base).exists():
            raise VolumeError(f"{dat_path(self.base)} already exists")
        # A leftover sqlite map from a deleted volume with this id would
        # feed the fresh volume phantom entries — this is a NEW volume,
        # so any prior map is dead by definition.
        Path(str(self.base) + ".sdx").unlink(missing_ok=True)
        self._dat = backend_mod.open_backend(
            self.backend_kind, dat_path(self.base), create=True)
        self._dat.append(self.super_block.to_bytes())
        self._idx = open(idx_path(self.base), "w+b")
        self.nm = self._new_needle_map()
        return self

    def _use_native_map(self) -> bool:
        """native kind requested AND the C library builds here; else
        warn once and fall back to the memory map."""
        from ..util import glog
        from . import needle_map_native
        if needle_map_native.available():
            return True
        glog.warning("native needle map unavailable (no g++?); "
                     "volume %s falls back to the memory map",
                     self.volume_id)
        return False

    def _new_needle_map(self):
        if self.needle_map_kind == "memory":
            return CompactMap()
        if self.needle_map_kind == "native":
            if self._use_native_map():
                from .needle_map_native import NativeNeedleMap
                return NativeNeedleMap()
            return CompactMap()
        if self.needle_map_kind == "sqlite":
            from .needle_map_sqlite import SqliteNeedleMap
            return SqliteNeedleMap(
                str(self.base) + ".sdx",
                generation=self.super_block.compact_revision)
        raise VolumeError(
            f"unknown needle map kind {self.needle_map_kind!r}")

    def _load_needle_map(self):
        ip = idx_path(self.base)
        if self.needle_map_kind == "memory":
            return CompactMap.load_from_idx(ip)
        if self.needle_map_kind == "native":
            if self._use_native_map():
                from .needle_map_native import NativeNeedleMap
                return NativeNeedleMap.load_from_idx(ip)
            return CompactMap.load_from_idx(ip)
        from .needle_map_sqlite import SqliteNeedleMap
        return SqliteNeedleMap.load_from_idx(
            str(self.base) + ".sdx", ip,
            generation=self.super_block.compact_revision)

    def load(self) -> "Volume":
        p = dat_path(self.base)
        from . import tier as tier_mod
        tiered = tier_mod.TierInfo.maybe_load(self.base) is not None
        if not p.exists():
            if tiered:
                return self._load_tiered()
            raise VolumeError(f"{p} does not exist")
        if tiered:
            # -keepLocal upload: local .dat kept as a hot read cache,
            # but the S3 copy is the durable one — stay read-only even
            # across restarts (the sidecar IS the durable marker)
            self.readonly = True
        # Compaction crash recovery. States (commit renames .cpd over
        # .dat FIRST, then .cpx over .idx):
        #   .cpd + .cpx  -> crash before commit: live volume untouched,
        #                   drop both.
        #   .cpx only    -> crash BETWEEN the renames: the .dat is
        #                   already the compacted one and the old .idx
        #                   points at stale offsets — the .cpx is the
        #                   only correct index, so FINISH the commit.
        #   .cpd only    -> crash mid-compact before .cpx existed: drop.
        cpd = Path(str(self.base) + ".cpd")
        cpx = Path(str(self.base) + ".cpx")
        if cpx.exists() and not cpd.exists():
            durability.durable_replace(cpx, idx_path(self.base))
        else:
            for leftover in (cpd, cpx):
                if leftover.exists():
                    leftover.unlink()
        self._dat = backend_mod.open_backend(self.backend_kind, p)
        head = self._dat.read_at(8, 0)
        if len(head) < 8:
            raise VolumeError(f"{p} shorter than a superblock")
        extra_len = struct.unpack_from(">H", head, 6)[0]
        self.super_block = SuperBlock.parse(
            head + self._dat.read_at(extra_len, 8))
        repairs = check_volume_data_integrity(self.base, self.super_block)
        if repairs.get("dat_truncated_bytes"):
            # the check truncated the file underneath the open backend
            self._dat.close()
            self._dat = backend_mod.open_backend(self.backend_kind, p)
        ip = idx_path(self.base)
        self._idx = open(ip, "a+b") if ip.exists() else open(ip, "w+b")
        self.nm = self._load_needle_map()
        return self

    def _load_tiered(self) -> "Volume":
        """Open a volume whose .dat lives on the S3 tier (sidecar
        present, no local .dat): data bytes come through ranged GETs,
        the hot .idx stays local (the reference's tiering split). A
        tiered volume was sealed before upload, so compaction-crash
        recovery and tail-integrity repair do not apply; the backend
        itself refuses writes."""
        self._dat = backend_mod.open_backend("s3", dat_path(self.base))
        self.backend_kind = "s3"
        self.readonly = True
        head = self._dat.read_at(8, 0)
        if len(head) < 8:
            raise VolumeError(f"{self._dat.name} shorter than a "
                              f"superblock")
        extra_len = struct.unpack_from(">H", head, 6)[0]
        self.super_block = SuperBlock.parse(
            head + self._dat.read_at(extra_len, 8))
        ip = idx_path(self.base)
        if not ip.exists():
            raise VolumeError(
                f"tiered volume {self.base} has no local .idx — the "
                f"index stays local when a volume tiers")
        self._idx = open(ip, "a+b")
        self.nm = self._load_needle_map()
        return self

    def retier(self) -> None:
        """Re-point ``_dat`` at wherever the bytes NOW live (local .dat
        vs .tier sidecar) after a tier move in either direction, while
        the volume keeps serving: in-flight readers are drained exactly
        like the compaction fd swap (new readers park on _no_readers),
        then the backend handle is swapped under the lock. The needle
        map and local .idx are untouched — the tier split keeps the
        index local either way."""
        from . import tier as tier_mod
        with self._lock:
            self._swap_pending = True
            try:
                while self._readers:
                    self._no_readers.wait()
                old = self._dat
                p = dat_path(self.base)
                tiered = tier_mod.TierInfo.maybe_load(self.base) \
                    is not None
                if p.exists():
                    # local bytes (possibly a -keepLocal hot copy)
                    self._dat = backend_mod.open_backend(
                        self._configured_backend, p)
                    self.backend_kind = self._configured_backend
                    self.readonly = tiered
                elif tiered:
                    self._dat = backend_mod.open_backend("s3", p)
                    self.backend_kind = "s3"
                    self.readonly = True
                else:
                    raise VolumeError(
                        f"volume {self.volume_id}: neither {p} nor a "
                        f"tier sidecar exists")
                if old is not None:
                    old.close()
            finally:
                self._swap_pending = False
                self._no_readers.notify_all()

    def close(self) -> None:
        for f in (self._dat, self._idx):
            if f is not None:
                f.close()
        self._dat = self._idx = None
        if hasattr(self.nm, "close"):
            self.nm.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- data plane -------------------------------------------------------

    def write_needle(self, n: needle_mod.Needle) -> int:
        """Append; returns the byte offset of the record. Mirrors
        Volume.writeNeedle: append to .dat, then journal to .idx."""
        if self._dat is None:
            raise VolumeError("volume not open")
        with self._lock:
            # checked UNDER the lock: tier_move seals under this same
            # lock, so a writer that raced past an outside-the-lock
            # check could otherwise append after the seal's sync and
            # lose the needle when the local .dat is dropped
            if self.readonly:
                raise VolumeError(
                    f"volume {self.volume_id} is read-only (tiered "
                    f"copy exists; a local write would silently "
                    f"diverge from it)")
            offset = self._dat.size()
            if offset % NEEDLE_PADDING_SIZE:
                pad = (-offset) % NEEDLE_PADDING_SIZE
                self._dat.write_at(b"\x00" * pad, offset)
                offset += pad
            rec = n.to_bytes(self.super_block.version)
            body_size = needle_mod.parse_header(rec)[2]
            self._dat.write_at(rec, offset)
            faults.check("crash.append.dat")  # seaweedlint: disable=SW103 — faults.check sleeps only under an armed test-harness delay spec, never in production
            # The barrier flushes (concurrent reads see the record the
            # moment the index entry is visible) and fsyncs per the
            # [storage] policy: under `commit`, the ack this method
            # returns means the needle survives power loss.
            durability.barrier(self._dat, len(rec))
            units = to_offset_units(offset)
            self._idx.write(IndexEntry(n.id, units, body_size).to_bytes())
            faults.check("crash.append.idx")  # seaweedlint: disable=SW103 — faults.check sleeps only under an armed test-harness delay spec, never in production
            durability.barrier(self._idx, NEEDLE_MAP_ENTRY_SIZE)
            self.nm.set(n.id, units, body_size)
        return offset

    def read_needle(self, key: int, cookie: Optional[int] = None
                    ) -> needle_mod.Needle:
        with self._lock:
            while self._swap_pending:
                self._no_readers.wait()
            entry = self.nm.get(key)
            if entry is None:
                raise KeyError(f"needle {key} not found")
            if self._dat is None:
                raise VolumeError("volume not open")
            dat = self._dat
            self._readers += 1
        try:
            rec = dat.read_at(
                needle_mod.record_size(entry.size,
                                       self.super_block.version),
                entry.byte_offset)
        finally:
            with self._lock:
                self._readers -= 1
                if not self._readers:
                    self._no_readers.notify_all()
        n = needle_mod.Needle.parse(rec, self.super_block.version)
        if n.id != key:
            raise VolumeError(
                f"index/offset mismatch: wanted {key}, found {n.id}")
        if cookie is not None and n.cookie != cookie:
            raise VolumeError("cookie mismatch")
        return n

    def read_record(self, key: int) -> tuple[bytes, int]:
        """Raw on-disk record bytes for a live needle plus its byte
        offset — the replica-sync read behind volume.check.disk
        (reference: volume_grpc_read_write.go ReadNeedleBlob, which
        also hands back the undecoded record)."""
        with self._lock:
            while self._swap_pending:
                self._no_readers.wait()
            entry = self.nm.get(key)
            if entry is None:
                raise KeyError(f"needle {key} not found")
            if self._dat is None:
                raise VolumeError("volume not open")
            dat = self._dat
            self._readers += 1
        try:
            rec = dat.read_at(
                needle_mod.record_size(entry.size,
                                       self.super_block.version),
                entry.byte_offset)
        finally:
            with self._lock:
                self._readers -= 1
                if not self._readers:
                    self._no_readers.notify_all()
        return rec, entry.byte_offset

    def write_raw_record(self, rec: bytes) -> int:
        """Append a raw record produced by :meth:`read_record` on a
        sibling replica (WriteNeedleBlob): same append discipline as
        write_needle, but the bytes are trusted verbatim so CRC and
        timestamps survive the copy bit-for-bit."""
        cookie, key, body_size = needle_mod.parse_header(rec)
        want = needle_mod.record_size(body_size,
                                      self.super_block.version)
        if len(rec) != want:
            raise VolumeError(
                f"raw record length {len(rec)} != expected {want} "
                f"for size {body_size}")
        if self._dat is None:
            raise VolumeError("volume not open")
        with self._lock:
            if self.readonly:
                raise VolumeError(
                    f"volume {self.volume_id} is read-only")
            offset = self._dat.size()
            if offset % NEEDLE_PADDING_SIZE:
                pad = (-offset) % NEEDLE_PADDING_SIZE
                self._dat.write_at(b"\x00" * pad, offset)
                offset += pad
            self._dat.write_at(rec, offset)
            faults.check("crash.append.dat")  # seaweedlint: disable=SW103 — faults.check sleeps only under an armed test-harness delay spec, never in production
            durability.barrier(self._dat, len(rec))
            units = to_offset_units(offset)
            self._idx.write(IndexEntry(key, units, body_size).to_bytes())
            faults.check("crash.append.idx")  # seaweedlint: disable=SW103 — faults.check sleeps only under an armed test-harness delay spec, never in production
            durability.barrier(self._idx, NEEDLE_MAP_ENTRY_SIZE)
            self.nm.set(key, units, body_size)
        return offset

    def delete_needle(self, key: int) -> bool:
        with self._lock:
            if self.readonly:
                raise VolumeError(
                    f"volume {self.volume_id} is read-only (tiered "
                    f"copy exists; a local delete would silently "
                    f"diverge from it)")
            if not self.nm.delete(key):
                return False
            self._idx.write(
                IndexEntry(key, 0, TOMBSTONE_FILE_SIZE).to_bytes())
            durability.barrier(self._idx, NEEDLE_MAP_ENTRY_SIZE)
        return True

    def configure_replication(self, replication: str) -> None:
        """Rewrite the superblock's replica-placement byte in place
        (reference: volume_grpc_admin.go VolumeConfigure — the setting
        lives only in the superblock, so no data moves)."""
        from .superblock import ReplicaPlacement
        rp = ReplicaPlacement.parse(replication)
        with self._lock:
            if self._dat is None:
                raise VolumeError("volume not open")
            if self.readonly:
                raise VolumeError(
                    f"volume {self.volume_id} is read-only (tiered); "
                    f"download it first")
            self.super_block.replica_placement = rp
            self._dat.write_at(self.super_block.to_bytes(), 0)
            durability.barrier(self._dat,
                               len(self.super_block.to_bytes()))

    def sync(self) -> None:
        with self._lock:
            if self._dat is not None:
                self._dat.sync()
            if self._idx is not None:
                self._idx.flush()
                os.fsync(self._idx.fileno())

    @property
    def dat_size(self) -> int:
        with self._lock:
            return self._dat.size()

    def content_size(self) -> int:
        return self.dat_size


def check_volume_data_integrity(base: str | Path,
                                super_block: SuperBlock) -> dict:
    """Crash-recovery tail verification, run on every load.

    The reference's volume_checking.go verifies the LAST index entry's
    needle and refuses the volume on mismatch; here torn tails are
    REPAIRED instead (the write order is dat-then-idx-then-ack, with a
    durability barrier between each under the default ``[storage]
    fsync = "commit"`` policy, so only un-acknowledged tail records can
    be casualties): a partial trailing .idx entry is truncated, a
    trailing .idx entry whose record is missing/short/mismatched/CRC-
    torn in the .dat is dropped, and .dat bytes past the last journaled
    record (a torn append that never reached the index) are truncated.
    Trailing records are validated by full checksum walk-back — a
    crash can persist a record's header sectors without its body, so
    header-only validation would let a torn needle back into the map.
    Mid-file records behind the first valid tail entry were barriered
    before their successors were acknowledged and are not re-read here;
    read-time CRC verification and the background scrub
    (storage/scrubber.py) guard those against bit-rot. Returns a dict
    of repairs performed (empty = clean)."""
    repairs: dict[str, int] = {}
    ip, dp = idx_path(base), dat_path(base)
    dat_size = dp.stat().st_size
    version = super_block.version
    if not ip.exists():
        return repairs
    blob = ip.read_bytes()  # one read serves every pass below
    idx_size = len(blob)
    if idx_size % NEEDLE_MAP_ENTRY_SIZE:
        idx_size -= idx_size % NEEDLE_MAP_ENTRY_SIZE
        repairs["idx_partial_entry"] = 1
    # Back-walk the trailing entries. Tombstones reference no .dat bytes
    # so they can't be validated — step over them and keep checking the
    # entries beneath (a torn record under a trailing delete must still
    # be caught). If any entry proves invalid, truncate at that entry:
    # everything journaled after it belongs to the same un-acknowledged
    # crash window.
    dat_fd = os.open(dp, os.O_RDONLY)
    try:
        truncate_to = idx_size
        pos = idx_size
        while pos >= NEEDLE_MAP_ENTRY_SIZE:
            e = IndexEntry.from_bytes(blob, pos - NEEDLE_MAP_ENTRY_SIZE)
            if e.is_deleted:
                pos -= NEEDLE_MAP_ENTRY_SIZE
                continue
            rec_len = needle_mod.record_size(e.size, version)
            end = e.byte_offset + rec_len
            ok = False
            if end <= dat_size:
                rec = os.pread(dat_fd, rec_len, e.byte_offset)
                try:
                    _, nid, nsize = needle_mod.parse_header(rec)
                    # full parse = checksum verification of the body
                    needle_mod.Needle.parse(rec, version)
                    ok = nid == e.key and nsize == e.size
                except needle_mod.NeedleError:
                    ok = False
            if ok:
                break
            pos -= NEEDLE_MAP_ENTRY_SIZE
            truncate_to = pos
    finally:
        os.close(dat_fd)
    if truncate_to < idx_size:
        repairs["idx_dropped_entries"] = \
            (idx_size - truncate_to) // NEEDLE_MAP_ENTRY_SIZE
        idx_size = truncate_to
    if idx_size < len(blob):
        blob = blob[:idx_size]
        with open(ip, "r+b") as f:
            f.truncate(idx_size)
            os.fsync(f.fileno())  # a repair is itself a commit point
    # The true append frontier is the max record end over every
    # journaled (non-tombstone) entry — deleted needles' bytes are still
    # in the file; anything beyond is a torn append.
    frontier = super_block.block_size
    for e in walk_index_blob(blob):
        if e.is_deleted:
            continue
        frontier = max(
            frontier,
            e.byte_offset + needle_mod.record_size(e.size, version))
    if dat_size > frontier:
        with open(dp, "r+b") as df:
            df.truncate(frontier)
            os.fsync(df.fileno())
        repairs["dat_truncated_bytes"] = dat_size - frontier
    return repairs


def generate_synthetic_volume(base: str | Path, volume_id: int,
                              n_needles: int, avg_size: int = 1024,
                              seed: int = 0,
                              version: int = 3) -> "Volume":
    """Create a .dat/.idx pair full of random needles (the ec_test.go
    fixture pattern). Needle sizes jitter around ``avg_size``; ids are
    1..n; cookies are random. Returns the still-open Volume."""
    rng = np.random.default_rng(seed)
    sb = SuperBlock(version=version)
    vol = Volume(base, volume_id, sb).create()
    for i in range(1, n_needles + 1):
        size = max(1, int(rng.integers(avg_size // 2, avg_size * 3 // 2)))
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        n = needle_mod.Needle(
            cookie=int(rng.integers(0, 2**32)), id=i, data=data,
            append_at_ns=int(1_700_000_000_000_000_000 + i))
        vol.write_needle(n)
    vol.sync()
    return vol
