"""Volume-file backend abstraction (weed/storage/backend analog).

Mirrors ``BackendStorageFile`` (SURVEY.md §2 "Backend"): the volume
engine talks to its ``.dat`` through this seam, so local files, mmap
read paths, and tiered stores (an S3-class backend would subclass the
same interface) are interchangeable without touching volume.py.

Concurrency contract: ``read_at`` may be called from many threads
concurrently with one appender (it uses positionless pread); mutations
(``write_at``/``truncate``/``flush``/``sync``) are serialized by the
Volume's lock.
"""

from __future__ import annotations

import mmap
import os
from pathlib import Path
from typing import Callable


class BackendStorageFile:
    """One volume data file. Offsets are absolute file offsets."""

    name: str

    def read_at(self, size: int, offset: int) -> bytes:
        raise NotImplementedError

    def write_at(self, data: bytes, offset: int) -> int:
        """Write ``data`` at ``offset``; returns bytes written."""
        raise NotImplementedError

    def append(self, data: bytes) -> int:
        """Append; returns the offset the data landed at."""
        off = self.size()
        self.write_at(data, off)
        return off

    def size(self) -> int:
        raise NotImplementedError

    def truncate(self, size: int) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class DiskFile(BackendStorageFile):
    """Plain local file (backend/disk_file.go)."""

    def __init__(self, path: str | Path, create: bool = False):
        self.name = str(path)
        mode = "w+b" if create else "r+b"
        self._f = open(self.name, mode)
        self._size = os.fstat(self._f.fileno()).st_size

    def read_at(self, size: int, offset: int) -> bytes:
        return os.pread(self._f.fileno(), size, offset)

    def write_at(self, data: bytes, offset: int) -> int:
        n = os.pwrite(self._f.fileno(), data, offset)
        self._size = max(self._size, offset + n)
        return n

    def size(self) -> int:
        return self._size

    def truncate(self, size: int) -> None:
        self._f.truncate(size)
        self._size = size

    def flush(self) -> None:
        self._f.flush()

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()

    def fileno(self) -> int:
        return self._f.fileno()


class MmapFile(DiskFile):
    """Disk file whose reads go through a shared read-only mmap —
    cheaper for hot random reads (backend's mmap option). Writes go to
    the file; the mapping is refreshed when a read crosses the mapped
    frontier."""

    def __init__(self, path: str | Path, create: bool = False):
        super().__init__(path, create)
        self._map: mmap.mmap | None = None
        self._mapped = 0
        self._remap()

    def _remap(self) -> None:
        # Concurrent readers may hold a reference to the outgoing map
        # mid-slice, so it is REPLACED, never closed here — the GC
        # closes it once the last reader drops it. Publish the map
        # before its length so a racing reader sees a map at least as
        # long as the length it reads.
        mapped = os.fstat(self.fileno()).st_size
        new_map = mmap.mmap(self.fileno(), mapped,
                            prot=mmap.PROT_READ) if mapped else None
        self._map = new_map
        self._mapped = mapped

    def read_at(self, size: int, offset: int) -> bytes:
        mp, mapped = self._map, self._mapped
        end = offset + size
        if end > mapped:
            self.flush()
            self._remap()
            mp, mapped = self._map, self._mapped
        if mp is None or end > mapped:
            return super().read_at(size, offset)
        return mp[offset:min(end, mapped)]

    def truncate(self, size: int) -> None:
        self._map = None
        self._mapped = 0
        super().truncate(size)
        self._remap()

    def close(self) -> None:
        self._map = None  # GC closes once readers drain
        super().close()


def _s3_factory(path, create: bool = False) -> BackendStorageFile:
    from .tier import S3TierFile
    return S3TierFile.from_dat_path(path, create=create)


#: name -> factory(path, create) registry (the -backend flag surface).
#: "s3" is the cold tier (storage/tier.py): read-only range GETs
#: against an S3 endpoint, selected automatically by Volume.load when a
#: .tier sidecar exists.
BACKENDS: dict[str, Callable[..., BackendStorageFile]] = {
    "disk": DiskFile,
    "mmap": MmapFile,
    "s3": _s3_factory,
}


def open_backend(kind: str, path: str | Path,
                 create: bool = False) -> BackendStorageFile:
    try:
        factory = BACKENDS[kind]
    except KeyError:
        raise ValueError(f"unknown backend {kind!r}; "
                         f"have {sorted(BACKENDS)}") from None
    return factory(path, create=create)
