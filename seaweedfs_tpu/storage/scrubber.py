"""Silent-corruption defense: paced data scrubbing with quarantine
and auto-repair.

Disks rot. A needle whose body flips a bit after the write is acked
passes every durability barrier and sits undetected until a client
read trips the CRC — possibly years later, possibly after the last
good replica has been rebalanced away. This module walks the data at
rest *proactively*:

- **Plain volumes** — every live needle record is re-read and
  CRC-verified (``Needle.parse`` runs the same checksum the read path
  does). A corrupt record's bytes are moved into a per-volume
  quarantine directory (``<base>.quarantine/``) for forensics, and
  when a fetcher for replica bytes is supplied the needle is repaired
  by re-appending the replica's raw record (``write_raw_record``) —
  the needle map flips to the fresh copy and the rotten bytes become
  ordinary vacuum garbage.

- **EC volumes** — each shard file carries a sha256 baseline in the
  ``<base>.scrub`` sidecar, established on the first scrub after a
  parity-consistency proof (reconstruct every non-source shard from
  ``k`` sources and compare — a rotten shard cannot pass). Later
  scrubs hash-compare against the baseline: a mismatched shard is
  quarantined **by moving the file** (``rebuild_ec_files`` refuses to
  overwrite an existing shard) and rebuilt from the survivors, then
  re-verified against the baseline hash.

Scrubbing is paced: a token-bucket :class:`RatePacer` caps the byte
read rate (``[storage.scrub] rate_bytes_per_second``) so a background
scrub never steals the disk from foreground reads — the bench's
``--scrub-overhead`` stage holds the paced scrub under 5% foreground
cost. Cluster integration lives in cluster/jobs.py (the ``scrub`` job
kind), cluster/master.py (``/cluster/scrub``), and the shell
(``scrub.start`` / ``scrub.status``); metrics render on the volume
server's ``/metrics`` as the ``seaweed_scrub_*`` family.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from pathlib import Path
from typing import Callable, Optional

from ..util import glog
from ..util.stats import Metrics
from . import ec_files
from . import needle as needle_mod

#: Rendered by the volume server's /metrics next to the store families.
METRICS = Metrics(namespace="seaweed")

#: Bytes hashed/reconstructed per EC verify step (also the pacer grain).
EC_CHUNK_BYTES = 4 * 1024 * 1024

_DEFAULT_RATE = 8 * 1024 * 1024
_RATE_BYTES_PER_SECOND = _DEFAULT_RATE


def configure(rate_bytes_per_second: Optional[int] = None) -> None:
    global _RATE_BYTES_PER_SECOND
    if rate_bytes_per_second is not None:
        _RATE_BYTES_PER_SECOND = int(rate_bytes_per_second)


def configure_from(conf: dict) -> None:
    """Apply a ``[storage.scrub]`` config-file section."""
    s = conf.get("storage") if isinstance(conf, dict) else None
    sc = s.get("scrub") if isinstance(s, dict) else None
    if isinstance(sc, dict):
        configure(rate_bytes_per_second=sc.get("rate_bytes_per_second"))


def configured_rate() -> int:
    return _RATE_BYTES_PER_SECOND


class RatePacer:
    """Token bucket over bytes: ``take(n)`` blocks until the scrub may
    read another ``n`` bytes. Capacity is one second of budget so a
    scrub that falls behind (slow CRC pass) bursts back to the target
    rate without ever exceeding it on average; ``rate <= 0`` disables
    pacing (tests, explicit full-speed runs)."""

    def __init__(self, bytes_per_second: Optional[int] = None,
                 clock=time.monotonic, sleep=time.sleep):
        self.rate = (_RATE_BYTES_PER_SECOND if bytes_per_second is None
                     else int(bytes_per_second))
        self._clock = clock
        self._sleep = sleep
        self._tokens = float(max(self.rate, 0))
        self._last = clock()
        self.slept_seconds = 0.0

    def take(self, n: int) -> None:
        if self.rate <= 0 or n <= 0:
            return
        now = self._clock()
        self._tokens = min(float(self.rate),
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        self._tokens -= n
        if self._tokens < 0:
            wait = -self._tokens / self.rate
            self.slept_seconds += wait
            self._sleep(wait)
            self._last = self._clock()


# ---------------------------------------------------------------------------
# per-volume scrub state sidecar
# ---------------------------------------------------------------------------


def state_path(base: str | Path) -> Path:
    return Path(str(base) + ".scrub")


def quarantine_dir(base: str | Path) -> Path:
    return Path(str(base) + ".quarantine")


def load_state(base: str | Path) -> dict:
    try:
        with open(state_path(base), "rb") as f:
            d = json.loads(f.read() or b"{}")
            return d if isinstance(d, dict) else {}
    except (OSError, ValueError):
        return {}


def save_state(base: str | Path, state: dict) -> None:
    """Durable sidecar write: tmp + fsync + rename into place (the
    startup orphan sweep reclaims a ``.tmp`` left by a crash here)."""
    from ..util import durability
    p = state_path(base)
    tmp = Path(str(p) + ".tmp")
    with open(tmp, "wb") as f:
        f.write(json.dumps(state, indent=1, sort_keys=True).encode())
    durability.durable_replace(tmp, p)


def _quarantine_blob(base: str | Path, name: str, data: bytes) -> Path:
    qdir = quarantine_dir(base)
    qdir.mkdir(exist_ok=True)
    dest = qdir / name
    with open(dest, "wb") as f:
        f.write(data)
    METRICS.counter("scrub_quarantined_total").inc()
    return dest


def _quarantine_file(base: str | Path, path: Path) -> Path:
    qdir = quarantine_dir(base)
    qdir.mkdir(exist_ok=True)
    dest = qdir / path.name
    # plain rename, deliberately NOT durable_replace: quarantine is
    # forensic best-effort, and the source file is corrupt anyway
    os.replace(path, dest)  # seaweedlint: disable=SW901 — forensic move of corrupt bytes, not a commit point
    METRICS.counter("scrub_quarantined_total").inc()
    return dest


# ---------------------------------------------------------------------------
# plain-volume scrub
# ---------------------------------------------------------------------------


def scrub_volume(vol, pacer: Optional[RatePacer] = None,
                 fetch_record: Optional[Callable[[int],
                                                 Optional[bytes]]] = None,
                 progress: Optional[Callable[[float], None]] = None
                 ) -> dict:
    """Walk every live needle of ``vol``, CRC-verifying the on-disk
    record. Corrupt records are quarantined; when ``fetch_record(key)``
    can produce replica bytes for the needle, the record is repaired by
    re-append and re-verified. Returns a result dict (also folded into
    the ``<base>.scrub`` sidecar)."""
    version = vol.super_block.version
    entries = vol.nm.live_entries()
    res = {"checked": 0, "bytes": 0, "corrupt": 0, "repaired": 0,
           "repair_failed": 0, "quarantined": []}
    for i, e in enumerate(entries):
        rec_len = needle_mod.record_size(e.size, version)
        if pacer is not None:
            pacer.take(rec_len)
        try:
            rec, _off = vol.read_record(e.key)
        except KeyError:
            continue      # deleted between snapshot and read
        res["checked"] += 1
        res["bytes"] += len(rec)
        METRICS.counter("scrub_needles_total").inc()
        METRICS.counter("scrub_bytes_total", kind="needle").inc(len(rec))
        ok = False
        try:
            n = needle_mod.Needle.parse(rec, version)
            ok = n.id == e.key
        except needle_mod.NeedleError:
            ok = False
        if ok:
            if progress is not None and len(entries):
                progress((i + 1) / len(entries))
            continue
        res["corrupt"] += 1
        METRICS.counter("scrub_corrupt_total", kind="needle").inc()
        q = _quarantine_blob(
            vol.base, f"needle-{vol.volume_id}-{e.key}.rec", rec)
        res["quarantined"].append(str(q))
        glog.warning("scrub: volume %d needle %d failed CRC "
                     "(%d bytes quarantined to %s)", vol.volume_id,
                     e.key, len(rec), q)
        repaired = False
        if fetch_record is not None and not vol.readonly:
            good = None
            try:
                good = fetch_record(e.key)
            except Exception as err:  # noqa: BLE001 — repair is best-effort
                glog.warning("scrub: replica fetch for needle %d "
                             "failed: %s", e.key, err)
            if good:
                try:
                    # verify the replica's bytes BEFORE trusting them
                    needle_mod.Needle.parse(good, version)
                    vol.write_raw_record(good)
                    # prove the repair: the map now points at the
                    # fresh copy and it parses clean
                    rec2, _ = vol.read_record(e.key)
                    needle_mod.Needle.parse(rec2, version)
                    repaired = True
                except Exception as err:  # noqa: BLE001 — NeedleError included
                    glog.warning("scrub: repair of needle %d failed: "
                                 "%s", e.key, err)
        if repaired:
            res["repaired"] += 1
            METRICS.counter("scrub_repaired_total", kind="needle").inc()
            glog.info("scrub: volume %d needle %d repaired from "
                      "replica", vol.volume_id, e.key)
        else:
            res["repair_failed"] += 1
            METRICS.counter("scrub_repair_failed_total",
                            kind="needle").inc()
        if progress is not None and len(entries):
            progress((i + 1) / len(entries))
    st = load_state(vol.base)
    st["volume"] = {"last_scrub_unix": time.time(),
                    "checked": res["checked"], "bytes": res["bytes"],
                    "corrupt": res["corrupt"],
                    "repaired": res["repaired"]}
    save_state(vol.base, st)
    METRICS.gauge("scrub_last_run_unix").set(time.time())
    return res


# ---------------------------------------------------------------------------
# EC shard scrub
# ---------------------------------------------------------------------------


def _hash_shard(path: Path, pacer: Optional[RatePacer]) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(EC_CHUNK_BYTES)
            if not chunk:
                break
            if pacer is not None:
                pacer.take(len(chunk))
            h.update(chunk)
            METRICS.counter("scrub_bytes_total", kind="ec").inc(
                len(chunk))
    return h.hexdigest()


def _parity_consistent(base, scheme, present: list[int],
                       pacer: Optional[RatePacer]) -> bool:
    """Baseline bootstrap proof: reconstruct every present shard
    outside the first-``k`` source set from the sources and compare
    bytes. Any rot in sources or targets breaks the equality (RS words
    mix every source into every target), so a True here certifies the
    whole present set."""
    import numpy as np
    k = scheme.data_shards
    sources = present[:k]
    targets = [i for i in present if i not in sources]
    if not targets:
        return True       # nothing to cross-check against
    size = ec_files.shard_path(base, sources[0]).stat().st_size
    enc = scheme.encoder
    pos = 0
    with _open_shards(base, sources) as src_fds:
        while pos < size:
            take = min(EC_CHUNK_BYTES, size - pos)
            if pacer is not None:
                pacer.take((len(sources) + len(targets)) * take)
            buf = np.empty((1, k, take), dtype=np.uint8)
            for s, fd in enumerate(src_fds):
                got = os.pread(fd, take, pos)
                if len(got) != take:
                    return False
                buf[0, s, :] = np.frombuffer(got, dtype=np.uint8)
            out = enc.reconstruct_batch_host(buf, sources, targets)
            for t, sid in enumerate(targets):
                with open(ec_files.shard_path(base, sid), "rb") as f:
                    f.seek(pos)
                    disk = f.read(take)
                if disk != bytes(out[0, t, :take].tobytes()):
                    return False
            pos += take
    return True


class _open_shards:
    def __init__(self, base, ids):
        self.paths = [ec_files.shard_path(base, i) for i in ids]
        self.fds: list[int] = []

    def __enter__(self):
        for p in self.paths:
            self.fds.append(os.open(p, os.O_RDONLY))
        return self.fds

    def __exit__(self, *exc):
        for fd in self.fds:
            try:
                os.close(fd)
            except OSError:  # seaweedlint: disable=SW301 — best-effort close-all
                pass


def scrub_ec(base: str | Path, scheme, pacer: Optional[RatePacer] = None,
             repair: bool = True,
             progress: Optional[Callable[[float], None]] = None) -> dict:
    """Verify the EC shards of ``base`` against their sha256 baseline
    (establishing it under a parity-consistency proof on first scrub).
    Mismatched shards are quarantined by move and rebuilt from the
    survivors when ``repair`` and at least ``k`` clean shards remain."""
    base = Path(base)
    present = ec_files.present_shards(base, scheme.total_shards)
    res = {"shards": len(present), "corrupt": 0, "repaired": 0,
           "repair_failed": 0, "baseline": False, "quarantined": []}
    if not present:
        return res
    st = load_state(base)
    baseline = st.get("shard_sha256")
    hashes = {}
    for i, sid in enumerate(present):
        hashes[sid] = _hash_shard(ec_files.shard_path(base, sid), pacer)
        METRICS.counter("scrub_shards_total").inc()
        if progress is not None:
            progress(0.8 * (i + 1) / len(present))
    if not isinstance(baseline, dict) or not baseline:
        if _parity_consistent(base, scheme, present, pacer):
            st["shard_sha256"] = {str(s): h for s, h in hashes.items()}
            st["ec"] = {"last_scrub_unix": time.time(),
                        "shards": len(present), "corrupt": 0}
            save_state(base, st)
            res["baseline"] = True
        else:
            # rot before any baseline existed: every shard is suspect
            # and none can be singled out — report, never guess.
            res["corrupt"] = -1
            METRICS.counter("scrub_corrupt_total",
                            kind="ec_unattributed").inc()
            glog.error("scrub: EC volume %s parity-inconsistent with "
                       "no baseline; manual repair required", base)
        METRICS.gauge("scrub_last_run_unix").set(time.time())
        return res
    bad = [sid for sid in present
           if baseline.get(str(sid)) not in (None, hashes[sid])]
    for sid in bad:
        res["corrupt"] += 1
        METRICS.counter("scrub_corrupt_total", kind="ec").inc()
        q = _quarantine_file(base, ec_files.shard_path(base, sid))
        res["quarantined"].append(str(q))
        glog.warning("scrub: EC volume %s shard %d sha256 mismatch "
                     "(quarantined to %s)", base, sid, q)
        if not repair:
            res["repair_failed"] += 1
            continue
        try:
            from ..pipeline.rebuild import rebuild_ec_files
            rebuild_ec_files(base, scheme, wanted=[sid])
            rebuilt = _hash_shard(ec_files.shard_path(base, sid), pacer)
            if rebuilt != baseline.get(str(sid)):
                raise RuntimeError(
                    f"rebuilt shard {sid} hash {rebuilt[:12]} != "
                    f"baseline {str(baseline.get(str(sid)))[:12]}")
            res["repaired"] += 1
            METRICS.counter("scrub_repaired_total", kind="ec").inc()
            glog.info("scrub: EC volume %s shard %d rebuilt and "
                      "verified against baseline", base, sid)
        except Exception as err:  # noqa: BLE001 — keep scrubbing other shards
            res["repair_failed"] += 1
            METRICS.counter("scrub_repair_failed_total", kind="ec").inc()
            glog.error("scrub: EC volume %s shard %d rebuild failed: "
                       "%s", base, sid, err)
    # fold shards that joined since the baseline (e.g. rebuilt
    # elsewhere) into it so the next scrub covers them too
    for sid, h in hashes.items():
        if sid not in bad:
            st["shard_sha256"][str(sid)] = h
    st["ec"] = {"last_scrub_unix": time.time(), "shards": len(present),
                "corrupt": res["corrupt"],
                "repaired": res["repaired"]}
    save_state(base, st)
    METRICS.gauge("scrub_last_run_unix").set(time.time())
    if progress is not None:
        progress(1.0)
    return res


def debug_payload() -> dict:
    return {"rate_bytes_per_second": _RATE_BYTES_PER_SECOND}
