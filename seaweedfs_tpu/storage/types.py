"""Core storage constants and id types.

Mirrors weed/storage/types/ (needle_types.go, offset.go; SURVEY.md §2
"Needle map" row): 16-byte index entries, 8-byte offset units (giving the
32 GB max volume size), tombstone size marker, and the
``<vid>,<id-hex><cookie-hex>`` file-id string format used across every
layer of the reference.
"""

from __future__ import annotations

from dataclasses import dataclass

# Sizes in bytes (types/needle_types.go).
COOKIE_SIZE = 4
NEEDLE_ID_SIZE = 8
SIZE_SIZE = 4
OFFSET_SIZE = 4
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16
NEEDLE_CHECKSUM_SIZE = 4
TIMESTAMP_SIZE = 8  # version-3 appended nanosecond timestamp

#: Needle records are padded so every offset is a multiple of 8; offsets in
#: the index are stored in these units, extending 32-bit offsets to 32 GB.
NEEDLE_PADDING_SIZE = 8

#: Size value marking a deleted needle in .idx entries (math.MaxUint32).
TOMBSTONE_FILE_SIZE = 0xFFFFFFFF

#: Maximum volume size addressable by 4-byte offsets in 8-byte units.
MAX_POSSIBLE_VOLUME_SIZE = (2**32) * NEEDLE_PADDING_SIZE  # 32 GiB


def actual_offset(offset_units: int) -> int:
    """Index offset field -> byte offset in the .dat file."""
    return offset_units * NEEDLE_PADDING_SIZE


def to_offset_units(byte_offset: int) -> int:
    if byte_offset % NEEDLE_PADDING_SIZE:
        raise ValueError(f"offset {byte_offset} not 8-byte aligned")
    return byte_offset // NEEDLE_PADDING_SIZE


def is_deleted_size(size: int) -> bool:
    return size == TOMBSTONE_FILE_SIZE


@dataclass(frozen=True)
class FileId:
    """A full file id ``<volume>,<key-hex><cookie-hex>`` (weed/storage/
    needle/file_id.go). The hex key is written without leading zeros; the
    cookie is always exactly 8 hex chars appended to it."""

    volume_id: int
    key: int
    cookie: int

    def __str__(self) -> str:
        return f"{self.volume_id},{self.key:x}{self.cookie:08x}"

    @classmethod
    def parse(cls, fid: str) -> "FileId":
        try:
            vid_str, rest = fid.split(",", 1)
            volume_id = int(vid_str)
            if len(rest) <= 8:
                raise ValueError(fid)
            key = int(rest[:-8], 16)
            cookie = int(rest[-8:], 16)
        except (ValueError, IndexError) as e:
            raise ValueError(f"malformed file id {fid!r}") from e
        return cls(volume_id=volume_id, key=key, cookie=cookie)
