"""Volume superblock — the first 8 bytes of every .dat file.

Mirrors weed/storage/super_block/ (super_block.go, replica_placement.go;
SURVEY.md §2 "Store / Volume engine", §5 checkpoint artifacts):

    byte 0   version (3 current)
    byte 1   replica placement, encoded DC*100 + rack*10 + sameRack
    byte 2-3 TTL (count u8, unit u8)
    byte 4-5 compact revision, big-endian u16
    byte 6-7 extra-block size, big-endian u16 (followed by that many bytes)

The TTL unit byte: 0 empty, 1 minute, 2 hour, 3 day, 4 week, 5 month,
6 year (volume_ttl.go).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

SUPER_BLOCK_SIZE = 8
CURRENT_VERSION = 3

_TTL_UNITS = {"": 0, "m": 1, "h": 2, "d": 3, "w": 4, "M": 5, "y": 6}
_TTL_UNITS_REV = {v: k for k, v in _TTL_UNITS.items()}


@dataclass(frozen=True)
class ReplicaPlacement:
    """Replica placement code ``<dc><rack><sameRack>`` e.g. "001", "110"."""

    same_rack: int = 0
    diff_rack: int = 0
    diff_dc: int = 0

    @classmethod
    def parse(cls, s: str) -> "ReplicaPlacement":
        if len(s) != 3 or not s.isdigit():
            raise ValueError(f"bad replica placement {s!r}")
        return cls(diff_dc=int(s[0]), diff_rack=int(s[1]),
                   same_rack=int(s[2]))

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls(diff_dc=b // 100, diff_rack=(b // 10) % 10,
                   same_rack=b % 10)

    def to_byte(self) -> int:
        return self.diff_dc * 100 + self.diff_rack * 10 + self.same_rack

    def copy_count(self) -> int:
        return self.diff_dc + self.diff_rack + self.same_rack + 1

    def __str__(self) -> str:
        return f"{self.diff_dc}{self.diff_rack}{self.same_rack}"


@dataclass(frozen=True)
class Ttl:
    """Volume TTL: count + unit char, e.g. "3d" (volume_ttl.go)."""

    count: int = 0
    unit: str = ""

    @classmethod
    def parse(cls, s: str) -> "Ttl":
        if not s or s == "0":
            return cls()
        unit = s[-1] if s[-1] in _TTL_UNITS else "m"
        num = s[:-1] if s[-1] in _TTL_UNITS else s
        return cls(count=int(num), unit=unit)

    @classmethod
    def from_bytes(cls, b: bytes) -> "Ttl":
        if len(b) != 2:
            raise ValueError("ttl must be 2 bytes")
        if b[0] == 0:
            return cls()
        return cls(count=b[0], unit=_TTL_UNITS_REV.get(b[1], ""))

    def to_bytes(self) -> bytes:
        if self.count == 0:
            return b"\x00\x00"
        return bytes([self.count & 0xFF, _TTL_UNITS.get(self.unit, 0)])

    def __str__(self) -> str:
        return "" if self.count == 0 else f"{self.count}{self.unit}"

    @property
    def seconds(self) -> int:
        """TTL duration in seconds (0 = no expiry), volume_ttl.go's
        Minutes()*60 equivalent."""
        per = {"": 0, "m": 60, "h": 3600, "d": 86400, "w": 7 * 86400,
               "M": 30 * 86400, "y": 365 * 86400}
        return self.count * per.get(self.unit, 60)


@dataclass
class SuperBlock:
    version: int = CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(
        default_factory=ReplicaPlacement)
    ttl: Ttl = field(default_factory=Ttl)
    compact_revision: int = 0
    extra: bytes = b""

    def to_bytes(self) -> bytes:
        head = struct.pack(
            ">BB2sHH", self.version, self.replica_placement.to_byte(),
            self.ttl.to_bytes(), self.compact_revision, len(self.extra))
        return head + self.extra

    @classmethod
    def parse(cls, buf: bytes) -> "SuperBlock":
        if len(buf) < SUPER_BLOCK_SIZE:
            raise ValueError("short superblock")
        version, rp, ttl_b, rev, extra_len = struct.unpack_from(
            ">BB2sHH", buf, 0)
        extra = bytes(buf[SUPER_BLOCK_SIZE:SUPER_BLOCK_SIZE + extra_len])
        if len(extra) != extra_len:
            raise ValueError("short superblock extra block")
        return cls(version=version,
                   replica_placement=ReplicaPlacement.from_byte(rp),
                   ttl=Ttl.from_bytes(ttl_b), compact_revision=rev,
                   extra=extra)

    @property
    def block_size(self) -> int:
        return SUPER_BLOCK_SIZE + len(self.extra)
