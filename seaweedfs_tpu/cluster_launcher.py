"""``weed cluster`` — spawn a multi-process localhost cluster.

The reference ships docker-compose topologies
(docker/local-cluster-compose.yml: 3 masters + volumes + filer + s3,
SURVEY.md §2 "Docker/compose") as the way to stand up a realistic
multi-node cluster on one machine. This environment has no docker, so
the same role is played process-natively: one command forks the REAL
``python -m seaweedfs_tpu master|volume|filer|s3|webdav`` entrypoints
onto localhost ports, wires peers/heartbeats, writes a manifest, and
tears everything down on SIGINT/SIGTERM — processes are cheap, exactly
the reference's own testing philosophy (SURVEY.md §4 "multi-node
without a real cluster").

    python -m seaweedfs_tpu cluster -dir /tmp/c1 -masters 3 -volumes 4 \
        -filer -s3

Ports: masters at portBase, portBase+1, ...; volumes at portBase+100+i;
filer at portBase+200; s3 at portBase+300; webdav at portBase+400. Each
server's gRPC twin rides the usual +10000 offset.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import Optional


def _spawn(argv: list[str], log_path: Path) -> subprocess.Popen:
    log = open(log_path, "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu"] + argv,
        stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True)


class LocalCluster:
    """Programmatic form of ``weed cluster`` (tests use this)."""

    def __init__(self, base_dir: str | Path, masters: int = 1,
                 volumes: int = 2, filer: bool = False,
                 s3: bool = False, webdav: bool = False,
                 port_base: int = 9333, volume_max: int = 8,
                 pulse_seconds: float = 1.0, config: str = "",
                 replication: str = ""):
        self.base = Path(base_dir)
        self.base.mkdir(parents=True, exist_ok=True)
        self.port_base = port_base
        self.n_masters = masters
        self.n_volumes = volumes
        self.with_filer = filer
        self.with_s3 = s3
        self.with_webdav = webdav
        self.volume_max = volume_max
        self.pulse = pulse_seconds
        self.config = config
        self.replication = replication
        self.procs: dict[str, subprocess.Popen] = {}

    # -- addresses ---------------------------------------------------------

    @property
    def master_urls(self) -> list[str]:
        return [f"127.0.0.1:{self.port_base + i}"
                for i in range(self.n_masters)]

    @property
    def volume_urls(self) -> list[str]:
        return [f"127.0.0.1:{self.port_base + 100 + i}"
                for i in range(self.n_volumes)]

    @property
    def filer_url(self) -> str:
        return f"127.0.0.1:{self.port_base + 200}"

    @property
    def s3_url(self) -> str:
        return f"127.0.0.1:{self.port_base + 300}"

    @property
    def webdav_url(self) -> str:
        return f"127.0.0.1:{self.port_base + 400}"

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "LocalCluster":
        peers = ",".join(self.master_urls)
        sec = ["-config", self.config] if self.config else []
        for i, url in enumerate(self.master_urls):
            port = int(url.rsplit(":", 1)[1])
            mdir = self.base / f"m{i}"
            mdir.mkdir(exist_ok=True)
            argv = ["master", "-port", str(port), "-mdir", str(mdir),
                    "-pulseSeconds", str(self.pulse)] + sec
            if self.n_masters > 1:
                argv += ["-peers", peers]
            if self.replication:
                argv += ["-defaultReplication", self.replication]
            self.procs[f"master{i}"] = _spawn(
                argv, self.base / f"master{i}.log")
        for i, url in enumerate(self.volume_urls):
            port = int(url.rsplit(":", 1)[1])
            vdir = self.base / f"v{i}"
            vdir.mkdir(exist_ok=True)
            self.procs[f"volume{i}"] = _spawn(
                ["volume", "-port", str(port), "-dir", str(vdir),
                 "-mserver", peers, "-max", str(self.volume_max),
                 "-rack", f"r{i % 2}",
                 "-pulseSeconds", str(self.pulse)] + sec,
                self.base / f"volume{i}.log")
        if self.with_filer:
            self.procs["filer"] = _spawn(
                ["filer", "-port", str(self.port_base + 200),
                 "-master", self.master_urls[0]] + sec,
                self.base / "filer.log")
        # Gateways take TLS credentials via -securityConfig (on the s3
        # gateway, -config means identities JSON, not security.toml).
        gwsec = (["-securityConfig", self.config] if self.config else [])
        # the same TOML also carries [ingress]/[qos]/[retry] for the
        # gateways (their -config slot means identities JSON on s3)
        gwsec += (["-toml", self.config] if self.config else [])
        if self.with_s3:
            self.procs["s3"] = _spawn(
                ["s3", "-port", str(self.port_base + 300),
                 "-filer", self.filer_url,
                 "-master", self.master_urls[0]] + gwsec,
                self.base / "s3.log")
        if self.with_webdav:
            self.procs["webdav"] = _spawn(
                ["webdav", "-port", str(self.port_base + 400),
                 "-filer", self.filer_url,
                 "-master", self.master_urls[0]] + gwsec,
                self.base / "webdav.log")
        self._write_manifest()
        return self

    def _write_manifest(self) -> None:
        manifest = {
            "masters": self.master_urls,
            "volumes": self.volume_urls,
            "filer": self.filer_url if self.with_filer else None,
            "s3": self.s3_url if self.with_s3 else None,
            "webdav": self.webdav_url if self.with_webdav else None,
            "pids": {k: p.pid for k, p in self.procs.items()},
        }
        (self.base / "cluster.json").write_text(
            json.dumps(manifest, indent=1))

    def wait_ready(self, timeout: float = 30.0) -> None:
        """Block until a master answers /cluster/status with every
        volume server registered (raises TimeoutError otherwise)."""
        import urllib.request
        deadline = time.time() + timeout
        last = ""
        while time.time() < deadline:
            self._reap_dead()
            for murl in self.master_urls:
                try:
                    # seaweedlint: disable=SW601 — launcher readiness poll on localhost: bounded by its own deadline loop + 2s timeout, runs before the cluster (and its breaker state) exists
                    with urllib.request.urlopen(
                            f"http://{murl}/cluster/status",
                            timeout=2) as r:
                        st = json.load(r)
                except Exception as e:  # noqa: BLE001 — keep polling
                    last = f"{murl}: {e}"
                    continue
                topo = st.get("Topology") or {}
                count = sum(
                    len(nodes)
                    for dc in (topo.get("DataCenters") or {}).values()
                    for nodes in dc.values())
                if count >= self.n_volumes:
                    return
                last = f"{murl}: {count}/{self.n_volumes} volumes"
            time.sleep(0.3)
        raise TimeoutError(f"cluster not ready: {last}")

    def _reap_dead(self) -> None:
        dead = [k for k, p in self.procs.items()
                if p.poll() is not None]
        if dead:
            raise RuntimeError(
                f"cluster processes died: {dead} "
                f"(see logs under {self.base})")

    def stop(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    p.terminate()
        deadline = time.time() + 10
        for p in self.procs.values():
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    p.kill()
        self.procs.clear()

    def __enter__(self) -> "LocalCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="cluster",
        description="spawn a localhost multi-process cluster "
                    "(docker/local-cluster-compose.yml analog)")
    p.add_argument("-dir", required=True, help="base data/log directory")
    p.add_argument("-masters", type=int, default=1)
    p.add_argument("-volumes", type=int, default=2)
    p.add_argument("-filer", action="store_true")
    p.add_argument("-s3", action="store_true")
    p.add_argument("-webdav", action="store_true")
    p.add_argument("-portBase", type=int, default=9333)
    p.add_argument("-replication", default="")
    p.add_argument("-pulseSeconds", type=float, default=2.0)
    p.add_argument("-config", default="",
                   help="security.toml handed to every server")
    args = p.parse_args(argv)
    if args.s3 and not args.filer:
        print("error: -s3 requires -filer", file=sys.stderr)
        return 2
    if args.webdav and not args.filer:
        print("error: -webdav requires -filer", file=sys.stderr)
        return 2

    c = LocalCluster(args.dir, masters=args.masters,
                     volumes=args.volumes, filer=args.filer,
                     s3=args.s3, webdav=args.webdav,
                     port_base=args.portBase,
                     pulse_seconds=args.pulseSeconds,
                     config=args.config,
                     replication=args.replication).start()
    try:
        c.wait_ready()
        print(f"cluster up: {json.dumps(json.loads((c.base / 'cluster.json').read_text()))}")
        stop = [False]

        def _sig(*_):
            stop[0] = True
        signal.signal(signal.SIGINT, _sig)
        signal.signal(signal.SIGTERM, _sig)
        while not stop[0]:
            time.sleep(0.5)
            c._reap_dead()
    except (TimeoutError, RuntimeError) as e:
        print(f"error: {e}", file=sys.stderr)
        c.stop()
        return 1
    c.stop()
    return 0
