"""On-read image resizing (weed/images analog).

``resized`` reproduces weed/images/resizing.go Resized semantics: when
width/height are given and the blob is a decodable image, scale it —
mode "" (fit within box, keep ratio), "fill" (cover + center crop), or
"fit" (exact box, may distort); otherwise return the original bytes
unchanged. Wired into the volume server's GET path via
``?width=&height=&mode=`` query parameters.
"""

from .resize import resized

__all__ = ["resized"]
