"""Image scaling for the read path (weed/images/resizing.go)."""

from __future__ import annotations

import io
from typing import Optional, Tuple

_FORMATS = {"JPEG": "image/jpeg", "PNG": "image/png", "GIF": "image/gif",
            "WEBP": "image/webp", "BMP": "image/bmp"}

#: Upper bound on any produced (or intermediate) image, in pixels —
#: query parameters are unauthenticated input, and an unbounded
#: ``?width=100000&height=100000&mode=fit`` would otherwise make Pillow
#: allocate a multi-GB buffer inside the volume server.
MAX_PIXELS = 16_000_000


def resized(data: bytes, width: int = 0, height: int = 0,
            mode: str = "") -> Tuple[bytes, str]:
    """Return (bytes, mime). Unchanged input when no dimensions are
    requested, the payload is not a decodable image, or it is already
    small enough (the reference only ever downscales)."""
    if width <= 0 and height <= 0:
        return data, ""
    try:
        from PIL import Image
    except ImportError:  # pragma: no cover — PIL ships in this env
        return data, ""
    try:
        img = Image.open(io.BytesIO(data))
        img.load()
    except Exception:  # noqa: BLE001 — not an image: serve as-is
        return data, ""
    fmt = (img.format or "PNG").upper()
    w, h = img.size
    if width < 0 or height < 0:
        return data, _FORMATS.get(fmt, "")
    tw, th = width or w, height or h
    if w <= tw and h <= th and mode != "fit":
        return data, _FORMATS.get(fmt, "")
    # The pixel cap is evaluated on what would actually be ALLOCATED
    # per mode (output, plus fill's cover intermediate) — capping
    # tw*th up front would wrongly reject a small single-axis
    # downscale of a large image (th defaults to the original height).
    if mode == "fit":
        # exact target box (resizing.go's "fit": may change the ratio)
        if tw * th > MAX_PIXELS:
            return data, _FORMATS.get(fmt, "")
        out = img.resize((tw, th))
    elif mode == "fill":
        # cover the box, then center-crop to it
        scale = max(tw / w, th / h)
        iw, ih = max(1, round(w * scale)), max(1, round(h * scale))
        if iw * ih > MAX_PIXELS or tw * th > MAX_PIXELS:
            return data, _FORMATS.get(fmt, "")
        out = img.resize((iw, ih))
        left = (out.width - tw) // 2
        top = (out.height - th) // 2
        out = out.crop((left, top, left + tw, top + th))
    else:
        # default: fit WITHIN the box, preserving the ratio
        scale = min(tw / w, th / h, 1.0)
        ow, oh = max(1, round(w * scale)), max(1, round(h * scale))
        if ow * oh > MAX_PIXELS:
            return data, _FORMATS.get(fmt, "")
        out = img.resize((ow, oh))
    buf = io.BytesIO()
    save_fmt = fmt if fmt in _FORMATS else "PNG"
    if save_fmt == "JPEG" and out.mode not in ("RGB", "L"):
        out = out.convert("RGB")
    out.save(buf, format=save_fmt)
    return buf.getvalue(), _FORMATS.get(save_fmt, "")
