"""Client-side CLI tools: upload, download, delete, benchmark.

Mirrors weed/command/{upload,download,benchmark}.go (SURVEY.md §2 "CLI
dispatcher", "Benchmark"): thin drivers over the operation client. The
benchmark is the reference's built-in load generator — N concurrent
writers then readers of small files against a live cluster, reporting
req/s and latency percentiles — doubling as an integration smoke test
(SURVEY.md §4 "Load/benchmark as test").
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import numpy as np

from .cluster import operation
from .cluster.wdclient import MasterClient
from .util import tls as tls_mod


def run_upload(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="upload")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    p.add_argument("-ttl", default="")
    p.add_argument("files", nargs="+")
    tls_mod.add_security_flag(p)
    args = p.parse_args(argv)
    tls_mod.install_from_flag(args)
    master = MasterClient(args.master)
    results = []
    for f in args.files:
        data = Path(f).read_bytes()
        a = operation.assign(master, 1, args.collection,
                             args.replication, args.ttl)
        operation.upload(a.url, a.fid, data, jwt=a.auth,
                         collection=args.collection)
        results.append({"file": f, "fid": a.fid, "size": len(data),
                        "url": f"{a.public_url}/{a.fid}"})
    print(json.dumps(results, indent=2))
    master.close()
    return 0


def run_download(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="download")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-collection", default="")
    p.add_argument("-dir", default=".")
    p.add_argument("fids", nargs="+")
    tls_mod.add_security_flag(p)
    args = p.parse_args(argv)
    tls_mod.install_from_flag(args)
    master = MasterClient(args.master)
    for fid in args.fids:
        data = operation.download(master, fid,
                                  collection=args.collection)
        out = Path(args.dir) / fid.replace(",", "_")
        out.write_bytes(data)
        print(f"{fid} -> {out} ({len(data)} bytes)")
    master.close()
    return 0


def run_delete(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="delete")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-collection", default="")
    p.add_argument("fids", nargs="+")
    tls_mod.add_security_flag(p)
    args = p.parse_args(argv)
    tls_mod.install_from_flag(args)
    master = MasterClient(args.master)
    for fid in args.fids:
        operation.delete(master, fid, collection=args.collection)
        print(f"deleted {fid}")
    master.close()
    return 0


def _percentiles(xs: list[float]) -> dict[str, float]:
    if not xs:
        return {}
    a = np.asarray(xs)
    return {"p50_ms": float(np.percentile(a, 50) * 1e3),
            "p90_ms": float(np.percentile(a, 90) * 1e3),
            "p99_ms": float(np.percentile(a, 99) * 1e3),
            "max_ms": float(a.max() * 1e3)}


def run_benchmark(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="benchmark")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-c", type=int, default=4, help="concurrency")
    p.add_argument("-n", type=int, default=100, help="file count")
    p.add_argument("-size", type=int, default=1024, help="bytes per file")
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    p.add_argument("-write-only", action="store_true")
    tls_mod.add_security_flag(p)
    args = p.parse_args(argv)
    tls_mod.install_from_flag(args)
    master = MasterClient(args.master)
    rng = np.random.default_rng(0)
    payloads = [rng.integers(0, 256, args.size, dtype=np.uint8).tobytes()
                for _ in range(min(args.n, 64))]

    fids: list[str] = []
    write_times: list[float] = []

    def write_one(i: int) -> tuple[str, float, bytes]:
        data = payloads[i % len(payloads)]
        t0 = time.perf_counter()
        a = operation.assign(master, 1, args.collection,
                             args.replication)
        operation.upload(a.url, a.fid, data, jwt=a.auth,
                         collection=args.collection)
        return a.fid, time.perf_counter() - t0, data

    t_start = time.perf_counter()
    with ThreadPoolExecutor(max_workers=args.c) as pool:
        out = list(pool.map(write_one, range(args.n)))
    write_wall = time.perf_counter() - t_start
    by_fid = {}
    for fid, dt, data in out:
        fids.append(fid)
        write_times.append(dt)
        by_fid[fid] = data
    wstats = _percentiles(write_times)
    print(f"write: {args.n} files x {args.size} B, "
          f"{args.n / write_wall:.1f} req/s, "
          f"{args.n * args.size / write_wall / 2**20:.2f} MiB/s, "
          f"{wstats}", file=sys.stderr)

    if not args.write_only:
        read_times: list[float] = []
        mismatches = 0

        def read_one(fid: str) -> tuple[float, bool]:
            t0 = time.perf_counter()
            data = operation.download(master, fid,
                                      collection=args.collection)
            return time.perf_counter() - t0, data == by_fid[fid]

        t_start = time.perf_counter()
        with ThreadPoolExecutor(max_workers=args.c) as pool:
            res = list(pool.map(read_one, fids))
        read_wall = time.perf_counter() - t_start
        for dt, ok in res:
            read_times.append(dt)
            mismatches += 0 if ok else 1
        rstats = _percentiles(read_times)
        print(f"read: {len(fids)} files, {len(fids) / read_wall:.1f} "
              f"req/s, {mismatches} mismatches, {rstats}",
              file=sys.stderr)
        if mismatches:
            master.close()
            return 1
    print(json.dumps({"written": args.n,
                      "write_req_s": round(args.n / write_wall, 1),
                      **{k: round(v, 2) for k, v in wstats.items()}}))
    master.close()
    return 0


def run_filer_copy(argv: list[str] | None = None) -> int:
    """``weed filer.copy <paths...> http://<filer>/<dir>/`` — upload
    local files or whole directory trees into the filer namespace
    (weed/command/filer_copy.go). Parallelism stays sequential: the
    single-core build gains nothing from upload workers."""
    import argparse
    import urllib.parse
    from pathlib import Path as _Path

    from .cluster.filer_client import FilerClient

    p = argparse.ArgumentParser(prog="filer.copy")
    p.add_argument("paths", nargs="+",
                   help="local files/directories, last arg is the "
                        "filer url (http://host:port/dir/)")
    p.add_argument("-collection", default="")
    p.add_argument("-ttl", default="")
    p.add_argument("-maxMB", type=int, default=0,
                   help="chunk size override")
    args = p.parse_args(argv)
    if len(args.paths) < 2:
        print("filer.copy: need at least one source and the filer url")
        return 1
    *sources, dest = args.paths
    u = urllib.parse.urlparse(dest)
    if u.scheme != "http" or not u.netloc:
        print(f"filer.copy: destination must be http://filer/dir/ "
              f"(got {dest!r})")
        return 1
    base = u.path if u.path.endswith("/") else u.path + "/"
    fc = FilerClient(u.netloc)
    params = {}
    if args.collection:
        params["collection"] = args.collection
    if args.ttl:
        params["ttl"] = args.ttl
    if args.maxMB:
        params["maxMB"] = str(args.maxMB)
    query = urllib.parse.urlencode(params)
    window = (args.maxMB or 8) * 1024 * 1024
    copied = failed = 0
    try:
        for src in sources:
            sp = _Path(src)
            if sp.is_dir():
                files = sorted(x for x in sp.rglob("*") if x.is_file())
                rels = [(x, f"{sp.name}/{x.relative_to(sp)}")
                        for x in files]
            elif sp.is_file():
                rels = [(sp, sp.name)]
            else:
                print(f"filer.copy: {src}: no such file or directory")
                failed += 1
                continue
            for local, rel in rels:
                target = base + rel
                try:
                    # stream in windows: the first PUT creates the
                    # entry, the rest append — a multi-GB file never
                    # sits in RAM whole (filer_copy.go streams too)
                    with open(local, "rb") as f:
                        first = True
                        while True:
                            piece = f.read(window)
                            if not piece and not first:
                                break
                            qx = query if first else (
                                f"{query}&op=append" if query
                                else "op=append")
                            fc.put_data(target, piece, query=qx)
                            first = False
                    copied += 1
                    print(f"{local} -> {target}")
                except Exception as e:  # noqa: BLE001 — keep copying
                    failed += 1
                    print(f"filer.copy: {local}: {e}")
    finally:
        fc.close()
    print(f"filer.copy: {copied} files copied"
          + (f", {failed} FAILED" if failed else ""))
    return 1 if failed else 0
