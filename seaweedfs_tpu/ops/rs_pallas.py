"""Pallas TPU kernel for the bitsliced GF(2^8) linear map — the hot op.

This is the fused, VMEM-resident version of ops/bitslice.py — the TPU
replacement for klauspost/reedsolomon's ``galMulSlice`` SIMD loop
(galois_amd64.s, SURVEY.md §2 L0) and the "Pallas GF(256) MAC" of the
BASELINE.json north star. The pure-XLA bitslice path materializes 4-byte
word expansions of every intermediate (B, k, S) tensor, which blows HBM
for GiB-scale volumes (a 1 GiB encode peaks > 50 GiB); here each grid
step streams one (k, 32, RB, 128)-word block HBM->VMEM, does the whole
bytes -> bitplanes -> XOR network -> bytes round trip in VMEM, and writes
only the (m, ...) parity block back.

Layout trick: the caller's (B, n, S) uint8 tensor is bitcast to u32 words
and reshaped to (B, n, 32, R, 128). A bit-transpose "group" is the 32
words sharing one (r, c) position — a strided word set rather than 32
consecutive words. Any fixed byte <-> (word, bit) bijection is correct as
long as input and output use the same one (the XOR network is pure
position-wise GF algebra and pack/unpack happen inside one kernel), and
this choice makes every kernel-side op a full-width operation on (8, 128)
u32 tiles:

* the 5 masked-swap transpose rounds pair slices along the leading
  32-axis (free), with shifts/XORs running over (RB, 128) tiles;
* each bit plane (d, j) is ``a4[d, :, j]`` of shape (4, RB, 128) — the
  byte-within-word axis rides along as a leading dim, so the unrolled
  XOR network never touches a partially-filled tile.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import bitslice, xor_cse

LANES = 128
GROUP_WORDS = 32
#: Sublanes per block (u32 tile height); S must pad to SEG_BYTES.
RB = 8
#: Byte granularity of the kernel along S: 4 * 32 * 8 * 128.
SEG_BYTES = 4 * GROUP_WORDS * RB * LANES

_MASKS = bitslice._MASKS
_SHIFTS = bitslice._SHIFTS


def _bit_transpose(a: jnp.ndarray) -> jnp.ndarray:
    """32x32 bit-matrix transpose, word axis at -3: (..., 32, R, C) u32.

    Same masked-swap network as bitslice.transpose32 (an involution), but
    pairing along a leading axis so the payload (R, C) tile stays intact.
    """
    pre = a.shape[:-3]
    r, c = a.shape[-2:]
    for mask_c, j in zip(_MASKS, _SHIFTS):
        mask = jnp.uint32(mask_c)
        aa = a.reshape(*pre, GROUP_WORDS // (2 * j), 2, j, r, c)
        lo = aa[..., 0, :, :, :]
        hi = aa[..., 1, :, :, :]
        t = (lo ^ (hi << j)) & mask
        lo = lo ^ t
        hi = hi ^ (t >> j)
        a = jnp.stack([lo, hi], axis=-4).reshape(*pre, GROUP_WORDS, r, c)
    return a


def _make_kernel(rows: tuple[tuple[int, ...], ...], n_in: int, n_out: int,
                 cse: bool = True):
    """Kernel closure for a static GF(2) matrix given as per-output-row
    tuples of selected input-plane indices (8*n_out rows over 8*n_in)."""

    def kernel(in_ref, out_ref):
        a = _bit_transpose(in_ref[0])          # (n_in, 32, RB, C)
        rb, c = a.shape[-2:]
        a4 = a.reshape(n_in, 4, 8, rb, c)
        ins = [a4[d, :, j] for d in range(n_in) for j in range(8)]
        results = _eval_xor_network(ins, rows, 8 * n_in, cse)
        zero = None
        out_groups = []
        for o in range(n_out):
            cols = []
            for i in range(8):
                acc = results[8 * o + i]
                if acc is None:
                    if zero is None:
                        zero = jnp.zeros((4, rb, c), jnp.uint32)
                    acc = zero
                cols.append(acc)
            grp = jnp.stack(cols, axis=1)      # (4, 8, rb, c)
            out_groups.append(grp.reshape(GROUP_WORDS, rb, c))
        out = jnp.stack(out_groups, axis=0)    # (n_out, 32, rb, c)
        out_ref[0] = _bit_transpose(out)

    return kernel


def _eval_xor_network(planes: list, rows: tuple[tuple[int, ...], ...],
                      n_inputs: int, cse: bool) -> list:
    """Evaluate output rows over ``planes`` (index t -> array), with
    Paar-factored shared pairs when ``cse`` (2.4x fewer XORs for
    RS(10,4): 1192 -> 495). Returns one array (or None for an empty
    row) per output row."""
    if cse:
        steps, outs = xor_cse.factor(rows, n_inputs)
        vals = list(planes)
        for nid, a, b in steps:
            assert nid == len(vals)
            vals.append(vals[a] ^ vals[b])
    else:
        vals, outs = list(planes), rows
    results = []
    for out in outs:
        if not out:
            results.append(None)
            continue
        acc = vals[out[0]]
        for t in out[1:]:
            acc = acc ^ vals[t]
        results.append(acc)
    return results


def _make_swar_kernel(rows: tuple[tuple[int, ...], ...],
                      n_in: int, n_out: int, cse: bool = True):
    """Transpose-free kernel: SWAR bitplanes inside u32 words.

    Bit j of each of the 4 packed bytes of a word is extracted with
    ``(x >> j) & 0x01010101`` — plane t = 8d+j holds its 4 bits at word
    bit positions 0, 8, 16, 24. The GF(2) XOR network then runs on
    these quarter-density planes, and output bit i re-enters the word at
    ``acc << i`` (disjoint positions across i, so OR == ADD == XOR).
    Every op is a full-width shift/AND/XOR on the (rows, 128) u32 tile:
    no reshapes, slices along sub-tile axes, stacks, or transposes for
    Mosaic to lower into VMEM copies — probe2 measured the transpose
    variant at ~5.5 GiB/s marginal, ~150x below HBM, pointing at
    layout-shuffling rather than XOR arithmetic as the cost.

    All 8*n_in masked planes are materialized before the network runs
    (CSE steps cross shard boundaries, so a shard-major streaming order
    cannot host them); instruction scheduling/liveness is left to the
    compiler. ``cse=False`` keeps this same structure minus factoring —
    it is an ablation of the factoring only, not a reconstruction of
    any earlier kernel layout.
    """

    def kernel(in_ref, out_ref):
        plane_mask = jnp.uint32(0x01010101)
        x = in_ref[0]                       # (n_in, rows, 128) u32
        planes = []
        for d in range(n_in):
            xd = x[d]
            for j in range(8):
                p = xd if j == 0 else (xd >> jnp.uint32(j))
                planes.append(p & plane_mask)
        accs = _eval_xor_network(planes, rows, 8 * n_in, cse)
        for o in range(n_out):
            y = None
            for i in range(8):
                acc = accs[8 * o + i]
                if acc is None:
                    continue
                sh = acc if i == 0 else (acc << jnp.uint32(i))
                y = sh if y is None else (y | sh)
            if y is None:
                y = jnp.zeros_like(x[0])
            out_ref[0, o] = y

    return kernel


#: Row granularity of the SWAR kernel: S must divide into
#: 4 (bytes/word) * SWAR_ROWS * 128 (lanes) byte segments.
SWAR_ROWS = 512
SWAR_SEG_BYTES = 4 * SWAR_ROWS * LANES


def swar_conforms(s: int, rows_per_block: int = SWAR_ROWS) -> bool:
    return s > 0 and s % (4 * rows_per_block * LANES) == 0


def _expand_rows(coefs: np.ndarray, n_out: int):
    mbits = bitslice.expand_gf2(np.asarray(coefs, dtype=np.uint8))
    return tuple(tuple(int(t) for t in np.nonzero(mbits[rr])[0])
                 for rr in range(8 * n_out))


def apply_gf_matrix_swar_words(coefs: np.ndarray, x4: jnp.ndarray,
                               interpret: bool = False,
                               rows_per_block: int = SWAR_ROWS,
                               cse: bool = True) -> jnp.ndarray:
    """SWAR kernel on the WORD form: x4 (B, n_in, R, 128) u32 ->
    (B, n_out, R, 128) u32.

    This is the zero-relayout entry point: a profiler trace of the
    u8-API path showed the Pallas kernel itself at ~6.5 ms per 160 MiB
    call (~24 GiB/s) with ~10x that spent in XLA copy/reshape/broadcast
    ops materializing the (B, n, R, 128) u32 view of a (B, n, S) u8
    array. The word form IS the array's natural tiled layout — host
    callers produce it with a free contiguous reshape (np view) and
    device_put lands it tiled, so nothing is shuffled on device."""
    n_out, n_in = coefs.shape
    if x4.ndim != 4 or x4.shape[1] != n_in or x4.shape[3] != LANES:
        raise ValueError(
            f"x4 must be (B, {n_in}, R, {LANES}) u32, got {x4.shape}")
    b, _, r, _ = x4.shape
    if r % rows_per_block:
        raise ValueError(f"R={r} must divide by {rows_per_block}")
    rows = _expand_rows(coefs, n_out)
    return pl.pallas_call(
        _make_swar_kernel(rows, n_in, n_out, cse=cse),
        grid=(b, r // rows_per_block),
        in_specs=[pl.BlockSpec(
            (1, n_in, rows_per_block, LANES),
            lambda bi, ri: (bi, 0, ri, 0),
            memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(
            (1, n_out, rows_per_block, LANES),
            lambda bi, ri: (bi, 0, ri, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (b, n_out, r, LANES), jnp.uint32),
        interpret=interpret,
    )(x4)


def apply_gf_matrix_swar(coefs: np.ndarray, x: jnp.ndarray,
                         interpret: bool = False,
                         rows_per_block: int = SWAR_ROWS,
                         cse: bool = True) -> jnp.ndarray:
    """Same contract as apply_gf_matrix, via the SWAR kernel. ``cse``
    evaluates the XOR network with Paar-factored shared pairs (2.4x
    fewer XORs; semantics identical — see ops/xor_cse.py)."""
    n_out, n_in = coefs.shape
    if x.ndim != 3 or x.shape[1] != n_in:
        raise ValueError(f"x must be (B, {n_in}, S), got {x.shape}")
    b, _, s = x.shape
    if not swar_conforms(s, rows_per_block):
        raise ValueError(
            f"S={s} must be a positive multiple of "
            f"{4 * rows_per_block * LANES}")
    w = s // 4
    r = w // LANES

    xw = jax.lax.bitcast_convert_type(
        x.reshape(b, n_in, w, 4), jnp.uint32)
    x4 = xw.reshape(b, n_in, r, LANES)
    y4 = apply_gf_matrix_swar_words(coefs, x4, interpret=interpret,
                                    rows_per_block=rows_per_block,
                                    cse=cse)
    yw = y4.reshape(b, n_out, w)
    return jax.lax.bitcast_convert_type(yw, jnp.uint8).reshape(b, n_out, s)


def conforms(s: int, rb: int = RB) -> bool:
    """True when a shard length S can feed the kernel without padding."""
    seg = 4 * GROUP_WORDS * rb * LANES
    return s > 0 and s % seg == 0


def apply_gf_matrix(coefs: np.ndarray, x: jnp.ndarray,
                    interpret: bool = False, rb: int = RB,
                    cse: bool = True) -> jnp.ndarray:
    """y[b, o, s] = XOR_d coefs[o, d] * x[b, d, s] over GF(2^8), fused.

    ``coefs`` (n_out, n_in) uint8 static; ``x`` (B, n_in, S) uint8 with
    S % (4 * 32 * rb * 128) == 0. ``rb`` is the block height in u32
    sublane rows per grid step — VMEM per step is
    (n_in + n_out) * 32 * rb * 128 * 4 bytes, double-buffered; keep it
    well under the ~16 MiB/core VMEM budget. Trace-time work (bit-matrix
    expansion, kernel construction) is cached per coefficient matrix;
    call under jit or rely on jit's own executable cache.
    """
    n_out, n_in = coefs.shape
    if x.ndim != 3 or x.shape[1] != n_in:
        raise ValueError(f"x must be (B, {n_in}, S), got {x.shape}")
    b, _, s = x.shape
    if not conforms(s, rb):
        seg = 4 * GROUP_WORDS * rb * LANES
        raise ValueError(f"S={s} must be a positive multiple of {seg}")
    w = s // 4
    r = w // (GROUP_WORDS * LANES)

    xw = jax.lax.bitcast_convert_type(
        x.reshape(b, n_in, w, 4), jnp.uint32)
    x4 = xw.reshape(b, n_in, GROUP_WORDS, r, LANES)
    y4 = apply_gf_matrix_words(coefs, x4, interpret=interpret, rb=rb,
                               cse=cse)
    yw = y4.reshape(b, n_out, w)
    return jax.lax.bitcast_convert_type(yw, jnp.uint8).reshape(b, n_out, s)


def apply_gf_matrix_words(coefs: np.ndarray, x4: jnp.ndarray,
                          interpret: bool = False, rb: int = RB,
                          cse: bool = True) -> jnp.ndarray:
    """Transpose kernel on the WORD form: x4 (B, n_in, 32, R, 128) u32
    -> (B, n_out, 32, R, 128) u32 — no u8<->u32 relayout around the
    kernel (see apply_gf_matrix_swar_words for why that matters)."""
    n_out, n_in = coefs.shape
    if (x4.ndim != 5 or x4.shape[1] != n_in
            or x4.shape[2] != GROUP_WORDS or x4.shape[4] != LANES):
        raise ValueError(
            f"x4 must be (B, {n_in}, {GROUP_WORDS}, R, {LANES}) u32, "
            f"got {x4.shape}")
    b, _, _, r, _ = x4.shape
    if r % rb:
        raise ValueError(f"R={r} must divide by {rb}")
    rows = _expand_rows(coefs, n_out)
    return pl.pallas_call(
        _make_kernel(rows, n_in, n_out, cse=cse),
        grid=(b, r // rb),
        in_specs=[pl.BlockSpec(
            (1, n_in, GROUP_WORDS, rb, LANES),
            lambda bi, ri: (bi, 0, 0, ri, 0),
            memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(
            (1, n_out, GROUP_WORDS, rb, LANES),
            lambda bi, ri: (bi, 0, 0, ri, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (b, n_out, GROUP_WORDS, r, LANES), jnp.uint32),
        interpret=interpret,
    )(x4)
