"""Batched, jittable Reed-Solomon codec for TPU (and XLA:CPU fallback).

This is the device-side replacement for the reference's
``klauspost/reedsolomon.Encoder`` (SURVEY.md §2 L0): the same method
surface as ops/rs_ref.py, but operating on batched ``(B, k, S)`` uint8
arrays through the bitsliced GF(2) XOR network in ops/bitslice.py. One
``Encoder`` instance serves any batch size; jitted executables are cached
per (coefficient-matrix, shape) pair, and shard length is padded to the
128-byte packing group internally (zero bytes encode to zero parity, so
padding is transparent).

Reconstruction follows klauspost ``reconstruct`` semantics: take the first
k surviving shard indices, invert those k rows of the code matrix on the
host (tiny GF(2^8) Gauss-Jordan), and apply the needed rows on-device via
the same bitsliced primitive used for encode. The inverted matrices are
memoized per survivor set, mirroring klauspost's inversion_tree.go cache.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from . import bitslice, gf256, rs_native, rs_pallas
from .rs_ref import ShardSizeError, TooFewShardsError

GROUP = bitslice.GROUP_BYTES

#: Use the fused Pallas kernel on TPU once a shard is at least this long
#: (below it, the pad to rs_pallas.SEG_BYTES and grid overhead dominate).
PALLAS_MIN_S = 256 * 1024
#: Chunk the pure-XLA path along S above this, bounding the ~12x word
#: expansion its unfused pack/XOR/unpack intermediates cost in HBM/RAM.
XLA_CHUNK_S = 4 * 1024 * 1024
#: Test/debug override: "pallas" | "pallas_swar" | "native" | "xla" |
#: None (auto).
FORCE: Optional[str] = None
#: Hybrid policy, part 2 (large HOST payloads): "auto" measures the
#: host->device link and the native codec once and sends host-resident
#: slabs to the device only when the link can stream bytes faster than
#: the host codec computes them (otherwise the transfer alone loses the
#: race — on this environment's ~24 MiB/s tunnel the device can never
#: win an e2e host encode, while a locally attached chip always can).
#: "device" / "native" pin the choice (the bench pins "device" to smoke
#: the production device path regardless of the link).
HOST_DISPATCH = os.environ.get("SEAWEEDFS_TPU_HOST_DISPATCH", "auto")
#: How many equally-shaped host slabs one device dispatch may carry on
#: the word-form path (apply_matrix_host_multi). The round-5 hardware
#: race measured the per-dispatch launch+sync floor dominating
#: single-slab calls (160 MiB/call -> ~4 GiB/s) while 16 slab-sized
#: args in ONE jitted call ran the same kernel at 119 GiB/s; the
#: remote-compile ceiling is per-BUFFER, not per-program (PERF.md), so
#: grouping scales throughput without approaching the compile limit.
DISPATCH_GROUP = os.environ.get("SEAWEEDFS_TPU_DISPATCH_GROUP", "16")
#: HBM reuse on the host-slab fast path: donate the freshly transferred
#: word-form arg to the jitted call (jax.jit donate_argnums) so XLA may
#: recycle its device memory for the computation instead of holding
#: input and output live together — a streaming encode keeps up to
#: group x batch slabs in flight, so without donation peak HBM is
#: roughly double the working set. "auto" (default) donates only on
#: accelerator backends: on CPU, jnp.asarray may ALIAS the host numpy
#: buffer (no transfer happens), and donating an aliased buffer would
#: hand the pooled batch the writer still references to XLA as scratch.
DONATE = os.environ.get("SEAWEEDFS_TPU_DONATE", "auto")
_link_gibps: Optional[float] = None
_native_gibps: Optional[float] = None
_calibrate_lock = threading.Lock()


def _dispatch_group() -> int:
    """Validated DISPATCH_GROUP, checked at use time (same rationale as
    _kernel(): a typo'd env var must surface as a normal error from the
    encode call, not an import-time traceback)."""
    try:
        g = int(DISPATCH_GROUP)
    except (TypeError, ValueError):
        g = -1
    if g < 1:
        raise ValueError(
            f"SEAWEEDFS_TPU_DISPATCH_GROUP={DISPATCH_GROUP!r}: expected "
            f"a positive integer")
    return g


def _dispatch_mode() -> str:
    """Validated HOST_DISPATCH, checked at use time on every backend
    (same rationale as _kernel())."""
    if HOST_DISPATCH not in ("auto", "device", "native"):
        raise ValueError(
            f"SEAWEEDFS_TPU_HOST_DISPATCH={HOST_DISPATCH!r}: expected "
            f"'auto', 'device' or 'native'")
    return HOST_DISPATCH


_donation_warning_squelched = False


def _donate() -> bool:
    """Validated DONATE knob (see its comment). Donation that XLA
    cannot alias (parity output is m/k the input size) still frees the
    input buffer inside the computation — that early release, not
    output aliasing, is the HBM win — but JAX warns about every such
    call, so the warning is squelched once when donation first engages.
    """
    if DONATE not in ("auto", "on", "off"):
        raise ValueError(
            f"SEAWEEDFS_TPU_DONATE={DONATE!r}: expected "
            f"'auto', 'on' or 'off'")
    if DONATE == "off":
        return False
    # deliberately the RAW backend, not _use_pallas(): tests monkeypatch
    # that predicate to force the device path on CPU (interpret-mode
    # kernels), and donating there is exactly the aliasing hazard the
    # auto mode exists to rule out
    on = True if DONATE == "on" \
        else jax.default_backend() in ("tpu", "axon")
    if on:
        global _donation_warning_squelched
        if not _donation_warning_squelched:
            import warnings
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            # idempotent one-way latch: racing writers both set True;
            # double-filtering a warning is harmless
            # seaweedlint: disable=SW801 — idempotent latch
            _donation_warning_squelched = True
    return on


def donation_enabled() -> bool:
    """Public form of the donation knob for the mesh plane
    (parallel/mesh): sharded apply-only steps donate their freshly
    device_put input shards under the same policy — and the same
    CPU-aliasing guard — as the single-device word-form path."""
    return _donate()
#: Which Pallas kernel the auto "pallas" variant uses: "transpose"
#: (default — oracle-smoked on hardware every bench round) or "swar"
#: (transpose-free; see rs_pallas.apply_gf_matrix_swar). Resolution
#: order: SEAWEEDFS_TPU_KERNEL env var > artifacts/KERNEL_CHOICE.json
#: (written by the bench watcher when a hardware race crowns a winner
#: by a clear margin — measured promotion without a code change) >
#: "transpose".


def _measured_kernel_default(path=None) -> str:
    import json as json_mod
    from pathlib import Path
    try:
        p = Path(path) if path is not None else (
            Path(__file__).resolve().parent.parent.parent
            / "artifacts" / "KERNEL_CHOICE.json")
        choice = json_mod.loads(p.read_text()).get("kernel")
        if choice in ("transpose", "swar"):
            return choice
    except Exception:  # noqa: BLE001 — absent/corrupt file = default
        pass
    return "transpose"


PALLAS_KERNEL = os.environ.get("SEAWEEDFS_TPU_KERNEL") \
    or _measured_kernel_default()


def _kernel() -> str:
    """Validated kernel selection, checked at *use* time rather than at
    import so a typo'd SEAWEEDFS_TPU_KERNEL surfaces as a normal error
    from the encode call instead of a bare traceback from every CLI
    entrypoint that transitively imports this module."""
    if PALLAS_KERNEL not in ("transpose", "swar"):
        raise ValueError(
            f"SEAWEEDFS_TPU_KERNEL={PALLAS_KERNEL!r}: expected "
            f"'transpose' or 'swar'")
    return PALLAS_KERNEL


def _use_pallas() -> bool:
    # Mosaic kernels lower only for TPU ("axon" is this environment's
    # tunneled TPU plugin); GPU/CPU take the XLA bitslice network.
    return jax.default_backend() in ("tpu", "axon")


def _pick_variant(s: int) -> str:
    if FORCE:
        return FORCE
    _kernel()  # validate the env knobs on EVERY backend, not just TPU —
    _dispatch_mode()  # a typo must not ride silently through CPU runs
    # into a deployment
    if _use_pallas() and s >= PALLAS_MIN_S:
        return "pallas_swar" if _kernel() == "swar" else "pallas"
    if rs_native.available():
        # Hybrid policy, part 1 (sub-slab work): below PALLAS_MIN_S the
        # dispatch+grid overhead beats any device win EVEN with a local
        # chip, so small payloads take the AVX2 nibble-LUT codec on the
        # host on EVERY backend — a 4 KiB interval repair must never
        # pay a device round trip (round-4 bench: 64 QPS of them on the
        # tunneled TPU drove read p99 to ~10 s; the reference serves
        # them from klauspost's SIMD loop for the same reason).
        return "native"
    return "xla"


def _measure_link_gibps(n_bytes: int = 8 * 1024 * 1024) -> float:
    """One-time h2d+d2h round-trip bandwidth probe (GiB/s of payload
    moved per second of wall time, both directions counted)."""
    import time

    x = np.zeros(n_bytes, dtype=np.uint8)
    t0 = time.perf_counter()
    d = jax.device_put(x)
    jax.block_until_ready(d)
    np.asarray(d)
    dt = time.perf_counter() - t0
    return 2 * n_bytes / (1024 ** 3) / max(dt, 1e-9)


def _measure_native_gibps(n_bytes: int = 16 * 1024 * 1024) -> float:
    """One-time host-codec throughput probe (input GiB/s)."""
    import time

    k = 10
    coefs = gf256.build_code_matrix(k, k + 4)[k:]
    x = np.zeros((k, n_bytes // k), dtype=np.uint8)
    rs_native.apply_gf_matrix(coefs, x)  # warm: builds .so + tables
    t0 = time.perf_counter()
    rs_native.apply_gf_matrix(coefs, x)
    dt = time.perf_counter() - t0
    return x.size / (1024 ** 3) / max(dt, 1e-9)


def _device_worth_it() -> bool:
    """Hybrid policy, part 2: should a large HOST payload cross to the
    device? Probes both bandwidths once; the device wins only when the
    link outruns the host codec (see HOST_DISPATCH)."""
    mode = _dispatch_mode()
    if mode == "device":
        return True
    if mode == "native":
        return False
    if not rs_native.available():
        return True
    global _link_gibps, _native_gibps
    if _link_gibps is None:
        with _calibrate_lock:
            # re-check under the lock: concurrent callers (the repair
            # aggregator + a bulk decode run in parallel by design)
            # must neither double-probe nor share the link with each
            # other's probe — that would cache a distorted verdict for
            # the process lifetime
            if _link_gibps is None:
                link = _measure_link_gibps()
                # The probe may trigger the one-time native build; the
                # calibrate lock exists to single-fly exactly that.
                # seaweedlint: disable=SW103 — intentional build-once
                _native_gibps = _measure_native_gibps()
                _link_gibps = link
                from ..util import glog
                glog.v(1, "rs dispatch calibration: link %.3f GiB/s, "
                          "native codec %.3f GiB/s -> host slabs %s",
                       _link_gibps, _native_gibps,
                       "cross to device" if _link_gibps > _native_gibps
                       else "stay on host")
    return _link_gibps > _native_gibps


@functools.lru_cache(maxsize=256)
def _jitted_apply(coefs_bytes: bytes, n_out: int, n_in: int, variant: str,
                  donate: bool = False):
    """One jitted executable per (coefficient matrix, backend variant);
    shapes stay polymorphic via jit's own shape cache. ``donate`` hands
    the input buffer to XLA (host word-form call sites only — they pass
    a freshly transferred device copy nothing else references)."""
    coefs = np.frombuffer(coefs_bytes, dtype=np.uint8).reshape(n_out, n_in)

    if variant == "pallas":
        def apply_fn(x: jnp.ndarray) -> jnp.ndarray:
            return rs_pallas.apply_gf_matrix(coefs, x)
    elif variant == "pallas_swar":
        def apply_fn(x: jnp.ndarray) -> jnp.ndarray:
            return rs_pallas.apply_gf_matrix_swar(coefs, x)
    elif variant == "pallas_words":
        def apply_fn(x4: jnp.ndarray) -> jnp.ndarray:
            return rs_pallas.apply_gf_matrix_words(coefs, x4)
    elif variant == "pallas_swar_words":
        def apply_fn(x4: jnp.ndarray) -> jnp.ndarray:
            return rs_pallas.apply_gf_matrix_swar_words(coefs, x4)
    elif variant == "xla":
        def apply_fn(x: jnp.ndarray) -> jnp.ndarray:
            return bitslice.apply_gf_matrix(coefs, x)
    else:  # "xla_chunked": x is (B, n_in, nc, sc)
        def apply_fn(x: jnp.ndarray) -> jnp.ndarray:
            # lax.map over column chunks keeps live intermediates to one
            # chunk's worth while XLA still fuses within each step.
            xc = x.transpose(2, 0, 1, 3)
            yc = jax.lax.map(
                lambda v: bitslice.apply_gf_matrix(coefs, v), xc)
            return yc.transpose(1, 2, 0, 3)

    return jax.jit(apply_fn, donate_argnums=(0,)) if donate \
        else jax.jit(apply_fn)


@functools.lru_cache(maxsize=64)
def _jitted_apply_multi(coefs_bytes: bytes, n_out: int, n_in: int,
                        variant: str, nargs: int, donate: bool = False):
    """One jitted executable per (coefficient matrix, words variant,
    group width): nargs word-form slabs in, nargs parities out. One
    dispatch for the whole group — the production analog of the bench
    race's n16 candidate (PERF.md: the launch+sync floor, not the
    kernel, dominates single-slab calls). ``donate`` hands every slab
    arg to XLA — the streaming pipeline's HBM high-water mark drops
    from (inputs + outputs) to one group of inputs, since each slab's
    buffer frees as the computation consumes it."""
    coefs = np.frombuffer(coefs_bytes, dtype=np.uint8).reshape(n_out, n_in)
    if variant == "pallas_swar_words":
        def kern(x):
            return rs_pallas.apply_gf_matrix_swar_words(coefs, x)
    else:
        def kern(x):
            return rs_pallas.apply_gf_matrix_words(coefs, x)

    def apply_fn(*xs):
        assert len(xs) == nargs
        return tuple(kern(x) for x in xs)

    return jax.jit(apply_fn, donate_argnums=tuple(range(nargs))) \
        if donate else jax.jit(apply_fn)


class _HostParity:
    """Async device parity held in word form; ``np.asarray`` (the
    pipeline writer's sync point) fetches it and re-views the bytes as
    (B, m, S) uint8 — a zero-copy host reshape."""

    __slots__ = ("dev", "b", "m", "s")

    def __init__(self, dev, b: int, m: int, s: int):
        self.dev = dev
        self.b = b
        self.m = m
        self.s = s

    def __array__(self, dtype=None, copy=None):
        w = np.asarray(self.dev)
        out = w.view(np.uint8).reshape(self.b, self.m, self.s)
        if dtype is not None and out.dtype != dtype:
            return out.astype(dtype)
        return out


def apply_matrix_host(coefs: np.ndarray, batch):
    """HOST (B, n_in, S) uint8 -> async result whose ``np.asarray``
    yields (B, n_out, S) uint8.

    The zero-relayout fast path behind Encoder.encode_parity_host /
    reconstruct_batch_host: when the Pallas dispatch applies and the
    shape conforms, the batch is VIEWED (zero-copy) in the kernel's
    pre-tiled word form and fed to the *_words entry point — none of
    the XLA copy/reshape/broadcast glue the profiler showed dominating
    the u8 path's device time (PERF.md). Anything ineligible defers to
    apply_matrix."""
    coefs = np.ascontiguousarray(coefs, dtype=np.uint8)
    n_out, n_in = coefs.shape
    wf = _host_word_form(n_in, batch)
    if wf is not None:
        if _stay_on_host():
            # link slower than the host codec: crossing can only lose.
            # (Pinned "native" without a built codec falls through to
            # the device leg instead of crashing.)
            return rs_native.apply_gf_matrix(coefs, batch)
        variant, xw = wf
        b, _, s = batch.shape
        fn = _jitted_apply(coefs.tobytes(), n_out, n_in, variant,
                           donate=_donate())
        return _HostParity(fn(jnp.asarray(xw)), b, n_out, s)
    if _host_prefers_native(n_in, batch):
        return rs_native.apply_gf_matrix(coefs, batch)
    return apply_matrix(coefs, batch)


def _host_eligible(n_in: int, batch) -> bool:
    """THE host-slab device-dispatch eligibility rule, shared by
    _host_word_form and _host_prefers_native: HOST-contiguous
    (B, n_in, S) uint8 with a Pallas-eligible S."""
    return (isinstance(batch, np.ndarray) and batch.ndim == 3
            and batch.dtype == np.uint8 and batch.flags.c_contiguous
            and FORCE is None and batch.shape[1] == n_in
            and _pick_variant(batch.shape[-1])
            in ("pallas", "pallas_swar"))


def _stay_on_host() -> bool:
    """Hybrid rule, spelled once: large host slabs stay on the host
    when the link can't outrun the host codec (and the codec exists)."""
    return not _device_worth_it() and rs_native.available()


def _host_prefers_native(n_in: int, batch) -> bool:
    """Slow-link guard for host slabs that are Pallas-ELIGIBLE but not
    word-form-CONFORMING (e.g. arbitrary-length tail chunks): crossing
    the device link through apply_matrix's padded u8 path can only lose
    when the link is slower than the host codec, so they take the
    native leg — the same hybrid rule conforming slabs get."""
    return _host_eligible(n_in, batch) and _stay_on_host()


def host_dispatch_group() -> int:
    """Group width for the host-slab pipelines (ONE policy for encode,
    the coalescing batcher and rebuild): >1 only on a single-device
    accelerator backend — multi-chip paths mesh-shard each batch
    instead (parallel/mesh), and CPU backends never take the word-form
    device path."""
    if not _use_pallas() or len(jax.devices()) > 1:
        return 1
    return _dispatch_group()


def _host_word_form(n_in: int, batch):
    """Eligibility + zero-copy word view for the device fast path.

    Returns (variant, words_view) when ``batch`` can ride the
    zero-relayout word-form dispatch — HOST-contiguous (B, n_in, S)
    uint8, Pallas-eligible S, kernel-conforming shape — else None.
    One predicate shared by the single and grouped call sites."""
    if not _host_eligible(n_in, batch):
        return None
    b, _, s = batch.shape
    w = s // 4
    lanes = rs_pallas.LANES
    if _kernel() == "swar" and rs_pallas.swar_conforms(s):
        return "pallas_swar_words", batch.view(np.uint32).reshape(
            b, n_in, w // lanes, lanes)
    if _kernel() != "swar" and rs_pallas.conforms(s):
        return "pallas_words", batch.view(np.uint32).reshape(
            b, n_in, rs_pallas.GROUP_WORDS,
            w // (rs_pallas.GROUP_WORDS * lanes), lanes)
    return None


def apply_matrix_host_multi(coefs: np.ndarray, batches):
    """Grouped apply_matrix_host: a list of HOST (B, n_in, S) uint8
    slabs -> a list of async results in the same order.

    Runs of adjacent, identically-shaped, fast-path-eligible slabs are
    dispatched as ONE jitted call with up to ``_dispatch_group()`` slab
    args (_jitted_apply_multi), amortizing the per-dispatch launch+sync
    floor that leaves single-slab calls ~25x under the same kernel's
    grouped throughput (round-5 race: 4.3 -> 119 GiB/s at n=16).
    Ineligible or odd-shaped slabs fall back to the single-slab paths;
    a shape change or a full group flushes, and a flushed run is split
    into power-of-two sub-dispatches — so the jit cache sees at most
    log2(group) (shape, width) pairs per workload, never a retrace
    storm (the pipeline's greedy drain yields arbitrary run lengths)."""
    coefs = np.ascontiguousarray(coefs, dtype=np.uint8)
    n_out, n_in = coefs.shape
    out: list = [None] * len(batches)
    cap = _dispatch_group()
    stay_host: Optional[bool] = None
    g_ix: list[int] = []
    g_xw: list = []
    g_shape = g_variant = None

    def dispatch(ixs, xws, width):
        if width == 1:
            # lone slab: the single-dispatch executable (already cached
            # for steady-state workloads) serves the word form the loop
            # already built
            i = ixs[0]
            b, _, s = batches[i].shape
            fn = _jitted_apply(coefs.tobytes(), n_out, n_in, g_variant,
                               donate=_donate())
            out[i] = _HostParity(fn(jnp.asarray(xws[0])), b, n_out, s)
            return
        fn = _jitted_apply_multi(coefs.tobytes(), n_out, n_in,
                                 g_variant, width, donate=_donate())
        ys = fn(*[jnp.asarray(x) for x in xws])
        for i, y in zip(ixs, ys):
            b, _, s = batches[i].shape
            out[i] = _HostParity(y, b, n_out, s)

    def flush():
        nonlocal g_ix, g_xw, g_shape, g_variant
        # quantize to power-of-two widths (13 -> 8+4+1) so executables
        # are shared across the drain's arbitrary run lengths
        pos = 0
        while pos < len(g_ix):
            width = 1 << ((len(g_ix) - pos).bit_length() - 1)
            dispatch(g_ix[pos:pos + width], g_xw[pos:pos + width], width)
            pos += width
        g_ix, g_xw, g_shape, g_variant = [], [], None, None

    for i, batch in enumerate(batches):
        wf = _host_word_form(n_in, batch)
        if wf is None:
            flush()
            out[i] = (rs_native.apply_gf_matrix(coefs, batch)
                      if _host_prefers_native(n_in, batch)
                      else apply_matrix(coefs, batch))
            continue
        if stay_host is None:
            stay_host = _stay_on_host()
        if stay_host:
            flush()
            out[i] = rs_native.apply_gf_matrix(coefs, batch)
            continue
        variant, xw = wf
        if g_ix and (batch.shape != g_shape or variant != g_variant
                     or len(g_ix) >= cap):
            flush()
        g_ix.append(i)
        g_xw.append(xw)
        g_shape, g_variant = batch.shape, variant
    flush()
    return out


def apply_matrix(coefs: np.ndarray, x) -> "np.ndarray | jnp.ndarray":
    """Dispatch to the fused Pallas kernel (TPU) or the chunked XLA
    network, padding S to the chosen path's granularity and slicing back
    (zero bytes encode to zero parity, so padding is transparent).

    Returns a device array, EXCEPT on the native host-codec leg with a
    host numpy input, where the host-resident result is returned as
    plain numpy (uploading it would defeat the hybrid policy)."""
    coefs = np.ascontiguousarray(coefs, dtype=np.uint8)
    n_out, n_in = coefs.shape
    if getattr(x, "ndim", None) not in (2, 3):
        raise ValueError(
            f"expected (n_in, S) or (B, n_in, S), got {getattr(x, 'shape', x)}")
    squeeze = x.ndim == 2
    variant = _pick_variant(x.shape[-1])
    if variant == "native" and FORCE is None \
            and not isinstance(x, np.ndarray) \
            and jax.default_backend() != "cpu":
        # never DOWNLOAD a device-resident array just to use the host
        # codec — the hybrid policy only redirects host payloads. On
        # the CPU backend a jax.Array is already host memory, so the
        # (~10x faster) native codec stays the right choice there.
        variant = "xla"
    if variant == "native":
        # Stay on the host end to end — converting through a device
        # buffer first would add two full copies of the payload, and on
        # a non-CPU backend jnp.asarray would UPLOAD the result, so the
        # host-resident answer is returned as plain numpy.
        return rs_native.apply_gf_matrix(coefs,
                                         np.asarray(x, dtype=np.uint8))
    x = jnp.asarray(x, dtype=jnp.uint8)
    if squeeze:
        x = x[None]
    b, _, s = x.shape
    nc = 1
    if variant == "pallas":
        seg = rs_pallas.SEG_BYTES
    elif variant == "pallas_swar":
        seg = rs_pallas.SWAR_SEG_BYTES
    elif variant == "xla" and s > XLA_CHUNK_S:
        variant = "xla_chunked"
        nc = -(-s // XLA_CHUNK_S)
        sc = -(-(-(-s // nc)) // GROUP) * GROUP  # ceil(s/nc) up to GROUP
        seg = nc * sc
    else:
        variant, seg = "xla", GROUP
    pad = (-s) % seg
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
    if variant == "xla_chunked":
        x = x.reshape(b, n_in, nc, (s + pad) // nc)
    fn = _jitted_apply(coefs.tobytes(), n_out, n_in, variant)
    y = fn(x)
    if variant == "xla_chunked":
        y = y.reshape(b, n_out, s + pad)
    if pad:
        y = y[..., :s]
    return y[0] if squeeze else y


class Encoder:
    """Parametrized RS(k, m) with the klauspost Encoder method set,
    executing on whatever backend JAX targets (TPU v5e here; XLA:CPU is
    the no-device fallback, mirroring the reference's SIMD CPU path)."""

    def __init__(self, data_shards: int, parity_shards: int):
        if data_shards <= 0 or parity_shards <= 0:
            raise ValueError("data_shards and parity_shards must be positive")
        if data_shards + parity_shards > 256:
            raise ValueError("at most 256 total shards in GF(2^8)")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = gf256.build_code_matrix(data_shards, self.total_shards)
        self._decode_cache: dict[tuple[int, ...], np.ndarray] = {}

    # -- batched array API (the TPU-native surface) -----------------------

    @property
    def parity_coefs(self) -> np.ndarray:
        """(m, k) uint8 parity rows of the code matrix, C-contiguous —
        the coefficients a caller hands to bitslice.apply_gf_matrix."""
        return np.ascontiguousarray(self.matrix[self.data_shards:],
                                    dtype=np.uint8)

    def encode_parity(self, data) -> jnp.ndarray:
        """data (B, k, S) or (k, S) uint8 -> parity (B, m, S) / (m, S)."""
        return apply_matrix(self.matrix[self.data_shards:], data)

    def encode_parity_host(self, batch):
        """Pipeline fast path: HOST (B, k, S) uint8 -> async parity
        whose ``np.asarray`` yields (B, m, S) uint8 — see
        apply_matrix_host."""
        return apply_matrix_host(self.matrix[self.data_shards:], batch)

    def encode_parity_host_multi(self, batches):
        """Grouped pipeline fast path: a list of HOST (B, k, S) uint8
        slabs -> a list of async parities, dispatching runs of
        same-shaped slabs as ONE device call (apply_matrix_host_multi)
        to amortize the per-dispatch floor."""
        return apply_matrix_host_multi(self.matrix[self.data_shards:],
                                       batches)

    def reconstruct_batch_host(self, shards, present: Sequence[int],
                               wanted: Optional[Sequence[int]] = None):
        """reconstruct_batch for HOST survivor arrays — rides the
        zero-relayout word-form path when eligible (apply_matrix_host).
        ``shards``: (B, len(present), S) uint8 np array."""
        rows = self._decode_rows_for(present, wanted)
        chosen = shards[:, :self.data_shards, :]
        if (isinstance(chosen, np.ndarray)
                and not chosen.flags.c_contiguous):
            chosen = np.ascontiguousarray(chosen)
        return apply_matrix_host(rows, chosen)

    def reconstruct_batch_host_multi(self, chunks,
                                     present: Sequence[int],
                                     wanted: Optional[Sequence[int]]
                                     = None):
        """Grouped reconstruct_batch_host: a list of HOST
        (B, len(present), S) uint8 chunks sharing one survivor set ->
        a list of async rebuilt shards, with runs of same-shaped chunks
        dispatched as one device call (apply_matrix_host_multi)."""
        rows = self._decode_rows_for(present, wanted)
        prepared = []
        for c in chunks:
            chosen = c[:, :self.data_shards, :]
            if (isinstance(chosen, np.ndarray)
                    and not chosen.flags.c_contiguous):
                chosen = np.ascontiguousarray(chosen)
            prepared.append(chosen)
        return apply_matrix_host_multi(rows, prepared)

    def _decode_rows_for(self, present: Sequence[int],
                         wanted: Optional[Sequence[int]]) -> np.ndarray:
        """Shared front half of the reconstruct paths: default wanted
        to every missing shard and build the decode rows."""
        present = list(present)
        if wanted is None:
            missing = set(range(self.total_shards)) - set(present)
            wanted = sorted(missing)
        if not wanted:
            raise ValueError("nothing to reconstruct")
        return self.decode_matrix_rows(present, wanted)

    def encode_batch(self, data) -> jnp.ndarray:
        """data (..., k, S) -> all shards (..., k+m, S) (data passthrough
        concatenated with computed parity)."""
        data = jnp.asarray(data, dtype=jnp.uint8)
        parity = self.encode_parity(data)
        return jnp.concatenate([data, parity], axis=-2)

    def verify_batch(self, shards) -> bool:
        shards = jnp.asarray(shards, dtype=jnp.uint8)
        parity = self.encode_parity(shards[..., :self.data_shards, :])
        return bool(jnp.array_equal(parity,
                                    shards[..., self.data_shards:, :]))

    def decode_matrix_rows(self, present: Sequence[int],
                           wanted: Sequence[int]) -> np.ndarray:
        """Host-side: coefficient rows that rebuild ``wanted`` shards from
        the shards listed in ``present`` (first k of them are used).

        Rows for wanted data shard d come from the inverted submatrix; rows
        for wanted parity shard p are parity coefficients composed with the
        decode matrix (so parity can be rebuilt directly from survivors in
        ONE device pass, without materializing the data shards first —
        unlike the reference's two-step reconstruct).
        """
        present = tuple(present)
        if len(present) < self.data_shards:
            raise TooFewShardsError(
                f"need {self.data_shards} shards, have {len(present)}")
        chosen = present[:self.data_shards]
        decode = self._decode_cache.get(chosen)
        if decode is None:
            decode = gf256.gf_matrix_invert(self.matrix[list(chosen), :])
            self._decode_cache[chosen] = decode
        rows = []
        for w in wanted:
            if w < self.data_shards:
                rows.append(decode[w])
            else:
                # parity row in terms of data = matrix[w]; in terms of the
                # chosen survivors = matrix[w] @ decode.
                rows.append(gf256.gf_matmul(self.matrix[w][None, :],
                                            decode)[0])
        return np.stack(rows, axis=0)

    def reconstruct_batch(self, shards, present: Sequence[int],
                          wanted: Optional[Sequence[int]] = None):
        """Rebuild shards on-device.

        ``shards``: (B, len(present), S) uint8 — ONLY the surviving shards,
        ordered to match ``present``. ``wanted``: which absolute shard ids
        to produce (default: every missing one). Returns (B, len(wanted), S).
        """
        rows = self._decode_rows_for(present, wanted)
        shards = jnp.asarray(shards, dtype=jnp.uint8)
        chosen = shards[..., :self.data_shards, :]
        return apply_matrix(rows, chosen)

    # -- klauspost-style in-place list API (drop-in for the oracle) -------

    def encode(self, shards: list) -> None:
        if len(shards) != self.total_shards:
            raise ShardSizeError(
                f"expected {self.total_shards} shards, got {len(shards)}")
        sizes = {len(s) for s in shards}
        if len(sizes) != 1:
            raise ShardSizeError("shards have inconsistent sizes")
        data = jnp.stack([jnp.asarray(s, dtype=jnp.uint8)
                          for s in shards[:self.data_shards]])
        parity = np.asarray(self.encode_parity(data))
        for i in range(self.parity_shards):
            shards[self.data_shards + i][:] = parity[i]

    def verify(self, shards: Sequence) -> bool:
        arr = jnp.stack([jnp.asarray(s, dtype=jnp.uint8) for s in shards])
        return self.verify_batch(arr)

    def reconstruct(self, shards: list, data_only: bool = False) -> None:
        if len(shards) != self.total_shards:
            raise ShardSizeError(
                f"expected {self.total_shards} shards, got {len(shards)}")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) == self.total_shards:
            return
        wanted = [i for i, s in enumerate(shards) if s is None
                  and (not data_only or i < self.data_shards)]
        if not wanted:
            return
        surv = jnp.stack([jnp.asarray(shards[i], dtype=jnp.uint8)
                          for i in present])
        rebuilt = np.asarray(self.reconstruct_batch(surv[None], present,
                                                    wanted))[0]
        for i, buf in zip(wanted, rebuilt):
            shards[i] = buf

    def reconstruct_data(self, shards: list) -> None:
        self.reconstruct(shards, data_only=True)

    def split(self, data) -> list:
        """klauspost ``Split``: one buffer -> k data shards (last
        zero-padded) + m zeroed parity shards, ready for encode()."""
        buf = np.frombuffer(bytes(data), dtype=np.uint8) \
            if not isinstance(data, np.ndarray) else data.astype(np.uint8)
        if buf.size == 0:
            raise ShardSizeError("cannot split empty buffer")
        per = -(-buf.size // self.data_shards)
        padded = np.zeros(per * self.data_shards, dtype=np.uint8)
        padded[:buf.size] = buf
        shards = [padded[i * per:(i + 1) * per].copy()
                  for i in range(self.data_shards)]
        shards += [np.zeros(per, dtype=np.uint8)
                   for _ in range(self.parity_shards)]
        return shards

    def join(self, shards: Sequence, size: int) -> bytes:
        """klauspost ``Join``: concatenate the k data shards, trim to
        ``size``."""
        if len(shards) < self.data_shards:
            raise TooFewShardsError("join needs all data shards")
        cat = np.concatenate([np.asarray(s, dtype=np.uint8)
                              for s in shards[:self.data_shards]])
        if cat.size < size:
            raise ShardSizeError("shards shorter than requested size")
        return cat[:size].tobytes()
