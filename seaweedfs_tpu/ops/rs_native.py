"""ctypes bridge to the native GF(2^8) codec (native/gf256_rs.cpp).

The reference links its Go code against SIMD Galois assembly
(klauspost/reedsolomon galois_amd64.s, SURVEY.md §2 L0); here the native
half is a small C++ library compiled on first use with the baked-in g++
and driven over ctypes (no pybind11 in this environment). Python threads
can fan one large apply out across column chunks because the C calls
release the GIL.

Roles: reference-class CPU baseline for bench.py, and the host-side
fast path for small interval repairs where a device round-trip costs
more than the math (read path, config 5). Dispatch ladder inside the
library: GFNI+AVX512 (one vgf2p8affineqb per 64 bytes — klauspost's
fastest amd64 path; bit convention self-calibrated at init) -> AVX2
nibble-LUT -> scalar table.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import Optional

import numpy as np

_SRC = Path(__file__).resolve().parent.parent / "native" / "gf256_rs.cpp"
_SO = _SRC.with_name("_gf256_rs.so")

_lib = None
_lib_lock = threading.Lock()
_pool: Optional[ThreadPoolExecutor] = None

#: Column chunk per worker thread when fanning out (bytes).
THREAD_CHUNK = 8 * 1024 * 1024


class NativeUnavailable(RuntimeError):
    pass


def _build() -> Path:
    if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    # Per-process temp name: concurrent builders (two servers starting on
    # a fresh checkout) each compile privately, then atomically rename —
    # last one wins, nobody ever dlopens a half-written file.
    tmp = _SO.with_suffix(f".so.tmp{os.getpid()}")
    cmd = ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", str(tmp)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        tmp.replace(_SO)
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        raise NativeUnavailable(f"g++ build failed: {detail}") from e
    finally:
        tmp.unlink(missing_ok=True)
    return _SO


def _load():
    global _lib
    with _lib_lock:
        if _lib is None:
            # This lock EXISTS to single-fly the one-time g++ build.
            # seaweedlint: disable=SW103 — intentional build-once lock
            lib = ctypes.CDLL(str(_build()))
            lib.gf256_init.restype = None
            lib.gf256_simd_level.restype = ctypes.c_int
            lib.rs_apply.restype = None
            lib.rs_apply.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
                ctypes.c_size_t]
            lib.gf256_init()
            _lib = lib
    return _lib


def available() -> bool:
    try:
        _load()
        return True
    except NativeUnavailable:
        return False


def simd_level() -> int:
    """0 = scalar, 2 = AVX2."""
    return int(_load().gf256_simd_level())


def _ptr(a: np.ndarray, offset: int = 0):
    return ctypes.cast(a.ctypes.data + offset,
                       ctypes.POINTER(ctypes.c_uint8))


def _apply_2d(lib, coefs: np.ndarray, x: np.ndarray, out: np.ndarray,
              threads: int) -> None:
    n_out, n_in = coefs.shape
    s = x.shape[-1]
    cp = _ptr(coefs)
    if threads <= 1 or s < 2 * THREAD_CHUNK:
        lib.rs_apply(cp, n_out, n_in, _ptr(x), s, _ptr(out), s, s)
        return
    global _pool
    with _lib_lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(max_workers=8)
    n_chunks = min(threads, -(-s // THREAD_CHUNK))
    bounds = [s * i // n_chunks for i in range(n_chunks + 1)]
    futs = []
    for lo, hi in zip(bounds, bounds[1:]):
        # Column windows are zero-copy: same row strides, offset base
        # pointers. ctypes calls release the GIL, so chunks run on all
        # cores in parallel.
        futs.append(_pool.submit(
            lib.rs_apply, cp, n_out, n_in,
            _ptr(x, lo), s, _ptr(out, lo), s, hi - lo))
    for f in futs:
        f.result()


def apply_gf_matrix(coefs: np.ndarray, x: np.ndarray,
                    threads: Optional[int] = None,
                    out: Optional[np.ndarray] = None) -> np.ndarray:
    """y[..., o, s] = XOR_d coefs[o, d] * x[..., d, s] on the host CPU.

    Same contract as bitslice/rs_pallas.apply_gf_matrix but pure numpy
    in/out, arbitrary S (no padding requirement). ``threads`` defaults
    to the CPU count (capped at 4): fanning chunks over more workers
    than cores only adds scheduler thrash — measured ~40% slower on a
    single-core host. ``out`` lets steady-state callers reuse a result
    buffer the way the reference writes into caller-provided shards
    (a fresh 10s-of-MB np.empty per call costs real page-fault time)."""
    if threads is None:
        threads = min(os.cpu_count() or 1, 4)
    lib = _load()
    coefs = np.ascontiguousarray(coefs, dtype=np.uint8)
    n_out, n_in = coefs.shape
    x = np.ascontiguousarray(x, dtype=np.uint8)
    if x.ndim == 2:
        want_shape = (n_out, x.shape[1])
        d_in = x.shape[0]
    elif x.ndim == 3:
        want_shape = (x.shape[0], n_out, x.shape[2])
        d_in = x.shape[1]
    else:
        raise ValueError(
            f"expected (n_in, S) or (B, n_in, S), got {x.shape}")
    if d_in != n_in:
        raise ValueError(
            f"x must have {n_in} input shards, got {x.shape}")
    if out is None:
        out = np.empty(want_shape, dtype=np.uint8)
    elif (out.shape != want_shape or out.dtype != np.uint8
          or not out.flags.c_contiguous):
        raise ValueError(
            f"out must be C-contiguous uint8 {want_shape}")
    if x.ndim == 2:
        _apply_2d(lib, coefs, x, out, threads)
    else:
        for b in range(x.shape[0]):
            _apply_2d(lib, coefs, x[b], out[b], threads)
    return out
