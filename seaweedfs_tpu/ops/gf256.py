"""GF(2^8) arithmetic and matrix algebra for Reed-Solomon coding.

This is the TPU-native rebuild of the math layer the reference delegates to
its vendored ``github.com/klauspost/reedsolomon`` dependency (``galois.go``,
``matrix.go``, ``inversion_tree.go``; see SURVEY.md §2 L0 row — the reference
mount was empty at survey time, so paths are the expected upstream layout and
line numbers are deliberately omitted).

Field: GF(2^8) with the primitive polynomial ``x^8 + x^4 + x^3 + x^2 + 1``
(0x11D) and generator 2 — the same field klauspost/reedsolomon uses, so code
matrices and therefore parity bytes match the reference byte-for-byte.

Everything here is host-side NumPy: table construction, code-matrix
construction (Vandermonde made systematic, klauspost ``buildMatrix``
semantics), and Gauss-Jordan inversion used to derive decode matrices. The
device-side codec (ops/rs_jax.py) consumes only the small uint8 matrices
produced here; per-byte GF multiplication never happens on the device — it is
bitsliced into GF(2) XOR networks instead (see ops/bitslice.py).
"""

from __future__ import annotations

import functools

import numpy as np

#: The primitive polynomial for GF(2^8), matching klauspost/reedsolomon
#: (galois.go) and therefore the reference's on-disk parity bytes.
PRIMITIVE_POLY = 0x11D

#: Field generator (alpha).
GENERATOR = 2


def _carryless_mul(a: int, b: int) -> int:
    """Polynomial multiply mod PRIMITIVE_POLY, table-free (bootstraps the
    tables). Tests keep their own independent bit-by-bit reference."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & 0x100:
            a ^= PRIMITIVE_POLY
    return r


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """Build exp/log tables for GF(2^8) over PRIMITIVE_POLY and GENERATOR.

    exp has 512 entries so products of two logs (< 510) index without a
    modulo; log[0] is unused (log of zero is undefined).
    """
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x = _carryless_mul(x, GENERATOR)
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    return exp, log


EXP_TABLE, LOG_TABLE = _build_tables()


def gf_mul(a: int, b: int) -> int:
    """Multiply two field elements."""
    if a == 0 or b == 0:
        return 0
    return int(EXP_TABLE[LOG_TABLE[a] + LOG_TABLE[b]])


def gf_div(a: int, b: int) -> int:
    """Divide a by b (b != 0)."""
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] - LOG_TABLE[b]) % 255])


def gf_inv(a: int) -> int:
    """Multiplicative inverse."""
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(EXP_TABLE[255 - LOG_TABLE[a]])


def gf_exp(a: int, n: int) -> int:
    """a ** n in the field, with klauspost ``galExp`` edge cases:
    a^0 == 1 for every a (including 0); 0^n == 0 for n > 0."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] * n) % 255])


@functools.lru_cache(maxsize=1)
def mul_table() -> np.ndarray:
    """Full 256x256 multiplication table, MUL[a, b] = a*b.

    Used by the NumPy oracle codec (ops/rs_ref.py) for vectorized
    constant-times-buffer products; never shipped to the device.
    """
    a = np.arange(256)
    la = LOG_TABLE[a][:, None]  # (256, 1)
    lb = LOG_TABLE[a][None, :]  # (1, 256)
    prod = EXP_TABLE[(la + lb) % 255].astype(np.uint8)
    prod[0, :] = 0
    prod[:, 0] = 0
    prod.setflags(write=False)
    return prod


def gf_mul_bytes(c: int, buf: np.ndarray) -> np.ndarray:
    """Multiply every byte of ``buf`` (uint8 array) by constant ``c``."""
    if c == 0:
        return np.zeros_like(buf)
    if c == 1:
        return buf.copy()
    return mul_table()[c][buf]


# ---------------------------------------------------------------------------
# Matrix algebra over GF(2^8) (klauspost matrix.go semantics)
# ---------------------------------------------------------------------------


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8); a (r,n) uint8, b (n,c) uint8."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    if a.shape[1] != b.shape[0]:
        raise ValueError(f"shape mismatch {a.shape} @ {b.shape}")
    mt = mul_table()
    # products[r, c, n] = a[r, n] * b[n, c]; XOR-reduce over n.
    products = mt[a[:, None, :], b.T[None, :, :]]
    return np.bitwise_xor.reduce(products, axis=2)


def gf_identity(n: int) -> np.ndarray:
    return np.eye(n, dtype=np.uint8)


def gf_matrix_invert(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^8).

    Raises ValueError on singular input (klauspost returns
    errSingular — callers treat it as "these shard rows cannot decode").
    """
    m = np.asarray(m, dtype=np.uint8)
    n = m.shape[0]
    if m.shape != (n, n):
        raise ValueError("matrix must be square")
    work = np.concatenate([m.copy(), gf_identity(n)], axis=1)
    mt = mul_table()
    for col in range(n):
        # Partial pivot: any row with a nonzero in this column.
        pivot = None
        for r in range(col, n):
            if work[r, col] != 0:
                pivot = r
                break
        if pivot is None:
            raise ValueError("singular matrix over GF(2^8)")
        if pivot != col:
            work[[col, pivot]] = work[[pivot, col]]
        # Scale pivot row to make the pivot 1.
        pv = int(work[col, col])
        if pv != 1:
            work[col] = mt[gf_inv(pv)][work[col]]
        # Eliminate this column from every other row.
        col_vals = work[:, col].copy()
        col_vals[col] = 0
        nz = np.nonzero(col_vals)[0]
        if nz.size:
            work[nz] ^= mt[col_vals[nz][:, None], work[col][None, :]]
    return work[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """Vandermonde matrix v[r, c] = r ** c in GF(2^8).

    Matches klauspost matrix.go ``vandermonde``: row 0 is [1, 0, 0, ...]
    because galExp(0, 0) == 1 and galExp(0, c>0) == 0.
    """
    v = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            v[r, c] = gf_exp(r, c)
    return v


@functools.lru_cache(maxsize=64)
def build_code_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """The systematic (total x data) code matrix, klauspost ``buildMatrix``.

    Take the (total x data) Vandermonde matrix, right-multiply by the
    inverse of its top (data x data) square so the top becomes identity;
    the bottom ``total - data`` rows are the parity coefficients. Any
    ``data`` rows of the result form an invertible matrix, which is what
    makes reconstruction from any k surviving shards possible.
    """
    if data_shards <= 0 or total_shards <= data_shards:
        raise ValueError("need 0 < data_shards < total_shards")
    if total_shards > 256:
        raise ValueError("GF(2^8) Reed-Solomon supports at most 256 shards")
    vm = vandermonde(total_shards, data_shards)
    top = vm[:data_shards, :data_shards]
    result = gf_matmul(vm, gf_matrix_invert(top))
    result.setflags(write=False)
    return result


def parity_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """Just the (parity x data) coefficient block of the code matrix."""
    full = build_code_matrix(data_shards, data_shards + parity_shards)
    return full[data_shards:, :]
