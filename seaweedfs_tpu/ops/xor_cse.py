"""Greedy XOR common-subexpression elimination (Paar's algorithm).

The RS encode/reconstruct bit-matrix is a dense GF(2) matrix: output
plane r = XOR of ~50% of the 8*k input planes. Evaluated row-by-row
that costs sum(len(row) - 1) XORs (~1200 for RS(10,4)). Many pairs of
input planes co-occur across rows, so factoring the most frequent pair
into a fresh virtual plane and substituting it everywhere (repeat until
no pair repeats) cuts the XOR count roughly in half — fewer vector ops
per Pallas grid step AND a smaller unrolled program for Mosaic to
compile.

Reference analog: klauspost/reedsolomon evaluates the matrix with
per-coefficient PSHUFB table lookups (galois_amd64.s) — table reuse is
its CSE; in the bitsliced domain the reusable unit is the XOR pair.
Paar, "Optimized arithmetic for Reed-Solomon encoders" (1997) is the
published greedy.
"""

from __future__ import annotations

from functools import lru_cache
from itertools import combinations


@lru_cache(maxsize=64)
def factor(rows: tuple[tuple[int, ...], ...], n_inputs: int):
    """Factor shared XOR pairs out of ``rows``.

    ``rows[r]`` lists input-plane indices (< n_inputs) to XOR into
    output r. Returns ``(steps, outs)`` where ``steps`` is a list of
    ``(new_id, a, b)`` — virtual plane ``new_id`` = plane a ^ plane b,
    ids assigned from ``n_inputs`` upward, each referring only to
    earlier ids — and ``outs[r]`` is the (possibly shorter) index list
    whose XOR equals the original row. Total XOR cost drops from
    ``sum(len(r) - 1)`` to ``len(steps) + sum(len(out) - 1)``.
    """
    work = [set(r) for r in rows]
    steps: list[tuple[int, int, int]] = []
    next_id = n_inputs
    while True:
        counts: dict[tuple[int, int], int] = {}
        for row in work:
            if len(row) < 2:
                continue
            for pair in combinations(sorted(row), 2):
                counts[pair] = counts.get(pair, 0) + 1
        if not counts:
            break
        (a, b), best = max(counts.items(), key=lambda kv: (kv[1], kv[0]))
        if best < 2:
            break
        steps.append((next_id, a, b))
        for row in work:
            if a in row and b in row:
                row.discard(a)
                row.discard(b)
                row.add(next_id)
        next_id += 1
    outs = tuple(tuple(sorted(r)) for r in work)
    return steps, outs


def xor_cost(rows) -> int:
    """XORs to evaluate rows directly (no factoring)."""
    return sum(max(0, len(r) - 1) for r in rows)


def factored_cost(rows: tuple[tuple[int, ...], ...], n_inputs: int) -> int:
    steps, outs = factor(tuple(tuple(r) for r in rows), n_inputs)
    return len(steps) + xor_cost(outs)
