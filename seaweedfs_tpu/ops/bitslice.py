"""Bitsliced GF(2^8) linear maps as GF(2) XOR networks — the TPU hot path.

The reference's hot loop is ``codeSomeShards`` in klauspost/reedsolomon
(reedsolomon.go), whose per-byte GF(2^8) multiply-accumulate runs as PSHUFB
nibble-table lookups in galois_amd64.s (SURVEY.md §2 L0 row, §3.1). Byte
gathers are catastrophically slow on TPU (~0.1 GiB/s measured at survey
time), so this module takes the other classical route — **bitslicing**:

* GF(2^8) is an 8-dimensional vector space over GF(2); multiplication by a
  constant ``c`` is GF(2)-linear, i.e. an 8x8 bit matrix ``M(c)`` with
  column ``j`` = bits of ``c * x^j``.
* A whole RS coefficient matrix (n_out x n_in bytes) therefore expands to
  one (8*n_out x 8*n_in) bit matrix, and the entire encode/reconstruct is
  output_bitplane[r] = XOR of selected input bitplanes — pure vector XOR on
  the VPU, 32 bytes of payload per u32 lane op, no MXU, no gathers.
* Bytes <-> bitplanes conversion is done 128 bytes at a time: bitcast to
  32 u32 words, then a 32x32 bit-matrix transpose in 5 masked-swap rounds
  (Hacker's Delight 7-3, vectorized over all groups). The transpose is an
  involution, so packing and unpacking share one primitive.

Everything traced here is static-shaped and jit-friendly; the XOR network
is unrolled at trace time from a host-side numpy bit matrix, so XLA sees a
straight-line fusion of shifts/ands/xors.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import gf256

#: Bytes per packing group: 32 u32 words = one 32x32 bit matrix.
GROUP_BYTES = 128

_MASKS = (0xFFFF0000, 0xFF00FF00, 0xF0F0F0F0, 0xCCCCCCCC, 0xAAAAAAAA)
_SHIFTS = (16, 8, 4, 2, 1)


def expand_gf2(coefs: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) coefficient matrix to its GF(2) bit matrix.

    coefs (R, C) uint8 -> (8R, 8C) bool with
    out[8r+i, 8c+j] = bit i of (coefs[r,c] * x^j).
    """
    coefs = np.asarray(coefs, dtype=np.uint8)
    r_n, c_n = coefs.shape
    out = np.zeros((8 * r_n, 8 * c_n), dtype=bool)
    for r in range(r_n):
        for c in range(c_n):
            v = int(coefs[r, c])
            if v == 0:
                continue
            for j in range(8):
                prod = gf256.gf_mul(v, 1 << j)
                for i in range(8):
                    if (prod >> i) & 1:
                        out[8 * r + i, 8 * c + j] = True
    return out


def transpose32(a: jnp.ndarray) -> jnp.ndarray:
    """Vectorized 32x32 bit-matrix transpose over the last axis.

    ``a`` is (..., 32) uint32, interpreted per-group as a bit matrix
    A[w, i] = bit i of word w; returns T with T[i, w] = A[w, i].
    Five rounds of masked swaps (the high-corner dual of Hacker's Delight
    7-3, which under little-endian bit numbering yields the TRUE transpose
    rather than the double-mirrored one); an involution (T(T(a)) == a).
    """
    shape = a.shape
    for mask_c, j in zip(_MASKS, _SHIFTS):
        mask = jnp.uint32(mask_c)
        aa = a.reshape(*shape[:-1], 32 // (2 * j), 2, j)
        lo = aa[..., 0, :]
        hi = aa[..., 1, :]
        t = (lo ^ (hi << j)) & mask
        lo = lo ^ t
        hi = hi ^ (t >> j)
        a = jnp.stack([lo, hi], axis=-2).reshape(shape)
    return a


def _bytes_to_words(x: jnp.ndarray) -> jnp.ndarray:
    """(..., S) uint8 -> (..., S//4) uint32, little-endian within the word."""
    b = x.reshape(*x.shape[:-1], -1, 4).astype(jnp.uint32)
    return (b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16)
            | (b[..., 3] << 24))


def _words_to_bytes(w: jnp.ndarray) -> jnp.ndarray:
    """(..., W) uint32 -> (..., 4W) uint8, inverse of _bytes_to_words."""
    parts = jnp.stack([w & 0xFF, (w >> 8) & 0xFF, (w >> 16) & 0xFF,
                       (w >> 24) & 0xFF], axis=-1)
    return parts.astype(jnp.uint8).reshape(*w.shape[:-1], -1)


def pack(x: jnp.ndarray) -> jnp.ndarray:
    """(..., S) uint8 with S % 128 == 0 -> (..., G, 32) uint32 planes.

    In the packed layout, word index i = 8*b + j within a group holds bit
    ``j`` of the group's bytes {4w + b : w in 0..31}; bit position w in the
    word addresses byte 4w+b. The XOR network only ever combines words with
    equal (b, position) across shards/bit-indices, so the scrambled byte
    order inside a word is harmless and unwinds exactly on unpack.
    """
    w = _bytes_to_words(x)
    g = w.reshape(*w.shape[:-1], -1, 32)
    return transpose32(g)


def unpack(p: jnp.ndarray) -> jnp.ndarray:
    """(..., G, 32) uint32 planes -> (..., 128*G) uint8; inverse of pack."""
    g = transpose32(p)
    w = g.reshape(*g.shape[:-2], -1)
    return _words_to_bytes(w)


def apply_bit_matrix(mbits: np.ndarray, planes: jnp.ndarray,
                     n_in: int, n_out: int) -> jnp.ndarray:
    """Apply a static (8*n_out, 8*n_in) GF(2) matrix to packed planes.

    ``planes`` is (B, n_in, G, 32) uint32 (the pack() of each input shard).
    Returns (B, n_out, G, 32) uint32. The XOR network is unrolled at trace
    time; each output word XORs together the input words its matrix row
    selects. Word index i = 8*b + j splits into (byte-sub-position b,
    bit-of-byte j); the network maps bit j of shard d to bit i of output
    o independently of b, so b rides along as a vector axis.
    """
    assert mbits.shape == (8 * n_out, 8 * n_in), mbits.shape
    # (B, n_in, G, 4, 8): last axis is bit-of-byte j, axis -2 is b.
    pin = planes.reshape(*planes.shape[:-1], 4, 8)
    ins = [pin[..., d, :, :, j] for d in range(n_in) for j in range(8)]
    zeros = None
    out_groups = []
    for o in range(n_out):
        cols = []
        for i in range(8):
            idx = np.nonzero(mbits[8 * o + i])[0]
            if idx.size == 0:
                if zeros is None:
                    zeros = jnp.zeros_like(ins[0])
                cols.append(zeros)
                continue
            acc = ins[int(idx[0])]
            for t in idx[1:]:
                acc = acc ^ ins[int(t)]
            cols.append(acc)
        # (B, G, 4, 8) -> word axis back to 32.
        grp = jnp.stack(cols, axis=-1)
        out_groups.append(grp.reshape(*grp.shape[:-2], 32))
    return jnp.stack(out_groups, axis=1)


def apply_gf_matrix(coefs: np.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """y[b, o, s] = XOR_d coefs[o, d] * x[b, d, s] over GF(2^8).

    ``coefs`` (n_out, n_in) uint8 is static (trace-time); ``x`` is
    (B, n_in, S) uint8 with S % 128 == 0. This one primitive implements
    encode (coefs = parity rows), reconstruct (coefs = inverted-submatrix
    rows), and any partial-interval repair.
    """
    n_out, n_in = coefs.shape
    if x.ndim != 3 or x.shape[1] != n_in:
        raise ValueError(f"x must be (B, {n_in}, S), got {x.shape}")
    if x.shape[-1] % GROUP_BYTES:
        raise ValueError(f"S must be a multiple of {GROUP_BYTES}")
    mbits = expand_gf2(coefs)
    planes = pack(x)
    out = apply_bit_matrix(mbits, planes, n_in, n_out)
    return unpack(out)
