"""Device and host codecs: GF(2^8) math, oracle RS, bitsliced JAX/Pallas."""
