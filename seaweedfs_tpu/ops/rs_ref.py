"""NumPy reference Reed-Solomon codec — the correctness oracle.

Mirrors the method surface of ``klauspost/reedsolomon``'s ``Encoder``
interface (reedsolomon.go; SURVEY.md §2 L0 row), which is the contract the
reference's EC layer (weed/storage/erasure_coding/ec_encoder.go,
ec_decoder.go) programs against:

    New(k, m) -> Encoder
    Encode(shards)            # fill parity from data
    Verify(shards) -> bool    # parity consistent with data?
    Reconstruct(shards)       # rebuild ALL missing shards in place
    ReconstructData(shards)   # rebuild only missing data shards
    Split(data) -> shards     # slice a buffer into k padded data shards
    Join(dst, shards, size)   # concatenate data shards, trim to size

The role klauspost plays for the reference — "correct by construction, fast
on the host" — this module plays for the TPU build: every device codec
(ops/rs_jax.py, ops/pallas_gf.py) is property-tested against this oracle.
It is deliberately simple NumPy; speed comes from the device paths.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from . import gf256


class ShardSizeError(ValueError):
    pass


class TooFewShardsError(ValueError):
    pass


class ReferenceEncoder:
    """Parametrized RS(k, m) codec over GF(2^8), klauspost semantics.

    ``k`` data shards, ``m`` parity shards, tolerates any ``m`` losses.
    The reference hardcodes k=10, m=4 (ec_encoder.go DataShardsCount /
    ParityShardsCount); BASELINE.json config 4 requires the parametrized
    form, so (k, m) are constructor arguments here.
    """

    def __init__(self, data_shards: int, parity_shards: int):
        if data_shards <= 0 or parity_shards <= 0:
            raise ValueError("data_shards and parity_shards must be positive")
        if data_shards + parity_shards > 256:
            raise ValueError("at most 256 total shards in GF(2^8)")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = gf256.build_code_matrix(data_shards, self.total_shards)

    # -- helpers ----------------------------------------------------------

    def _check_shards(self, shards: Sequence[Optional[np.ndarray]],
                      nil_ok: bool) -> int:
        if len(shards) != self.total_shards:
            raise ShardSizeError(
                f"expected {self.total_shards} shards, got {len(shards)}")
        size = -1
        for s in shards:
            if s is None:
                if not nil_ok:
                    raise ShardSizeError("unexpected missing shard")
                continue
            if size == -1:
                size = len(s)
            elif len(s) != size:
                raise ShardSizeError("shards have inconsistent sizes")
        if size <= 0:
            raise ShardSizeError("no shard data")
        return size

    def _code_some(self, coef_rows: np.ndarray,
                   inputs: Sequence[np.ndarray]) -> list[np.ndarray]:
        """outputs[r] = XOR_j coef_rows[r, j] * inputs[j] (the codeSomeShards
        loop that klauspost's galois_amd64.s accelerates on the host)."""
        outs = []
        for r in range(coef_rows.shape[0]):
            acc = np.zeros_like(inputs[0])
            for j, inp in enumerate(inputs):
                c = int(coef_rows[r, j])
                if c == 0:
                    continue
                acc ^= gf256.gf_mul_bytes(c, inp)
            outs.append(acc)
        return outs

    # -- Encoder surface --------------------------------------------------

    def encode(self, shards: list[np.ndarray]) -> None:
        """Fill shards[k:] (parity) from shards[:k] (data), in place."""
        self._check_shards(shards, nil_ok=False)
        parity = self._code_some(self.matrix[self.data_shards:],
                                 shards[:self.data_shards])
        for i, p in enumerate(parity):
            shards[self.data_shards + i][:] = p

    def encode_parity(self, data: np.ndarray) -> np.ndarray:
        """Functional form: data (k, S) uint8 -> parity (m, S) uint8."""
        data = np.asarray(data, dtype=np.uint8)
        if data.shape[0] != self.data_shards:
            raise ShardSizeError(
                f"expected {self.data_shards} data rows, got {data.shape[0]}")
        parity = self._code_some(self.matrix[self.data_shards:], list(data))
        return np.stack(parity, axis=0)

    def verify(self, shards: Sequence[np.ndarray]) -> bool:
        self._check_shards(shards, nil_ok=False)
        expect = self._code_some(self.matrix[self.data_shards:],
                                 list(shards[:self.data_shards]))
        return all(np.array_equal(e, s)
                   for e, s in zip(expect, shards[self.data_shards:]))

    def reconstruct(self, shards: list[Optional[np.ndarray]],
                    data_only: bool = False) -> None:
        """Rebuild missing (None) shards in place from any k survivors.

        klauspost ``reconstruct``: pick the first k present shards, invert
        the corresponding k rows of the code matrix, apply the inverse rows
        for missing data shards, then (unless data_only) re-encode missing
        parity from the completed data shards.
        """
        if len(shards) != self.total_shards:
            raise ShardSizeError(
                f"expected {self.total_shards} shards, got {len(shards)}")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < self.data_shards:
            # Checked before shard-size validation so total loss reports as
            # "too few" (klauspost ErrTooFewShards), not a malformed input.
            raise TooFewShardsError(
                f"need {self.data_shards} shards, have {len(present)}")
        self._check_shards(shards, nil_ok=True)
        if len(present) == self.total_shards:
            return

        sub_rows = present[:self.data_shards]
        sub_matrix = self.matrix[sub_rows, :]
        decode_matrix = gf256.gf_matrix_invert(sub_matrix)
        sub_shards = [shards[i] for i in sub_rows]

        missing_data = [i for i in range(self.data_shards)
                        if shards[i] is None]
        if missing_data:
            rows = decode_matrix[missing_data, :]
            rebuilt = self._code_some(rows, sub_shards)
            for i, buf in zip(missing_data, rebuilt):
                shards[i] = buf
        if data_only:
            return

        missing_parity = [i for i in range(self.data_shards,
                                           self.total_shards)
                          if shards[i] is None]
        if missing_parity:
            rows = self.matrix[missing_parity, :]
            rebuilt = self._code_some(rows, [shards[i] for i in
                                             range(self.data_shards)])
            for i, buf in zip(missing_parity, rebuilt):
                shards[i] = buf

    def reconstruct_data(self, shards: list[Optional[np.ndarray]]) -> None:
        self.reconstruct(shards, data_only=True)

    def split(self, data: bytes | np.ndarray) -> list[np.ndarray]:
        """Split a buffer into k+m equal shards: k data shards carrying the
        buffer (last one zero-padded) plus m zeroed parity shards, matching
        klauspost ``Split`` which returns ``total_shards`` slices ready to
        pass straight to ``encode``."""
        buf = np.frombuffer(bytes(data), dtype=np.uint8) \
            if not isinstance(data, np.ndarray) else data.astype(np.uint8)
        if buf.size == 0:
            raise ShardSizeError("cannot split empty buffer")
        per = -(-buf.size // self.data_shards)  # ceil
        padded = np.zeros(per * self.data_shards, dtype=np.uint8)
        padded[:buf.size] = buf
        shards = [padded[i * per:(i + 1) * per].copy()
                  for i in range(self.data_shards)]
        shards += [np.zeros(per, dtype=np.uint8)
                   for _ in range(self.parity_shards)]
        return shards

    def join(self, shards: Sequence[np.ndarray], size: int) -> bytes:
        """Concatenate the k data shards and trim to ``size`` bytes."""
        if len(shards) < self.data_shards:
            raise TooFewShardsError("join needs all data shards")
        cat = np.concatenate([np.asarray(s, dtype=np.uint8)
                              for s in shards[:self.data_shards]])
        if cat.size < size:
            raise ShardSizeError("shards shorter than requested size")
        return cat[:size].tobytes()
