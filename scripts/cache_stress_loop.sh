#!/bin/bash
# Hunt flakes in the chunk-cache concurrency tests: 10x the full cache
# suite (thread stress, disk-tier crash reload, invalidation hooks).
# Any non-pass line lands in artifacts/cache_stress.log with a
# timestamp; a clean hunt ends with "done all-passed".
cd /root/repo || exit 1
mkdir -p artifacts
fails=0
for i in $(seq 1 10); do
  out=$(JAX_PLATFORMS=cpu timeout 300 python -m pytest \
        tests/test_chunk_cache.py tests/test_cache_invalidation.py \
        -q -p no:cacheprovider 2>&1 | tail -3)
  line=$(echo "$out" | grep -E "FAILED|ERROR|passed|failed" | tail -2)
  echo "$(date +%s) run$i: $line" >> artifacts/cache_stress.log
  if echo "$out" | grep -qE "FAILED|ERROR"; then
    fails=$((fails + 1))
  fi
done
if [ "$fails" -eq 0 ]; then
  echo "$(date +%s) done all-passed" >> artifacts/cache_stress.log
else
  echo "$(date +%s) done $fails/10 runs had failures" >> artifacts/cache_stress.log
fi
exit "$fails"
