#!/bin/bash
# Maintenance-plane smoke (docs/jobs.md): boots a real subprocess
# cluster (1 master, 2 volume servers), grows >= 4 volumes in one
# collection, submits a distributed ec.encode sweep over HTTP, then
# fails if
#   - /cluster/jobs does not show the sweep progressing to done with
#     one task per volume, or
#   - fewer than 2 distinct workers executed tasks (the sweep must
#     actually distribute), or
#   - any needle fails to read back after its volume is sealed, or
#   - the seaweed_jobs_* gauges are absent from the master's /metrics
#     or unparseable by the suite's mini Prometheus parser.
#
#   bash scripts/jobs_smoke.sh [portBase] [workdir]
set -euo pipefail
PORT=${1:-49633}
WORK=${2:-$(mktemp -d /tmp/seaweed-jobs.XXXXXX)}
cd "$(dirname "$0")/.."
export PYTHONPATH=$PWD
unset PALLAS_AXON_POOL_IPS || true
export JAX_PLATFORMS=cpu
W="python -m seaweedfs_tpu"
M=127.0.0.1:$PORT
V0=127.0.0.1:$((PORT + 100))
V1=127.0.0.1:$((PORT + 101))

say() { printf '\n== %s ==\n' "$*"; }

mkdir -p "$WORK/data"
cat > "$WORK/jobs.toml" <<'EOF'
[jobs]
enabled = true
lease_seconds = 10.0
poll_seconds = 0.2
EOF
$W cluster -dir "$WORK/data" -volumes 2 -portBase "$PORT" \
  -pulseSeconds 1 -config "$WORK/jobs.toml" > "$WORK/cluster.log" 2>&1 &
CPID=$!
trap 'kill $CPID 2>/dev/null; sleep 1;
      pkill -f "seaweedfs_tpu (master|volume) -port (${PORT}|$((PORT + 100))|$((PORT + 101)))" 2>/dev/null || true' EXIT
for _ in $(seq 1 120); do
  curl -sf "http://$M/dir/assign" >/dev/null 2>&1 &&
    curl -sf "http://$V0/debug/vars" -o /dev/null 2>&1 &&
    curl -sf "http://$V1/debug/vars" -o /dev/null 2>&1 && break
  sleep 0.5
done

say "grow 4 volumes in collection=sweep and spread data over them"
curl -sf -X POST "http://$M/vol/grow?collection=sweep&count=4" \
  -o "$WORK/grow.json"
python - "$M" "$WORK/grow.json" "$WORK/fids.txt" <<'EOF'
import json
import sys
import time

from seaweedfs_tpu.cluster import operation
from seaweedfs_tpu.cluster.wdclient import MasterClient

grown = json.load(open(sys.argv[2], encoding="utf-8"))
assert grown["count"] >= 4, grown
mc = MasterClient(sys.argv[1])
vids, fids = set(), []
deadline = time.time() + 60
while len(vids) < 4 and time.time() < deadline:
    a = operation.assign(mc, collection="sweep")
    operation.upload(a.url, a.fid, b"sweep-needle" * 256,
                     jwt=a.auth, collection="sweep")
    vids.add(int(a.fid.split(",")[0]))
    fids.append(a.fid)
mc.close()
assert len(vids) >= 4, f"data never spread over 4 volumes: {vids}"
open(sys.argv[3], "w", encoding="utf-8").write("\n".join(fids))
print(f"uploaded {len(fids)} needles across volumes {sorted(vids)}")
EOF

say "submit distributed ec_encode sweep (parallel=2) over HTTP"
curl -sf -X POST "http://$M/cluster/jobs/submit" \
  -d '{"kind": "ec_encode", "collection": "sweep", "parallel": 2,
       "submittedBy": "jobs_smoke"}' -o "$WORK/submit.json"
JOB=$(python -c "import json; print(json.load(open('$WORK/submit.json'))['job']['jobId'])")
echo "submitted job $JOB"

say "/cluster/jobs must show the sweep complete on 2 distinct workers"
OK=0
for _ in $(seq 1 240); do
  curl -sf "http://$M/cluster/jobs" -o "$WORK/jobs.json" &&
    python - "$WORK/jobs.json" "$JOB" <<'EOF' && OK=1 && break
import json
import sys

doc = json.load(open(sys.argv[1], encoding="utf-8"))
job = next(j for j in doc["jobs"] if j["jobId"] == sys.argv[2])
if job["state"] == "failed":
    sys.exit(f"FAIL: sweep failed: {job}")
if job["state"] != "done":
    sys.exit(1)  # still running -> retry
tasks = job["tasks"]
if len(tasks) < 4:
    sys.exit(f"FAIL: expected >= 4 tasks, got {len(tasks)}")
if any(t["state"] != "done" for t in tasks):
    sys.exit(f"FAIL: non-done task in done job: {tasks}")
workers = {t["worker"] for t in tasks}
if len(workers) < 2:
    sys.exit(f"FAIL: sweep never distributed: workers={workers}")
assert doc["enabled"] and "policy" in doc, doc
print(f"job {job['jobId']}: {len(tasks)} tasks done across "
      f"{len(workers)} workers {sorted(workers)}")
EOF
  sleep 0.5
done
[ "$OK" = 1 ] || { echo "FAIL: sweep never completed"
                   cat "$WORK/jobs.json" 2>/dev/null; exit 1; }

say "every needle must still read back from its sealed volume"
python - "$M" "$WORK/fids.txt" <<'EOF'
import sys

from seaweedfs_tpu.cluster import operation
from seaweedfs_tpu.cluster.wdclient import MasterClient

fids = open(sys.argv[2], encoding="utf-8").read().split()
mc = MasterClient(sys.argv[1])
for fid in fids:
    got = operation.download(mc, fid, collection="sweep")
    assert got == b"sweep-needle" * 256, f"FAIL: {fid} read back wrong"
mc.close()
print(f"{len(fids)} needles read back intact after the sweep")
EOF

say "seaweed_jobs_* gauges must render on the master's /metrics"
curl -sf "http://$M/metrics" -o "$WORK/metrics.txt"
python - "$WORK/metrics.txt" <<'EOF'
import sys

sys.path.insert(0, "tests")
from conftest import parse_exposition

fams = parse_exposition(open(sys.argv[1], encoding="utf-8").read())
tasks = {tuple(sorted(lb.items())): v
         for lb, v in fams.get("seaweed_jobs_tasks", [])}
done = tasks.get((("kind", "ec_encode"), ("state", "done")))
if not done or done < 4:
    sys.exit(f"FAIL: seaweed_jobs_tasks done gauge: {tasks}")
jobs = {lb.get("state"): v for lb, v in fams.get("seaweed_jobs_jobs", [])}
if jobs.get("done", 0) < 1:
    sys.exit(f"FAIL: seaweed_jobs_jobs gauge: {jobs}")
print(f"jobs gauges: {int(done)} ec_encode tasks done, "
      f"{int(jobs['done'])} job(s) done")
EOF

say "JOBS SMOKE PASSED — workdir: $WORK"
