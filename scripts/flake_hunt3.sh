#!/bin/bash
# Serial full-suite repetitions hunting the once-seen test_mount flake
# (ROUND4.md "Test-suite health"). Pauses while artifacts/tpu.lock is
# held so suite CPU load never distorts a benchmark window on this
# single-core host. Failures land in artifacts/flake3_fail_<n>.log with
# full tracebacks.
set -u
cd /root/repo || exit 1
N=${1:-20}
LOG=artifacts/flake_hunt3.log
for i in $(seq 1 "$N"); do
  while [ -f artifacts/tpu.lock ]; do sleep 60; done
  T0=$(date +%s)
  if python -m pytest tests/ -q -rf --tb=long \
       > "artifacts/flake3_run.log" 2>&1; then
    echo "$(date +%s) run $i PASS ($(( $(date +%s) - T0 ))s)" >> "$LOG"
  else
    cp artifacts/flake3_run.log "artifacts/flake3_fail_$i.log"
    echo "$(date +%s) run $i FAIL -> flake3_fail_$i.log" >> "$LOG"
  fi
done
echo "$(date +%s) done ($N runs)" >> "$LOG"
