#!/bin/bash
# Traffic-accounting smoke (docs/observability.md): boots a 1-volume
# cluster with a filer plus an authenticated S3 gateway, drives
# zipfian traffic from two tenants, then fails if
#   - /cluster/topk does not attribute the hot object to its tenant
#     (with the SpaceSaving count bound holding), or
#   - /cluster/usage does not account both tenants with per-bucket
#     rows and latency quantiles, or
#   - the seaweed_tenant_* gauges are absent from the master's
#     /metrics or unparseable by the suite's mini Prometheus parser.
#
#   bash scripts/usage_smoke.sh [portBase] [workdir]
set -euo pipefail
PORT=${1:-49333}
WORK=${2:-$(mktemp -d /tmp/seaweed-usage.XXXXXX)}
cd "$(dirname "$0")/.."
export PYTHONPATH=$PWD
unset PALLAS_AXON_POOL_IPS || true
export JAX_PLATFORMS=cpu
W="python -m seaweedfs_tpu"
M=127.0.0.1:$PORT
F=127.0.0.1:$((PORT + 200))
S=127.0.0.1:$((PORT + 300))

say() { printf '\n== %s ==\n' "$*"; }

mkdir -p "$WORK/data"
cat > "$WORK/identities.json" <<'JSON'
{"identities": [
  {"name": "alice", "credentials":
     [{"accessKey": "AK1", "secretKey": "S1"}]},
  {"name": "bob", "credentials":
     [{"accessKey": "AK2", "secretKey": "S2"}]}
]}
JSON
$W cluster -dir "$WORK/data" -volumes 1 -filer -portBase "$PORT" \
  -pulseSeconds 1 > "$WORK/cluster.log" 2>&1 &
CPID=$!
# The launcher wires -master into its own s3 spawn, but identities
# ride -config there; run the gateway directly so both are set.
$W s3 -port $((PORT + 300)) -filer "$F" -master "$M" \
  -config "$WORK/identities.json" > "$WORK/s3.log" 2>&1 &
SPID=$!
trap 'kill $SPID $CPID 2>/dev/null; sleep 1' EXIT
for _ in $(seq 1 120); do
  curl -sf "http://$M/dir/assign" >/dev/null 2>&1 &&
    curl -sf "http://$F/" -o /dev/null 2>&1 &&
    curl -s "http://$S/" -o /dev/null 2>&1 && break
  sleep 0.5
done

say "two tenants, zipfian: alice hammers one key, bob tails off"
python - "$S" <<'EOF'
import sys
import urllib.request
from seaweedfs_tpu.gateway.s3_auth import sign_request_headers

gw = sys.argv[1]

def s3(method, path, body=b"", ak="AK1", sk="S1"):
    url = f"http://{gw}{path}"
    hdrs = sign_request_headers(method, url, {}, body, ak, sk)
    req = urllib.request.Request(url, data=body or None,
                                 method=method, headers=hdrs)
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.read()

s3("PUT", "/photos")
s3("PUT", "/photos/hot.bin", b"h" * 8192)
for _ in range(25):
    assert s3("GET", "/photos/hot.bin") == b"h" * 8192
s3("PUT", "/logs", ak="AK2", sk="S2")
for i in range(5):
    s3("PUT", f"/logs/l{i}.txt", b"l" * 128, ak="AK2", sk="S2")
    s3("GET", f"/logs/l{i}.txt", ak="AK2", sk="S2")
print("traffic: alice 27 requests on photos/, bob 11 on logs/")
EOF

say "/cluster/topk must attribute the hot key to alice"
OK=0
for _ in $(seq 1 40); do
  curl -sf "http://$M/cluster/topk?n=20" -o "$WORK/topk.json" &&
    python - "$WORK/topk.json" <<'EOF' && OK=1 && break
import json, sys
doc = json.load(open(sys.argv[1], encoding="utf-8"))
top = doc.get("top", [])
if not top or top[0]["key"] != "photos/hot.bin":
    sys.exit(1)
hot = top[0]
if hot["tenant"] != "alice":
    sys.exit(f"FAIL: hot key owned by {hot['tenant']!r}, want alice")
if not hot["count"] - hot["error"] <= 26 <= hot["count"]:
    sys.exit(f"FAIL: bound broken: count={hot['count']} "
             f"error={hot['error']} true=26")
print(f"topk: photos/hot.bin count={hot['count']}±{hot['error']} "
      f"tenant=alice ({doc['sources']} sources merged)")
EOF
  sleep 0.5
done
[ "$OK" = 1 ] || { echo "FAIL: hot key never surfaced at /cluster/topk"
                   cat "$WORK/topk.json" 2>/dev/null; exit 1; }

say "/cluster/usage must account both tenants"
curl -sf "http://$M/cluster/usage" -o "$WORK/usage.json" ||
  { echo "FAIL: /cluster/usage unreachable"; exit 1; }
python - "$WORK/usage.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1], encoding="utf-8"))
tenants = doc.get("tenants", {})
for t in ("alice", "bob"):
    if t not in tenants:
        sys.exit(f"FAIL: tenant {t!r} missing: {sorted(tenants)}")
alice, bob = tenants["alice"], tenants["bob"]
if alice["requests"] <= bob["requests"]:
    sys.exit("FAIL: alice should dominate the request count")
if alice["bytes_out"] < 25 * 8192:
    sys.exit(f"FAIL: alice bytes_out={alice['bytes_out']} < 25*8192")
photos = alice["buckets"].get("photos")
if not photos or "latency" not in photos or \
        "p99" not in photos["latency"]:
    sys.exit(f"FAIL: photos bucket row lacks latency quantiles")
print(f"usage: alice {alice['requests']} req "
      f"(p99 {photos['latency']['p99'] * 1e3:.1f}ms), "
      f"bob {bob['requests']} req; totals "
      f"{doc['totals']['requests']} over "
      f"{len(doc['sources'])} sources")
EOF

say "seaweed_tenant_* gauges must render on the master's /metrics"
curl -sf "http://$M/metrics" -o "$WORK/metrics.txt"
python - "$WORK/metrics.txt" <<'EOF'
import sys
sys.path.insert(0, "tests")
from conftest import parse_exposition
fams = parse_exposition(open(sys.argv[1], encoding="utf-8").read())
for want in ("seaweed_tenant_requests_total",
             "seaweed_tenant_bytes_out_total"):
    rows = fams.get(want, [])
    tenants = {lb.get("tenant") for lb, _ in rows}
    if not {"alice", "bob"} <= tenants:
        sys.exit(f"FAIL: {want} tenants={sorted(tenants)}")
print("tenant gauges present for alice and bob, exposition parses")
EOF

say "USAGE SMOKE PASSED — workdir: $WORK"
